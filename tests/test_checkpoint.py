"""Native checkpoint save/restore (models/checkpoint.py).

Covers: roundtrip fidelity, HF→native conversion parity, sharded restore
straight into NamedSharding placements, and the worker's
native_checkpoint load path.
"""

import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import requests

from distributed_llm_inferencing_tpu.models import checkpoint
from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config


def tree_equal(a, b):
    flat_a, _ = jax.tree.flatten(a)
    flat_b, _ = jax.tree.flatten(b)
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_roundtrip(tmp_path):
    cfg = get_config("tiny-llama").replace(dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    checkpoint.save_checkpoint(str(tmp_path / "ck"), cfg, params)
    cfg2, params2 = checkpoint.load_checkpoint(str(tmp_path / "ck"))
    assert cfg2 == cfg
    tree_equal(params, params2)


def test_hf_convert_parity(tmp_path):
    torch = pytest.importorskip("torch")
    import transformers
    from distributed_llm_inferencing_tpu.models.convert import load_hf_model
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
        vocab_size=97, n_positions=64, n_embd=32, n_layer=2, n_head=4)).eval()
    hf.save_pretrained(tmp_path / "hf")
    checkpoint.convert_hf_to_native(str(tmp_path / "hf"),
                                    str(tmp_path / "native"))
    cfg_direct, params_direct = load_hf_model(str(tmp_path / "hf"))
    cfg_native, params_native = checkpoint.load_checkpoint(
        str(tmp_path / "native"))
    assert cfg_native.family == cfg_direct.family == "gpt2"
    tree_equal(params_direct, params_native)


def test_tokenizer_travels_with_native_checkpoint(tmp_path):
    """convert copies tokenizer artifacts; the worker only uses a dir as a
    tokenizer source when artifacts exist (else byte-level fallback)."""
    from distributed_llm_inferencing_tpu.utils.tokenizer import has_tokenizer
    cfg = get_config("tiny-llama").replace(dtype="float32")
    checkpoint.save_checkpoint(
        str(tmp_path / "ck"), cfg,
        init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32))
    assert not has_tokenizer(str(tmp_path / "ck"))   # weights-only dir
    (tmp_path / "ck" / "tokenizer.json").write_text("{}")
    assert has_tokenizer(str(tmp_path / "ck"))
    assert not has_tokenizer(None)


def test_sharded_restore(tmp_path):
    """Leaves restore directly into their mesh placement, and the sharded
    model computes the same logits as the host-restored one."""
    from distributed_llm_inferencing_tpu.models import transformer
    from distributed_llm_inferencing_tpu.ops.kvcache import init_cache
    from distributed_llm_inferencing_tpu.parallel.mesh import (
        MeshSpec, create_mesh)

    cfg = get_config("tiny-llama").replace(dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    checkpoint.save_checkpoint(str(tmp_path / "ck"), cfg, params)

    spec = MeshSpec(tp=2, dp=2)
    mesh = create_mesh(spec)
    cfg2, sharded = checkpoint.load_checkpoint(
        str(tmp_path / "ck"), mesh=mesh, mesh_spec=spec)
    # attention projections must actually live sharded over tp
    qw = sharded["layers"]["q"]["w"]
    assert len(qw.sharding.device_set) == 4

    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 8)), jnp.int32)
    lens = jnp.full((2,), 8, jnp.int32)

    def fwd(p):
        cache = init_cache(cfg, 2, 16, dtype=jnp.float32)
        logits, _ = transformer.prefill(p, cfg, toks, lens, cache)
        return logits

    with mesh:
        got = jax.jit(fwd)(sharded)
    want = jax.jit(fwd)(params)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_cli_convert_and_worker_load(tmp_path):
    out = str(tmp_path / "native-gpt2")
    r = subprocess.run(
        [sys.executable, "-m", "distributed_llm_inferencing_tpu", "convert",
         "--model_name", "tiny-gpt2", "--allow_random_init",
         "--dtype", "float32", "--out", out],
        capture_output=True, text=True, timeout=300,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr
    assert "saved native checkpoint" in r.stdout

    from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent
    agent = WorkerAgent()
    srv = agent.serve(host="127.0.0.1", port=0, background=True)
    port = srv.server_address[1]
    try:
        resp = requests.post(
            f"http://127.0.0.1:{port}/load_model",
            json={"model_name": "m", "native_checkpoint": out,
                  "max_seq": 64}, timeout=300)
        assert resp.status_code == 200, resp.text
        resp = requests.post(
            f"http://127.0.0.1:{port}/inference",
            json={"model_name": "m", "prompt_tokens": [1, 2, 3],
                  "max_new_tokens": 4, "sampling": {"do_sample": False}},
            timeout=300)
        assert resp.status_code == 200, resp.text
        assert len(resp.json()["tokens"]) == 4
    finally:
        agent.service.shutdown()


def test_generate_cli_loads_native_checkpoint(tmp_path, capsys):
    """`generate --checkpoint_path <native dir>` auto-detects the Orbax
    layout (params/ subdir) and serves it without torch — same surface
    the worker uses, now from the CLI."""
    import jax
    from distributed_llm_inferencing_tpu import __main__ as cli
    from distributed_llm_inferencing_tpu.models import checkpoint
    from distributed_llm_inferencing_tpu.models.params import init_params
    from distributed_llm_inferencing_tpu.models.registry import get_config

    cfg = get_config("tiny-llama").replace(dtype="float32")
    checkpoint.save_checkpoint(
        str(tmp_path / "native"), cfg,
        init_params(cfg, jax.random.PRNGKey(0)))
    cli.main(["--platform", "cpu", "generate",
              "--checkpoint_path", str(tmp_path / "native"),
              "--prompt", "ab", "--max_new_tokens", "4", "--greedy"])
    out = capsys.readouterr().out
    assert len(out.strip()) > 0


def test_roundtrip_per_layer_windows(tmp_path):
    """attn_windows survives config.json (tuple -> list -> tuple) and the
    int32 ``attn_window`` leaf restores with its dtype intact."""
    cfg = get_config("tiny-llama").replace(
        dtype="float32", sliding_window=None,
        attn_windows=(None, 3, None, 3))
    params = init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    checkpoint.save_checkpoint(str(tmp_path / "ck"), cfg, params)
    cfg2, params2 = checkpoint.load_checkpoint(str(tmp_path / "ck"))
    assert cfg2 == cfg
    assert cfg2.attn_windows == (None, 3, None, 3)
    assert params2["layers"]["attn_window"].dtype == jnp.int32
    tree_equal(params, params2)
