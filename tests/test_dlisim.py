"""Cluster-simulator suite (tools/dlisim, docs/simulator.md).

Small-scale versions of the bench gates (`bench.py --scenario
sim_scale`): the simulator drives the REAL master control plane —
`_pick_node`, the breaker state machine, the store's group-commit
claim path — on a virtual clock, so these tests assert cluster-level
behavior (deterministic decision journals, invariant-clean scheduling,
breaker recovery under fault injection, disagg planning) in
milliseconds of wall time.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest

from tools.dlisim import (DEFAULT_MODEL, DEFAULT_TOLERANCES, SimConfig,
                          WorkerModel, arrival_trace_from_events,
                          divergence_report, fit_worker_model, run_sim,
                          synthetic_arrivals)


# ---- end-to-end sim runs ----------------------------------------------

def _small(**kw):
    cfg = dict(nodes=20, requests=400, duration_s=60.0,
               arrival="bursty", seed=7)
    cfg.update(kw)
    return SimConfig(**cfg)


def test_healthy_run_completes_everything_clean():
    rep = run_sim(_small())
    assert rep.completed == 400
    assert rep.failed == 0
    assert rep.starved == 0
    assert rep.violations == []
    assert rep.journal_counts.get("request-submitted") == 400
    assert rep.pick_us_mean is not None


def test_identical_seeds_identical_journals():
    """The reproducibility bar: same seed, same config -> byte-for-byte
    identical decision journal (hash over every event the control
    plane emitted, in order, with virtual timestamps)."""
    a, b = run_sim(_small()), run_sim(_small())
    assert a.journal_hash == b.journal_hash
    assert a.journal_counts == b.journal_counts
    c = run_sim(_small(seed=8))
    assert c.journal_hash != a.journal_hash


def test_adversarial_faults_open_and_recover_breakers():
    rep = run_sim(_small(
        requests=800, arrival="adversarial", duration_s=120.0,
        fail_nodes=[(0, 20.0, 60.0), (1, 30.0, 80.0)]))
    # every request reaches a terminal state even with two nodes dark
    assert rep.completed + rep.failed == 800
    assert rep.starved == 0
    assert rep.violations == []
    assert rep.breaker["opened"] >= 1
    assert rep.breaker["half_opened"] >= 1
    assert rep.breaker["closed"] >= 1
    assert rep.journal_counts.get("breaker-open", 0) >= 1
    assert rep.journal_counts.get("request-requeued", 0) >= 1


def test_disagg_planner_runs_with_prefill_pool():
    rep = run_sim(_small(nodes=12, prefill_nodes=4,
                         disagg_min_prompt=16))
    assert rep.completed == 400
    assert rep.violations == []
    # the planner journals a verdict per eligible first attempt
    assert rep.journal_counts.get("disagg-plan", 0) > 0


def test_sim_emits_observability_artifacts():
    rep = run_sim(_small())
    # the same counter families the live master exposes
    assert any(k.startswith("requests_") for k in rep.metrics)
    assert any(k.startswith("scheduler_pick_") for k in rep.metrics)
    assert rep.ttft_ms_p50 is not None
    assert rep.goodput_req_per_s is not None
    d = rep.to_json()
    json.dumps(d)      # report is a plain JSON artifact


# ---- worker-model fitting ---------------------------------------------

def test_fit_worker_model_medians_and_provenance():
    rows = [{"prefill_ms": 10.0 * u, "prefill_uncached_tokens": u,
             "prefill_cached_tokens": 0,
             "decode_ms": 5.0 * d, "decode_tokens": d}
            for u, d in [(10, 10), (20, 20), (30, 30)]]
    m = fit_worker_model(rows)
    assert m.prefill_ms_per_token == pytest.approx(10.0)
    assert m.decode_ms_per_token == pytest.approx(5.0)
    assert m.source["prefill_ms_per_token"] == "cost-ledger(3)"
    assert m.source["decode_ms_per_token"] == "cost-ledger(3)"
    assert m.source["overhead_ms"] == "prior"   # no dt==1 rows


def test_fit_tolerates_json_strings_and_junk():
    rows = [json.dumps({"prefill_ms": 8.0, "prefill_uncached_tokens": 4,
                        "decode_ms": 12.0, "decode_tokens": 6}),
            "not json", None, 17,
            {"prefill_ms": None, "decode_tokens": "x"}]
    m = fit_worker_model(rows)
    assert m.prefill_ms_per_token == pytest.approx(2.0)
    assert m.decode_ms_per_token == pytest.approx(2.0)


def test_fit_skips_cache_hit_prefills():
    """Cache-hit prefills say nothing about compute cost — the fitter
    applies the master's own mostly-uncached filter."""
    rows = [{"prefill_ms": 1.0, "prefill_uncached_tokens": 2,
             "prefill_cached_tokens": 100}]
    m = fit_worker_model(rows)
    assert m.prefill_ms_per_token == DEFAULT_MODEL.prefill_ms_per_token
    assert m.source["prefill_ms_per_token"] == "prior"


def test_worker_model_service_decomposition():
    m = WorkerModel(prefill_ms_per_token=2.0, decode_ms_per_token=10.0,
                    overhead_ms=5.0, chars_per_token=4)
    prefill, decode, dtoks = m.service(prompt_chars=80,
                                       max_new_tokens=16)
    assert prefill == pytest.approx(5.0 + 2.0 * 20)
    assert decode == pytest.approx(10.0 * 16)
    assert dtoks == 16
    # cached tokens shrink the prefill bill
    cached, _, _ = m.service(prompt_chars=80, max_new_tokens=16,
                             cached_tokens=19)
    assert cached == pytest.approx(5.0 + 2.0 * 1)


# ---- arrival traces ---------------------------------------------------

@pytest.mark.parametrize("kind", ["uniform", "diurnal", "bursty",
                                  "adversarial"])
def test_synthetic_arrivals_shape(kind):
    a = synthetic_arrivals(kind, 500, 100.0, seed=3)
    assert len(a) == 500
    ts = [r["at"] for r in a]
    assert ts == sorted(ts)
    assert 0.0 <= ts[0] and ts[-1] <= 100.0
    assert synthetic_arrivals(kind, 500, 100.0, seed=3) == a
    assert synthetic_arrivals(kind, 500, 100.0, seed=4) != a


def test_adversarial_arrivals_have_ties_and_heavy_tails():
    a = synthetic_arrivals("adversarial", 2000, 100.0, seed=5)
    ts = [r["at"] for r in a]
    assert len(set(ts)) < len(ts)                     # exact ties
    assert max(r["prompt_chars"] for r in a) >= 512 * 8


def test_arrival_trace_from_events_round_trip():
    rows = [
        {"type": "request-submitted", "ts": 100.5,
         "data": {"model": "m", "prompt_chars": 64,
                  "max_new_tokens": 8}},
        {"type": "node-drain", "ts": 101.0, "data": {}},   # filtered
        {"type": "request-submitted", "ts": 102.0,
         "data": json.dumps({"prompt_chars": 32, "max_length": 24})},
    ]
    trace = arrival_trace_from_events(rows)
    assert [r["at"] for r in trace] == [0.0, 1.5]
    assert trace[0]["model"] == "m"
    assert trace[1]["max_new_tokens"] == 24     # max_length fallback
    assert trace[1]["model"] == "tiny-llama"    # default


# ---- calibration ------------------------------------------------------

def test_divergence_report_pass_fail_and_skip():
    real = {"goodput_req_per_s": 10.0, "ttft_ms_p50": 100.0,
            "queue_depth_mean": None}
    sim = {"goodput_req_per_s": 12.0, "ttft_ms_p50": 300.0,
           "queue_depth_mean": 0.5}
    rep = divergence_report(real, sim)
    assert rep["metrics"]["goodput_req_per_s"]["ok"] is True
    assert rep["metrics"]["ttft_ms_p50"]["ok"] is False   # 200% > 75%
    assert rep["metrics"]["queue_depth_mean"]["ok"] is None  # skipped
    assert rep["ok"] is False
    sim["ttft_ms_p50"] = 130.0
    assert divergence_report(real, sim)["ok"] is True


def test_divergence_queue_depth_absolute_slack():
    """0.2 vs 0.8 queued requests is a 3x relative error and an
    operationally identical run — the absolute slack passes it."""
    real = {"goodput_req_per_s": 1.0, "ttft_ms_p50": 1.0,
            "queue_depth_mean": 0.2}
    sim = {"goodput_req_per_s": 1.0, "ttft_ms_p50": 1.0,
           "queue_depth_mean": 0.8}
    assert divergence_report(real, sim)["ok"] is True
    sim["queue_depth_mean"] = 0.2 + DEFAULT_TOLERANCES["queue_depth_abs"] + 1
    assert divergence_report(real, sim)["ok"] is False


# ---- workload capture + journal pagination ----------------------------

def test_submit_journals_workload_and_seq_pagination():
    """Every api_submit journals a replayable request-submitted event;
    /api/events pages on seq without loss or double-serve."""
    from distributed_llm_inferencing_tpu.runtime.master import Master
    m = Master(":memory:")
    try:
        for i in range(5):
            r = m.api_submit({"model_name": "tiny-llama",
                              "prompt": "x" * (10 + i),
                              "max_new_tokens": 4})
            assert r["status"] == "success"
        page1 = m.api_events({"type": "request-submitted", "limit": 3})
        assert page1["status"] == "success"
        # newest `limit` matches, oldest-first within the page
        assert [e["data"]["prompt_chars"] for e in page1["events"]] \
            == [12, 13, 14]
        assert page1["next_seq"] == page1["events"][-1]["seq"]
        for i in (5, 6):
            m.api_submit({"model_name": "tiny-llama",
                          "prompt": "x" * (10 + i),
                          "max_new_tokens": 4})
        # the cursor chains strictly after the last served row: the
        # follow-up page carries exactly the two new events, no
        # double-serve even though all of them share a timestamp
        page2 = m.api_events({"type": "request-submitted",
                              "since_seq": str(page1["next_seq"])})
        assert [e["data"]["prompt_chars"] for e in page2["events"]] \
            == [15, 16]
        seqs = [e["seq"] for e in page1["events"] + page2["events"]]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    finally:
        m.stop()
