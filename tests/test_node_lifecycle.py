"""Node lifecycle: breaker state machine, reactivation, accounting.

Covers what the HTTP suites never did: strike accumulation to the
FAILURE_STRIKES trip point, the half-open probe edges in both
directions, reactivation of a dead-then-revived worker via the health
loop, bounded crash-loop recovery, and the master's in-flight counter
staying non-negative under concurrent failures.
"""

import threading
import time

import requests

from distributed_llm_inferencing_tpu.runtime.master import (
    FAILURE_STRIKES, MAX_ATTEMPTS, Master)
from distributed_llm_inferencing_tpu.runtime.state import Store
from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent


def _url(port, path):
    return f"http://127.0.0.1:{port}{path}"


# ---- breaker state machine (no sockets) ------------------------------

def test_strikes_accumulate_then_open_at_threshold():
    m = Master(":memory:")           # no background threads started
    nid = m.store.add_node("n1", "127.0.0.1", 1, is_active=True)
    node = m.store.get_node(nid)
    for i in range(FAILURE_STRIKES - 1):
        m._node_failure(node)
        n = m.store.get_node(nid)
        assert n["consecutive_failures"] == i + 1
        assert n["is_active"] == 1 and n["breaker_state"] == "closed"
    m._node_failure(node)
    n = m.store.get_node(nid)
    assert n["is_active"] == 0 and n["breaker_state"] == "open"
    assert n["breaker_opened_at"] is not None
    assert m.metrics.snapshot()["counters"]["breaker_opened"] == 1


def test_half_open_probe_failure_reopens_immediately():
    m = Master(":memory:")
    nid = m.store.add_node("n1", "127.0.0.1", 1, is_active=True)
    m.store.update_node(nid, breaker_state="half_open", is_active=1,
                        consecutive_failures=FAILURE_STRIKES)
    m._node_failure(m.store.get_node(nid))
    n = m.store.get_node(nid)
    assert n["breaker_state"] == "open" and n["is_active"] == 0


def test_success_closes_half_open_and_clears_strikes():
    m = Master(":memory:")
    nid = m.store.add_node("n1", "127.0.0.1", 1, is_active=True)
    m.store.update_node(nid, breaker_state="half_open", is_active=1,
                        consecutive_failures=FAILURE_STRIKES)
    m._node_success(m.store.get_node(nid))
    n = m.store.get_node(nid)
    assert n["breaker_state"] == "closed"
    assert n["consecutive_failures"] == 0 and n["is_active"] == 1
    assert m.metrics.snapshot()["counters"]["breaker_closed"] == 1


def test_pick_node_skips_open_draining_and_limits_half_open():
    m = Master(":memory:")
    a = m.store.add_node("a", "127.0.0.1", 1, is_active=True)
    b = m.store.add_node("b", "127.0.0.1", 2, is_active=True)
    # open breaker on a -> only b schedulable
    m.store.update_node(a, breaker_state="open", is_active=0)
    assert m._pick_node(None)["id"] == b
    # draining b too -> nothing schedulable
    m.store.update_node(b, draining=1)
    assert m._pick_node(None) is None
    # half-open a admits exactly one in-flight probe
    m.store.update_node(a, breaker_state="half_open", is_active=1)
    assert m._pick_node(None)["id"] == a
    m._inflight[a] = 1
    assert m._pick_node(None) is None
    # exclusion falls back to the excluded node rather than failing
    m._inflight[a] = 0
    m.store.update_node(b, draining=0)
    assert m._pick_node(None, exclude={b})["id"] == a
    assert m._pick_node(None, exclude={a, b}) is not None


def test_timeout_retry_prefers_node_holding_the_generation():
    """A timeout requeue records the node and does not exclude it; the
    retry pins back to that node (its idempotency cache / in-flight
    join has the generation) instead of re-generating on a peer."""
    m = Master(":memory:")
    a = m.store.add_node("a", "127.0.0.1", 1, is_active=True)
    b = m.store.add_node("b", "127.0.0.1", 2, is_active=True)
    rid = m.store.submit_request("x", "p", 3, {})
    assert m.store.claim_next_pending()["id"] == rid
    m.store.requeue(rid, excluded_node_id=None, delay_s=0.0, last_node_id=b)
    req = m.store.claim_next_pending()
    assert req["node_id"] == b and req["excluded_nodes"] == []
    # plain least-loaded would tie-break to node a; prefer pins b
    assert m._pick_node("x", exclude=set())["id"] == a
    assert m._pick_node("x", exclude=set(), prefer=b)["id"] == b
    # an excluded (faulted) node is never pinned
    m.store.requeue(rid, excluded_node_id=b, delay_s=0.0, last_node_id=b)
    req = m.store.get_request(rid)
    assert req["excluded_nodes"] == [b]
    assert m._pick_node("x", exclude={b}, prefer=None)["id"] == a


# ---- reactivation via the health loop --------------------------------

def test_dead_node_reactivates_via_health_probe():
    """Worker dies -> breaker opens; worker comes back on the same port
    -> health probe half-opens; real traffic closes. The reference
    deactivated forever on one strike (SURVEY.md §3.4)."""
    agent = WorkerAgent()
    srv = agent.serve("127.0.0.1", 0, background=True)
    port = srv.server_address[1]
    m = Master(":memory:", dispatcher_threads=2, health_interval=0.2,
               retry_backoff_base=0.05)
    m.start_background()
    msrv = m.service.serve("127.0.0.1", 0, background=True)
    mport = msrv.server_address[1]
    revived = None
    try:
        r = requests.post(_url(mport, "/api/nodes/add"), json={
            "name": "lazarus", "host": "127.0.0.1", "port": port}).json()
        nid = r["node_id"]
        agent.service.shutdown()          # node dies
        deadline = time.time() + 15
        while time.time() < deadline:
            n = m.store.get_node(nid)
            if n["breaker_state"] == "open":
                break
            time.sleep(0.1)
        assert n["breaker_state"] == "open" and not n["is_active"]

        revived = WorkerAgent()           # same address, new process-alike
        revived.serve("127.0.0.1", port, background=True)
        deadline = time.time() + 15
        while time.time() < deadline:
            n = m.store.get_node(nid)
            if n["breaker_state"] == "half_open":
                break
            time.sleep(0.1)
        assert n["breaker_state"] == "half_open" and n["is_active"]

        # a real request through the half-open probe closes the breaker
        rid = requests.post(_url(mport, "/api/inference/submit"), json={
            "model_name": "tiny-gpt2", "prompt": "hi", "max_new_tokens": 3,
            "sampling": {"do_sample": False, "allow_random_init": True},
        }).json()["request_id"]
        deadline = time.time() + 90
        while time.time() < deadline:
            st = requests.get(_url(
                mport, f"/api/inference/status/{rid}")).json()["request"]
            if st["status"] in ("completed", "failed"):
                break
            time.sleep(0.2)
        assert st["status"] == "completed", st
        n = m.store.get_node(nid)
        assert n["breaker_state"] == "closed"
        assert n["consecutive_failures"] == 0
    finally:
        m.stop()
        if revived is not None:
            revived.service.shutdown()
        agent.service.shutdown()


# ---- in-flight accounting under concurrent failures ------------------

def test_inflight_never_negative_under_concurrent_failures():
    m = Master(":memory:", retry_backoff_base=0.01)
    m.store.add_node("dead", "127.0.0.1", 1, is_active=True)  # refused port
    for _ in range(8):
        m.store.submit_request("x", "p", 3, {})

    def run():
        req = m.store.claim_next_pending()
        while req is not None:
            m._execute_on_node(req)
            req = m.store.claim_next_pending()

    threads = [threading.Thread(target=run) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert all(v >= 0 for v in m._inflight.values()), m._inflight


# ---- bounded crash-loop recovery (satellite) -------------------------

def test_recover_stale_counts_attempts_and_bounds_poison_requests():
    s = Store(":memory:")
    rid = s.submit_request("m", "p")
    assert s.claim_next_pending()["id"] == rid
    assert s.recover_stale_processing(max_attempts=MAX_ATTEMPTS) == 1
    r = s.get_request(rid)
    assert r["status"] == "pending" and r["attempts"] == 1
    # a poison request that kills its worker on every dispatch stops
    # being requeued once recovery has consumed the attempt budget
    while True:
        r = s.get_request(rid)
        if r["status"] == "failed":
            break
        assert r["attempts"] < MAX_ATTEMPTS
        assert s.claim_next_pending() is not None
        s.recover_stale_processing(max_attempts=MAX_ATTEMPTS)
    assert "crash recovery" in r["error"]
    assert r["attempts"] == MAX_ATTEMPTS - 1   # the final one failed, not ran


def test_requeue_records_exclusion_and_backoff():
    s = Store(":memory:")
    rid = s.submit_request("m", "p")
    s.claim_next_pending()
    s.requeue(rid, excluded_node_id=7, delay_s=5.0)
    r = s.get_request(rid)
    assert r["status"] == "pending" and r["attempts"] == 1
    assert r["excluded_nodes"] == [7]
    assert r["next_attempt_at"] > time.time() + 3
    # parked behind backoff: invisible to the dispatcher until due
    assert s.claim_next_pending() is None
    s.requeue(rid, excluded_node_id=7, delay_s=0.0)   # no duplicate entry
    r = s.get_request(rid)
    assert r["excluded_nodes"] == [7] and r["attempts"] == 2
    assert s.claim_next_pending()["id"] == rid


def test_schema_migration_adds_new_columns(tmp_path):
    """A pre-PR2 on-disk DB (no breaker/backoff columns) upgrades in
    place at open instead of crashing the master."""
    import sqlite3
    db = str(tmp_path / "old.sqlite3")
    conn = sqlite3.connect(db)
    conn.executescript("""
        CREATE TABLE nodes (
            id INTEGER PRIMARY KEY AUTOINCREMENT, name TEXT UNIQUE NOT NULL,
            host TEXT NOT NULL, port INTEGER NOT NULL,
            is_active INTEGER DEFAULT 0, consecutive_failures INTEGER
            DEFAULT 0, last_heartbeat REAL, added_at REAL,
            info TEXT DEFAULT '{}');
        CREATE TABLE requests (
            id INTEGER PRIMARY KEY AUTOINCREMENT, model_name TEXT NOT NULL,
            prompt TEXT NOT NULL, status TEXT DEFAULT 'pending',
            result TEXT, error TEXT, node_id INTEGER,
            attempts INTEGER DEFAULT 0, max_new_tokens INTEGER,
            max_length INTEGER, sampling TEXT DEFAULT '{}', created_at REAL,
            started_at REAL, completed_at REAL, execution_time REAL,
            tokens_per_s REAL);
        INSERT INTO nodes (name, host, port, is_active)
            VALUES ('old', 'h', 1, 1);
        INSERT INTO requests (model_name, prompt, status)
            VALUES ('m', 'p', 'pending');
    """)
    conn.commit()
    conn.close()
    s = Store(db)
    n = s.list_nodes()[0]
    assert n["breaker_state"] == "closed" and n["draining"] == 0
    r = s.claim_next_pending()
    assert r is not None and r["excluded_nodes"] == []
