"""Pipeline-parallel continuous batching (parallel/paged_pipeline.py).

The contract: a batcher on a pp>1 mesh serves requests with outputs
identical to the single-stage batcher — admission waves, decode chunks,
prefix reuse and per-request PRNG streams all preserved — while the
layer stack (params AND paged pool) lives sharded across stages. Run on
the 8-virtual-CPU-device mesh (conftest.py), the same harness the dryrun
uses (SURVEY.md §4).
"""

import numpy as np

from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec
from distributed_llm_inferencing_tpu.runtime.batcher import ContinuousBatcher

CFG = get_config("tiny-llama").replace(dtype="float32", attn_backend="xla")
RNG = np.random.default_rng(0)


def _run(b, reqs, steps=200):
    for _ in range(steps):
        b.step()
        if all(r.done.is_set() for r in reqs):
            break
    return [r.wait() for r in reqs]


def _submit_mixed(b):
    base = RNG.integers(0, 256, 6).tolist()
    prompts = [(base * 4)[:20],
               RNG.integers(0, 256, 9).tolist(),
               RNG.integers(0, 256, 13).tolist()]
    return [
        b.submit(prompts[0], max_new_tokens=14,
                 sampling=SamplingParams.greedy(), seed=1),
        b.submit(prompts[1], max_new_tokens=10,
                 sampling=SamplingParams(temperature=0.8, top_k=40), seed=2),
        b.submit(prompts[2], max_new_tokens=12,
                 sampling=SamplingParams.greedy(), seed=3),
    ]


def test_pp_batcher_matches_dense():
    """pp=2 batcher ≡ single-stage batcher: same tokens for greedy AND
    sampled requests (per-slot PRNG streams are data, so the pipelined
    program must reproduce them bit-for-bit)."""
    global RNG
    RNG = np.random.default_rng(0)
    dense = ContinuousBatcher(CFG, num_blocks=96, block_size=8, slots=4,
                              max_seq=64, seed=0)
    want = _run(dense, _submit_mixed(dense))

    RNG = np.random.default_rng(0)
    pp = ContinuousBatcher(CFG, num_blocks=96, block_size=8, slots=4,
                           max_seq=64, seed=0, mesh_spec=MeshSpec(pp=2))
    got = _run(pp, _submit_mixed(pp))
    assert got == want, (got, want)


def test_pp_batcher_eos_budget_and_inflight_admission():
    """Per-slot eos stops a pp-scheduled slot mid-chunk; freed slots
    admit queued requests mid-flight exactly like the dense batcher."""
    global RNG
    RNG = np.random.default_rng(7)
    prompts = [RNG.integers(0, 256, n).tolist() for n in (8, 11, 9, 7, 12)]

    def run(mesh_spec):
        b = ContinuousBatcher(CFG, num_blocks=96, block_size=8, slots=2,
                              max_seq=64, seed=0, mesh_spec=mesh_spec)
        # more requests than slots: forces queueing + in-flight admission
        reqs = [b.submit(p, max_new_tokens=6 + i,
                         sampling=SamplingParams.greedy(), seed=10 + i)
                for i, p in enumerate(prompts)]
        return _run(b, reqs)

    want = run(None)
    got = run(MeshSpec(pp=2))
    assert got == want, (got, want)

    # eos: derive it from a full run, then check truncation matches
    b = ContinuousBatcher(CFG, num_blocks=96, block_size=8, slots=2,
                          max_seq=64, seed=0, mesh_spec=MeshSpec(pp=2))
    r_full = b.submit(prompts[0], max_new_tokens=10,
                      sampling=SamplingParams.greedy(), seed=10)
    full = _run(b, [r_full])[0]
    eos = full[4]
    b2 = ContinuousBatcher(CFG, num_blocks=96, block_size=8, slots=2,
                           max_seq=64, seed=0, mesh_spec=MeshSpec(pp=2))
    r_eos = b2.submit(prompts[0], max_new_tokens=10,
                      sampling=SamplingParams.greedy(), seed=10,
                      eos_token_id=eos)
    got_eos = _run(b2, [r_eos])[0]
    if eos not in full[:4]:
        assert got_eos == full[:4], (got_eos, full)
    assert eos not in got_eos


def test_pp_batcher_prefix_reuse():
    """Radix prefix hits survive the pp pool layout: a second request
    sharing a long prompt prefix admits with a cached prefix (fewer
    fresh blocks) and still matches the dense batcher's tokens."""
    global RNG
    RNG = np.random.default_rng(3)
    head = RNG.integers(0, 256, 24).tolist()
    p1 = head + RNG.integers(0, 256, 4).tolist()
    p2 = head + RNG.integers(0, 256, 5).tolist()

    def run(mesh_spec):
        b = ContinuousBatcher(CFG, num_blocks=96, block_size=8, slots=2,
                              max_seq=64, seed=0, mesh_spec=mesh_spec)
        r1 = b.submit(p1, max_new_tokens=6,
                      sampling=SamplingParams.greedy(), seed=1)
        out1 = _run(b, [r1])[0]
        hits0 = b.pool.stats()["prefix_hits"]
        r2 = b.submit(p2, max_new_tokens=6,
                      sampling=SamplingParams.greedy(), seed=2)
        out2 = _run(b, [r2])[0]
        hit = b.pool.stats()["prefix_hits"] > hits0
        return out1, out2, hit

    w1, w2, whit = run(None)
    g1, g2, ghit = run(MeshSpec(pp=2))
    assert (g1, g2) == (w1, w2)
    assert ghit == whit


def test_pp_batcher_lockstep_replay_evolves_identical_cache():
    """The lockstep contract extends to the pp program kinds: a follower
    replaying the leader's broadcast admit/decode args (JSON round-trip)
    evolves a bit-identical pp-sharded paged pool."""
    import json
    import jax

    mk = lambda: ContinuousBatcher(  # noqa: E731
        CFG, num_blocks=64, block_size=8, slots=2, max_seq=64, seed=0,
        mesh_spec=MeshSpec(pp=2))
    leader, follower = mk(), mk()

    def hook(kind, args, run):
        follower.replay(kind, json.loads(json.dumps(args)))
        return run()

    leader.program_hook = hook
    global RNG
    RNG = np.random.default_rng(5)
    prompts = [RNG.integers(0, 256, 9).tolist(),
               RNG.integers(0, 256, 12).tolist()]
    reqs = [leader.submit(p, max_new_tokens=8,
                          sampling=SamplingParams.greedy(), seed=20 + i)
            for i, p in enumerate(prompts)]
    outs = _run(leader, reqs)
    assert all(len(o) == 8 for o in outs)
    np.testing.assert_array_equal(np.asarray(jax.device_get(leader.paged.k)),
                                  np.asarray(jax.device_get(follower.paged.k)))
    np.testing.assert_array_equal(np.asarray(jax.device_get(leader.paged.v)),
                                  np.asarray(jax.device_get(follower.paged.v)))


def test_pp_batcher_kv8_matches_dense_kv8():
    """int8 KV cache composes with pipeline parallelism: the pp batcher
    over a quantized pool reproduces the single-stage kv8 batcher's
    tokens exactly (same quantize-at-write / dequantize-at-read points,
    so the rounding is identical)."""
    kcfg = CFG.replace(kv_quant="int8")
    global RNG
    RNG = np.random.default_rng(11)
    prompts = [RNG.integers(0, 256, n).tolist() for n in (9, 14)]

    def run(mesh_spec):
        b = ContinuousBatcher(kcfg, num_blocks=96, block_size=8, slots=2,
                              max_seq=64, seed=0, mesh_spec=mesh_spec)
        reqs = [b.submit(p, max_new_tokens=8,
                         sampling=SamplingParams.greedy(), seed=30 + i)
                for i, p in enumerate(prompts)]
        return _run(b, reqs)

    want = run(None)
    got = run(MeshSpec(pp=2))
    assert got == want, (got, want)


def test_pp_batcher_rejects_unsupported_combos():
    import pytest
    with pytest.raises(ValueError, match="speculative"):
        ContinuousBatcher(CFG, num_blocks=32, block_size=8, slots=2,
                          max_seq=64, mesh_spec=MeshSpec(pp=2),
                          speculative="ngram")
    # slots round UP to a pp multiple
    b = ContinuousBatcher(CFG, num_blocks=32, block_size=8, slots=3,
                          max_seq=64, mesh_spec=MeshSpec(pp=2))
    assert b.slots == 4
