"""Pipeline-parallel continuous batching (parallel/paged_pipeline.py).

The contract: a batcher on a pp>1 mesh serves requests with outputs
identical to the single-stage batcher — admission waves, decode chunks,
prefix reuse and per-request PRNG streams all preserved — while the
layer stack (params AND paged pool) lives sharded across stages. Run on
the 8-virtual-CPU-device mesh (conftest.py), the same harness the dryrun
uses (SURVEY.md §4).
"""

import numpy as np

from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec
from distributed_llm_inferencing_tpu.runtime.batcher import ContinuousBatcher

CFG = get_config("tiny-llama").replace(dtype="float32", attn_backend="xla")
RNG = np.random.default_rng(0)


def _run(b, reqs, steps=200):
    for _ in range(steps):
        b.step()
        if all(r.done.is_set() for r in reqs):
            break
    return [r.wait() for r in reqs]


def _submit_mixed(b):
    base = RNG.integers(0, 256, 6).tolist()
    prompts = [(base * 4)[:20],
               RNG.integers(0, 256, 9).tolist(),
               RNG.integers(0, 256, 13).tolist()]
    return [
        b.submit(prompts[0], max_new_tokens=14,
                 sampling=SamplingParams.greedy(), seed=1),
        b.submit(prompts[1], max_new_tokens=10,
                 sampling=SamplingParams(temperature=0.8, top_k=40), seed=2),
        b.submit(prompts[2], max_new_tokens=12,
                 sampling=SamplingParams.greedy(), seed=3),
    ]


def test_pp_batcher_matches_dense():
    """pp=2 batcher ≡ single-stage batcher: same tokens for greedy AND
    sampled requests (per-slot PRNG streams are data, so the pipelined
    program must reproduce them bit-for-bit)."""
    global RNG
    RNG = np.random.default_rng(0)
    dense = ContinuousBatcher(CFG, num_blocks=96, block_size=8, slots=4,
                              max_seq=64, seed=0)
    want = _run(dense, _submit_mixed(dense))

    RNG = np.random.default_rng(0)
    pp = ContinuousBatcher(CFG, num_blocks=96, block_size=8, slots=4,
                           max_seq=64, seed=0, mesh_spec=MeshSpec(pp=2))
    got = _run(pp, _submit_mixed(pp))
    assert got == want, (got, want)


def test_pp_batcher_eos_budget_and_inflight_admission():
    """Per-slot eos stops a pp-scheduled slot mid-chunk; freed slots
    admit queued requests mid-flight exactly like the dense batcher."""
    global RNG
    RNG = np.random.default_rng(7)
    prompts = [RNG.integers(0, 256, n).tolist() for n in (8, 11, 9, 7, 12)]

    def run(mesh_spec):
        b = ContinuousBatcher(CFG, num_blocks=96, block_size=8, slots=2,
                              max_seq=64, seed=0, mesh_spec=mesh_spec)
        # more requests than slots: forces queueing + in-flight admission
        reqs = [b.submit(p, max_new_tokens=6 + i,
                         sampling=SamplingParams.greedy(), seed=10 + i)
                for i, p in enumerate(prompts)]
        return _run(b, reqs)

    want = run(None)
    got = run(MeshSpec(pp=2))
    assert got == want, (got, want)

    # eos: derive it from a full run, then check truncation matches
    b = ContinuousBatcher(CFG, num_blocks=96, block_size=8, slots=2,
                          max_seq=64, seed=0, mesh_spec=MeshSpec(pp=2))
    r_full = b.submit(prompts[0], max_new_tokens=10,
                      sampling=SamplingParams.greedy(), seed=10)
    full = _run(b, [r_full])[0]
    eos = full[4]
    b2 = ContinuousBatcher(CFG, num_blocks=96, block_size=8, slots=2,
                           max_seq=64, seed=0, mesh_spec=MeshSpec(pp=2))
    r_eos = b2.submit(prompts[0], max_new_tokens=10,
                      sampling=SamplingParams.greedy(), seed=10,
                      eos_token_id=eos)
    got_eos = _run(b2, [r_eos])[0]
    if eos not in full[:4]:
        assert got_eos == full[:4], (got_eos, full)
    assert eos not in got_eos


def test_pp_batcher_prefix_reuse():
    """Radix prefix hits survive the pp pool layout: a second request
    sharing a long prompt prefix admits with a cached prefix (fewer
    fresh blocks) and still matches the dense batcher's tokens."""
    global RNG
    RNG = np.random.default_rng(3)
    head = RNG.integers(0, 256, 24).tolist()
    p1 = head + RNG.integers(0, 256, 4).tolist()
    p2 = head + RNG.integers(0, 256, 5).tolist()

    def run(mesh_spec):
        b = ContinuousBatcher(CFG, num_blocks=96, block_size=8, slots=2,
                              max_seq=64, seed=0, mesh_spec=mesh_spec)
        r1 = b.submit(p1, max_new_tokens=6,
                      sampling=SamplingParams.greedy(), seed=1)
        out1 = _run(b, [r1])[0]
        hits0 = b.pool.stats()["prefix_hits"]
        r2 = b.submit(p2, max_new_tokens=6,
                      sampling=SamplingParams.greedy(), seed=2)
        out2 = _run(b, [r2])[0]
        hit = b.pool.stats()["prefix_hits"] > hits0
        return out1, out2, hit

    w1, w2, whit = run(None)
    g1, g2, ghit = run(MeshSpec(pp=2))
    assert (g1, g2) == (w1, w2)
    assert ghit == whit


def test_pp_batcher_lockstep_replay_evolves_identical_cache():
    """The lockstep contract extends to the pp program kinds: a follower
    replaying the leader's broadcast admit/decode args (JSON round-trip)
    evolves a bit-identical pp-sharded paged pool."""
    import json
    import jax

    mk = lambda: ContinuousBatcher(  # noqa: E731
        CFG, num_blocks=64, block_size=8, slots=2, max_seq=64, seed=0,
        mesh_spec=MeshSpec(pp=2))
    leader, follower = mk(), mk()

    def hook(kind, args, run):
        follower.replay(kind, json.loads(json.dumps(args)))
        return run()

    leader.program_hook = hook
    global RNG
    RNG = np.random.default_rng(5)
    prompts = [RNG.integers(0, 256, 9).tolist(),
               RNG.integers(0, 256, 12).tolist()]
    reqs = [leader.submit(p, max_new_tokens=8,
                          sampling=SamplingParams.greedy(), seed=20 + i)
            for i, p in enumerate(prompts)]
    outs = _run(leader, reqs)
    assert all(len(o) == 8 for o in outs)
    np.testing.assert_array_equal(np.asarray(jax.device_get(leader.paged.k)),
                                  np.asarray(jax.device_get(follower.paged.k)))
    np.testing.assert_array_equal(np.asarray(jax.device_get(leader.paged.v)),
                                  np.asarray(jax.device_get(follower.paged.v)))


def test_pp_batcher_kv8_matches_dense_kv8():
    """int8 KV cache composes with pipeline parallelism: the pp batcher
    over a quantized pool reproduces the single-stage kv8 batcher's
    tokens exactly (same quantize-at-write / dequantize-at-read points,
    so the rounding is identical)."""
    kcfg = CFG.replace(kv_quant="int8")
    global RNG
    RNG = np.random.default_rng(11)
    prompts = [RNG.integers(0, 256, n).tolist() for n in (9, 14)]

    def run(mesh_spec):
        b = ContinuousBatcher(kcfg, num_blocks=96, block_size=8, slots=2,
                              max_seq=64, seed=0, mesh_spec=mesh_spec)
        reqs = [b.submit(p, max_new_tokens=8,
                         sampling=SamplingParams.greedy(), seed=30 + i)
                for i, p in enumerate(prompts)]
        return _run(b, reqs)

    want = run(None)
    got = run(MeshSpec(pp=2))
    assert got == want, (got, want)


def test_pp_batcher_rejects_unsupported_combos():
    # slots round UP to a pp multiple
    b = ContinuousBatcher(CFG, num_blocks=32, block_size=8, slots=3,
                          max_seq=64, mesh_spec=MeshSpec(pp=2))
    assert b.slots == 4


def test_pp_spec_chunk_matches_single_stage():
    """paged_speculative_chunk_pp ≡ paged_speculative_chunk: identical
    (toks, keeps, eos_seen) AND an identical committed pool — verified
    by decoding a follow-up chunk from each resulting cache."""
    import jax
    import jax.numpy as jnp
    from distributed_llm_inferencing_tpu.models import transformer
    from distributed_llm_inferencing_tpu.ops.paged_kvcache import (
        init_paged_cache, PagedKVCache)
    from distributed_llm_inferencing_tpu.parallel import paged_pipeline
    from distributed_llm_inferencing_tpu.parallel.mesh import create_mesh

    cfg = CFG
    rng = np.random.default_rng(0)
    base = rng.integers(0, 256, 6).tolist()
    prompts = [(base * 4)[:20], rng.integers(0, 256, 9).tolist(),
               (base * 3)[:14], (base * 4)[:18]]
    r = len(prompts)
    bs, mb = 8, 8
    from distributed_llm_inferencing_tpu.models.params import init_params
    params = init_params(cfg, jax.random.PRNGKey(0))
    paged0 = init_paged_cache(cfg, r * mb + 1, bs)
    tables = np.zeros((r, mb), np.int32)
    toks = np.zeros((r, 24), np.int32)
    tail_len = np.asarray([len(p) - 1 for p in prompts], np.int32)
    nb = 1
    for i, p in enumerate(prompts):
        toks[i, :len(p) - 1] = p[:-1]
        tables[i] = np.arange(nb, nb + mb)
        nb += mb
    _, paged0 = transformer.paged_prefill_tail(
        params, cfg, jnp.asarray(toks), jnp.asarray(tail_len),
        jnp.asarray(tables[:, :3]), jnp.zeros((r, 1), jnp.int32),
        jnp.zeros((r,), jnp.int32), paged0)
    cur = jnp.asarray([p[-1] for p in prompts], jnp.int32)
    cl = jnp.asarray(tail_len)
    hist = np.zeros((r, 64), np.int32)
    for i, p in enumerate(prompts):
        hist[i, :len(p)] = p
    hist = jnp.asarray(hist)

    seeds = jnp.asarray([11, 22, 33, 44], jnp.int32)
    steps0 = jnp.zeros((r,), jnp.int32)
    temps = jnp.asarray([1.0, 1.0, 0.8, 1.0], jnp.float32)
    tks = jnp.asarray([0, 0, 40, 0], jnp.int32)
    tps = jnp.asarray([1.0, 1.0, 0.9, 1.0], jnp.float32)
    ds = jnp.asarray([False, False, True, False])
    budget = jnp.full((r,), 10, jnp.int32)
    eos = jnp.full((r,), -1, jnp.int32)
    args = (cur, hist, paged0, jnp.asarray(tables), cl, seeds, steps0,
            temps, tks, tps, ds, budget, eos)

    w_toks, w_keeps, w_eos, w_paged = transformer.paged_speculative_chunk(
        params, cfg, 10, 3, *args, dummy_block=0)

    mesh = create_mesh(MeshSpec(pp=2))
    # the batcher launches this inside jit (a shard_map with a manual-pp
    # subset needs the surrounding jit); mirror that here
    pp_fn = jax.jit(lambda *a: paged_pipeline.paged_speculative_chunk_pp(
        params, cfg, 10, 3, *a, dummy_block=0, mesh=mesh))
    g_toks, g_keeps, g_eos, g_paged = pp_fn(*args)

    np.testing.assert_array_equal(np.asarray(w_keeps), np.asarray(g_keeps))
    np.testing.assert_array_equal(np.asarray(w_eos), np.asarray(g_eos))
    # only kept entries are defined outputs
    for t in range(10):
        for i in range(r):
            n = int(w_keeps[t, i])
            np.testing.assert_array_equal(
                np.asarray(w_toks[t, i, :n]), np.asarray(g_toks[t, i, :n]))

    # committed pools must agree where it matters: decode a plain chunk
    # from each and compare the emitted tokens
    cl2 = cl + np.asarray(w_keeps).sum(axis=0).astype(np.int32)
    cur2 = jnp.asarray([
        int(np.asarray(w_toks[t, i, :int(w_keeps[t, i])])[-1])
        for i in range(r)
        for t in [max(tt for tt in range(10) if int(w_keeps[tt, i]) > 0)]
    ], jnp.int32)
    follow = lambda pg: transformer.paged_decode_chunk(  # noqa: E731
        params, cfg, 4, cur2, pg, jnp.asarray(tables), cl2, seeds, steps0,
        temps, tks, tps, ds, jnp.full((r,), 4, jnp.int32), eos,
        dummy_block=0)
    ft, fe, _ = follow(w_paged)
    gt, ge, _ = follow(PagedKVCache(
        k=jnp.asarray(g_paged.k), v=jnp.asarray(g_paged.v),
        k_scale=g_paged.k_scale, v_scale=g_paged.v_scale))
    np.testing.assert_array_equal(np.asarray(fe), np.asarray(ge))
    np.testing.assert_array_equal(np.asarray(ft) * np.asarray(fe),
                                  np.asarray(gt) * np.asarray(ge))


def test_pp_batcher_speculative_matches_single_stage():
    """Batcher-level: speculative serving on a pp=2 mesh ≡ the
    single-stage speculative batcher for greedy AND sampled requests,
    across multiple chunks (pool commits included)."""
    global RNG

    def run(mesh_spec):
        global RNG
        RNG = np.random.default_rng(0)
        b = ContinuousBatcher(CFG, num_blocks=96, block_size=8, slots=4,
                              max_seq=64, seed=0, mesh_spec=mesh_spec,
                              speculative="ngram", spec_gamma=3)
        return _run(b, _submit_mixed(b))

    want = run(None)
    got = run(MeshSpec(pp=2))
    assert got == want, (got, want)
