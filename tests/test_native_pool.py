"""Native block pool + radix prefix cache (native/src/block_pool.cc).

Covers what the continuous batcher relies on: alloc/free accounting,
ref-counted prefix sharing, LRU eviction of unreferenced cached blocks, and
C++ ≡ Python-fallback semantics (differential test with random ops).
"""

import random

import pytest

from distributed_llm_inferencing_tpu.native import BlockPool


@pytest.fixture(params=["native", "python"])
def pool_kind(request):
    return request.param


def make_pool(kind, num_blocks=16, block_size=4):
    p = BlockPool(num_blocks, block_size, force_python=(kind == "python"))
    if kind == "native" and not p.is_native:
        pytest.skip("g++ unavailable; native path not built")
    return p


def test_alloc_free_roundtrip(pool_kind):
    p = make_pool(pool_kind)
    assert p.free_count() == 16
    a = p.alloc(5)
    assert len(a) == 5 and len(set(a)) == 5
    assert p.free_count() == 11
    p.release(a)
    assert p.free_count() == 16


def test_alloc_exhaustion(pool_kind):
    p = make_pool(pool_kind, num_blocks=4)
    a = p.alloc(4)
    assert a is not None
    assert p.alloc(1) is None     # nothing evictable: all blocks referenced
    p.release(a[:1])
    assert p.alloc(1) is not None


def test_prefix_match_and_share(pool_kind):
    p = make_pool(pool_kind, num_blocks=16, block_size=4)
    tokens = list(range(12))          # 3 full blocks
    blocks, n = p.match_prefix(tokens)
    assert blocks == [] and n == 0
    fresh = p.alloc(3)
    p.insert_prefix(tokens, fresh, skip=0)

    # same prompt again: full hit, refcount bumped
    blocks2, n2 = p.match_prefix(tokens)
    assert blocks2 == fresh and n2 == 12
    assert p.refcount(fresh[0]) == 2

    # longer prompt sharing the first 2 blocks
    longer = tokens[:8] + [99, 98, 97, 96]
    blocks3, n3 = p.match_prefix(longer)
    assert blocks3 == fresh[:2] and n3 == 8
    tail = p.alloc(1)
    p.insert_prefix(longer, tail, skip=2)
    blocks4, n4 = p.match_prefix(longer)
    assert blocks4 == fresh[:2] + tail and n4 == 12
    p.release(blocks2 + blocks3 + blocks4 + fresh + tail)


def test_eviction_lru(pool_kind):
    p = make_pool(pool_kind, num_blocks=4, block_size=2)
    a = p.alloc(2)
    p.insert_prefix([1, 2, 3, 4], a, skip=0)
    b = p.alloc(2)
    p.insert_prefix([9, 9, 8, 8], b, skip=0)
    # both sequences released: all 4 blocks cached, refcount 0
    p.release(a)
    p.release(b)
    assert p.free_count() == 0
    # touch prefix A so B becomes LRU, then release so BOTH chains are
    # refcount-0 evictable and only recency picks the victim
    got, _ = p.match_prefix([1, 2, 3, 4])
    assert got == a
    p.release(got)
    # allocating 2 must evict B's leaf then its parent (LRU), not A's
    c = p.alloc(2)
    assert c is not None and set(c) == set(b)
    # A's chain must still be matchable
    got2, n = p.match_prefix([1, 2, 3, 4])
    assert got2 == a and n == 4
    assert p.stats()["evictions"] >= 2


def test_cached_block_not_freed_while_referenced(pool_kind):
    p = make_pool(pool_kind, num_blocks=2, block_size=2)
    a = p.alloc(2)
    p.insert_prefix([5, 6, 7, 8], a, skip=0)
    # a second sequence shares the prefix
    shared, n = p.match_prefix([5, 6, 7, 8])
    assert shared == a and n == 4
    p.release(a)            # first sequence done; second still holds refs
    assert p.alloc(1) is None   # nothing evictable
    p.release(shared)
    assert p.alloc(1) is not None


def test_insert_validation(pool_kind):
    p = make_pool(pool_kind, num_blocks=8, block_size=4)
    with pytest.raises(ValueError):
        p.insert_prefix(list(range(8)), [], skip=0)   # needs 2 blocks
    with pytest.raises(ValueError):
        p.release([-1])
    with pytest.raises(ValueError):
        p.release([8])
    # sub-block prefix: no full blocks to insert — a silent no-op
    p.insert_prefix([1, 2, 3], [], skip=0)
    assert p.free_count() == 8


def test_differential_native_vs_python():
    """Random op sequence must behave identically in C++ and Python."""
    native = BlockPool(32, 4)
    if not native.is_native:
        pytest.skip("g++ unavailable")
    py = BlockPool(32, 4, force_python=True)
    rng = random.Random(0)
    held = []   # parallel lists of (native_blocks, py_blocks)

    for step in range(300):
        op = rng.choice(["alloc", "release", "match", "insert"])
        if op == "alloc":
            n = rng.randint(1, 4)
            a, b = native.alloc(n), py.alloc(n)
            assert (a is None) == (b is None), f"step {step}"
            if a is not None:
                held.append((a, b, None))
        elif op == "release" and held:
            a, b, _ = held.pop(rng.randrange(len(held)))
            native.release(a)
            py.release(b)
        elif op == "match":
            toks = [rng.randint(0, 3) for _ in range(rng.randint(0, 16))]
            (na, nn), (pa, pn) = native.match_prefix(toks), py.match_prefix(toks)
            assert nn == pn, f"step {step}: match len {nn} != {pn}"
            if na:
                held.append((na, pa, None))
        elif op == "insert":
            toks = [rng.randint(0, 3) for _ in range(rng.randint(4, 16))]
            (ma, mn), (mb, _) = native.match_prefix(toks), py.match_prefix(toks)
            need = len(toks) // 4 - len(ma)
            fa, fb = native.alloc(need), py.alloc(need)
            assert (fa is None) == (fb is None)
            if fa is not None:
                native.insert_prefix(toks, fa, skip=len(ma))
                py.insert_prefix(toks, fb, skip=len(mb))
                held.append((ma + fa, mb + fb, None))
            else:
                native.release(ma)
                py.release(mb)
        assert native.free_count() == py.free_count(), f"step {step}"

    s_n, s_p = native.stats(), py.stats()
    assert s_n["prefix_hits"] == s_p["prefix_hits"]
    assert s_n["evictions"] == s_p["evictions"]


def test_evict_hook_reports_block_and_full_chain(pool_kind):
    """The eviction hook (the host KV-offload tier's feed) must report
    the evicted block id together with the FULL token chain root->leaf —
    the content key the arena stores the block's KV under."""
    p = make_pool(pool_kind, num_blocks=4, block_size=2)
    seen = []
    p.set_evict_hook(lambda ev: seen.extend(ev))
    a = p.alloc(2)
    p.insert_prefix([1, 2, 3, 4], a, skip=0)
    p.release(a)
    b = p.alloc(2)          # evicts nothing: 2 blocks still free? no —
    # pool is 4 blocks, chain A holds 2 cached: this alloc takes the
    # free 2, so nothing evicts yet
    assert seen == []
    c = p.alloc(1)          # now the LRU leaf of chain A must evict
    assert c is not None
    assert seen and seen[0][0] == a[1] and seen[0][1] == [1, 2, 3, 4]
    p.release(b)
    p.release(c)
    p.set_evict_hook(None)  # unregister: further evictions are silent
    # alloc(4) MUST evict the remaining cached block a[0] (only 3 blocks
    # are free) — alloc(3) would satisfy from the free list and assert
    # nothing about unregistration
    d = p.alloc(4)
    assert len(seen) == 1 and d is not None


def test_evict_hook_differential_native_vs_python():
    """Eviction events (block + chain) must be identical across the C++
    pool and its Python mirror under a random op schedule."""
    native = BlockPool(16, 2)
    if not native.is_native:
        pytest.skip("g++ unavailable")
    py = BlockPool(16, 2, force_python=True)
    ev_n, ev_p = [], []
    native.set_evict_hook(lambda ev: ev_n.extend(ev))
    py.set_evict_hook(lambda ev: ev_p.extend(ev))
    rng = random.Random(3)
    held = []
    for step in range(200):
        op = rng.choice(["cache", "alloc", "release"])
        if op == "cache":
            toks = [rng.randint(0, 2) for _ in range(rng.randint(2, 8))]
            (ma, _), (mb, _) = native.match_prefix(toks), py.match_prefix(toks)
            need = len(toks) // 2 - len(ma)
            fa, fb = native.alloc(need), py.alloc(need)
            assert (fa is None) == (fb is None)
            if fa is not None:
                native.insert_prefix(toks, fa, skip=len(ma))
                py.insert_prefix(toks, fb, skip=len(ma))
                native.release(ma + fa)
                py.release(mb + fb)
            else:
                native.release(ma)
                py.release(mb)
        elif op == "alloc":
            n = rng.randint(1, 3)
            a, b = native.alloc(n), py.alloc(n)
            assert (a is None) == (b is None)
            if a is not None:
                held.append((a, b))
        elif op == "release" and held:
            a, b = held.pop(rng.randrange(len(held)))
            native.release(a)
            py.release(b)
        # chains must match event-for-event (block ids may differ only
        # if allocation order ever diverged — it must not)
        assert [c for _, c in ev_n] == [c for _, c in ev_p], f"step {step}"
        assert [blk for blk, _ in ev_n] == [blk for blk, _ in ev_p]
