"""Worker agent in batched serving mode over localhost HTTP.

The reference worker serialized all inference behind one sync gunicorn
worker (reference: worker/Dockerfile:47). Batched mode instead runs the
continuous batcher (runtime/batcher.py) behind the same /inference API:
concurrent requests share decode steps.
"""

import json
import threading
import time

import pytest
import requests

from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent


@pytest.fixture(scope="module")
def worker():
    agent = WorkerAgent()
    srv = agent.serve(host="127.0.0.1", port=0, background=True)
    port = srv.server_address[1]
    r = requests.post(f"http://127.0.0.1:{port}/load_model", json={
        "model_name": "tiny-llama", "allow_random_init": True,
        "serving": "batched", "kv_blocks": 64, "kv_block_size": 8,
        "slots": 4, "max_seq": 128, "dtype": "float32",
    }, timeout=300)
    assert r.status_code == 200, r.text
    yield agent, port
    agent.service.shutdown()


def _url(port, path):
    return f"http://127.0.0.1:{port}{path}"


def test_health_reports_scheduler(worker):
    _, port = worker
    h = requests.get(_url(port, "/health")).json()
    [m] = h["loaded_models"]
    assert m["serving"] == "batched"
    assert m["scheduler"]["slots"] == 4


def test_concurrent_inference_shares_batch(worker):
    agent, port = worker
    results = {}

    def go(i):
        r = requests.post(_url(port, "/inference"), json={
            "model_name": "tiny-llama",
            "prompt_tokens": [3, 5, 7, 11 + i],
            "max_new_tokens": 16,
            "sampling": {"do_sample": False},
        }, timeout=300)
        results[i] = r.json()

    threads = [threading.Thread(target=go, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert len(results) == 6
    for i, r in results.items():
        assert r["status"] == "success", r
        assert len(r["tokens"]) == 16
        assert r["ttft_ms"] is not None
        # cost ledger rides every completed response: phases partition
        # the e2e span (queue+prefill+decode ≈ execution_time, which
        # adds only handler overhead around the batcher span)
        c = r["cost"]
        phase_sum_ms = c["queue_ms"] + c["prefill_ms"] + c["decode_ms"]
        assert 0 < phase_sum_ms <= r["execution_time"] * 1e3 * 1.02, r
        assert c["decode_tokens"] == 16
        assert c["weight_passes"] >= 1
        assert c["kv_blocks_peak"] >= 1
    # identical prompts -> identical greedy outputs
    r_a = requests.post(_url(port, "/inference"), json={
        "model_name": "tiny-llama", "prompt_tokens": [3, 5, 7, 11],
        "max_new_tokens": 16, "sampling": {"do_sample": False}},
        timeout=300).json()
    assert r_a["tokens"] == results[0]["tokens"]
    # the scheduler actually ran these (prefix cache saw the repeats)
    assert r_a["scheduler"]["tokens_out"] >= 7 * 16


def test_cost_ledger_cached_tokens_match_kvtier_counters(worker):
    """The cost record's cached/uncached prefill tokens use the exact
    expressions behind the cluster ``dli_prefill_{cached,uncached}_
    tokens_total`` counters, so per-request ledgers reconcile with the
    fleet metrics (the acceptance contract of the telemetry PR)."""
    agent, port = worker
    prompt = list(range(101, 121))    # 20 tokens: 2 full 8-token blocks
    before = dict(agent.metrics.snapshot()["counters"])
    costs = []
    for _ in range(2):
        r = requests.post(_url(port, "/inference"), json={
            "model_name": "tiny-llama", "prompt_tokens": prompt,
            "max_new_tokens": 4, "sampling": {"do_sample": False},
        }, timeout=300)
        assert r.status_code == 200, r.text
        costs.append(r.json()["cost"])
    after = agent.metrics.snapshot()["counters"]

    def delta(name):
        return after.get(name, 0) - before.get(name, 0)

    # the second identical prompt hit the radix cache for its two full
    # prefix blocks (the first may also hit KV left by earlier tests)
    assert costs[1]["prefill_cached_tokens"] >= 16, costs
    assert sum(c["prefill_cached_tokens"] for c in costs) == \
        delta("prefill_cached_tokens")
    assert sum(c["prefill_uncached_tokens"] for c in costs) == \
        delta("prefill_uncached_tokens")


def test_streaming_batched(worker):
    _, port = worker
    with requests.post(_url(port, "/inference_stream"), json={
        "model_name": "tiny-llama", "prompt_tokens": [2, 4, 6, 8],
        "max_new_tokens": 8, "sampling": {"do_sample": False},
    }, stream=True, timeout=300) as r:
        assert r.status_code == 200
        events = []
        for line in r.iter_lines():
            if line.startswith(b"data: "):
                events.append(json.loads(line[6:]))
    kinds = [e["event"] for e in events]
    assert kinds.count("token") == 8
    assert kinds[-1] == "done"
    streamed = [e["token"] for e in events if e["event"] == "token"]
    done = [e for e in events if e["event"] == "done"][0]
    assert done["result"]  # decoded text present


def test_stream_validation_is_http_400(worker):
    """Bad stream requests fail with a status code, not a 200+SSE error —
    same contract as /inference."""
    _, port = worker
    r = requests.post(_url(port, "/inference_stream"), json={
        "model_name": "tiny-llama", "prompt_tokens": [],
        "max_new_tokens": 4}, timeout=60)
    assert r.status_code == 400
    r = requests.post(_url(port, "/inference_stream"), json={
        "model_name": "no-such-model", "prompt_tokens": [1]}, timeout=60)
    assert r.status_code == 400


def test_profiler_endpoints(worker, tmp_path):
    _, port = worker
    d = str(tmp_path / "trace")
    r = requests.post(_url(port, "/profile/start"), json={"trace_dir": d})
    assert r.status_code == 200
    # double-start is rejected
    assert requests.post(_url(port, "/profile/start"), json={}).status_code == 409
    requests.post(_url(port, "/inference"), json={
        "model_name": "tiny-llama", "prompt_tokens": [1, 2, 3],
        "max_new_tokens": 2, "sampling": {"do_sample": False}}, timeout=300)
    r = requests.post(_url(port, "/profile/stop"), json={})
    assert r.status_code == 200
    import glob
    assert glob.glob(d + "/**/*.xplane.pb", recursive=True), \
        "trace produced no xplane"
    assert requests.post(_url(port, "/profile/stop"), json={}).status_code == 409
    m = requests.get(_url(port, "/memory_profile"))
    assert m.status_code == 200 and len(m.content) > 0


def test_unload_stops_batcher(worker):
    agent, port = worker
    # load a second batched model and unload it; its batcher thread stops
    r = requests.post(_url(port, "/load_model"), json={
        "model_name": "tiny-gpt2", "allow_random_init": True,
        "serving": "batched", "kv_blocks": 32, "kv_block_size": 8,
        "slots": 2, "max_seq": 64, "dtype": "float32"}, timeout=300)
    assert r.status_code == 200, r.text
    b = agent.models["tiny-gpt2"].batcher
    assert b._thread is not None
    r = requests.post(_url(port, "/unload_model"),
                      json={"model_name": "tiny-gpt2"}, timeout=60)
    assert r.status_code == 200
    assert b._thread is None


def test_batched_with_tp_mesh():
    """Round-2 lift: batched serving accepts a tp mesh (the old 400 is
    gone); dp/pp/sp on the batcher still 400s before any restore."""
    agent = WorkerAgent()
    srv = agent.serve(host="127.0.0.1", port=0, background=True)
    port = srv.server_address[1]
    try:
        r = requests.post(_url(port, "/load_model"), json={
            "model_name": "tiny-llama", "allow_random_init": True,
            "serving": "batched", "kv_blocks": 32, "kv_block_size": 8,
            "slots": 2, "max_seq": 64, "dtype": "float32",
            "mesh": {"tp": 2},
        }, timeout=300)
        assert r.status_code == 200, r.text
        h = requests.get(_url(port, "/health")).json()
        [m] = h["loaded_models"]
        assert m["scheduler"]["mesh"]["tp"] == 2
        r = requests.post(_url(port, "/inference"), json={
            "model_name": "tiny-llama", "prompt_tokens": [2, 4, 6],
            "max_new_tokens": 5, "sampling": {"do_sample": False},
        }, timeout=300)
        assert r.status_code == 200, r.text
        assert len(r.json()["tokens"]) == 5

        r = requests.post(_url(port, "/load_model"), json={
            "model_name": "tiny-gpt2", "allow_random_init": True,
            "serving": "batched", "mesh": {"dp": 2}, "dtype": "float32",
        }, timeout=60)
        assert r.status_code == 400
        assert "tp/ep" in r.json()["message"]
    finally:
        agent.service.shutdown()


def test_timeout_and_cancel_free_slots():
    """A request that exceeds its budget 408s AND releases its batcher
    slot; a tagged in-flight request can be cancelled via /cancel
    (round-2 master↔worker timeout/cancel story)."""
    agent = WorkerAgent()
    srv = agent.serve(host="127.0.0.1", port=0, background=True)
    port = srv.server_address[1]
    try:
        r = requests.post(_url(port, "/load_model"), json={
            "model_name": "tiny-llama", "allow_random_init": True,
            "serving": "batched", "kv_blocks": 64, "kv_block_size": 8,
            "slots": 2, "max_seq": 512, "dtype": "float32",
        }, timeout=300)
        assert r.status_code == 200, r.text

        # 1) worker-side budget: long generation, tiny timeout -> 408
        r = requests.post(_url(port, "/inference"), json={
            "model_name": "tiny-llama", "prompt_tokens": [1, 2, 3],
            "max_new_tokens": 120, "timeout": 0.5,
        }, timeout=60)
        assert r.status_code == 408, r.text
        deadline = time.time() + 30
        while time.time() < deadline:   # cancel lands at the next step
            st = requests.get(_url(port, "/health")).json()[
                "loaded_models"][0]["scheduler"]
            if st["active"] == 0:
                break
            time.sleep(0.2)
        assert st["active"] == 0, st

        # 2) tagged cancel: kick off a long request, cancel it mid-flight
        results = {}

        def go():
            results["r"] = requests.post(_url(port, "/inference"), json={
                "model_name": "tiny-llama", "prompt_tokens": [5, 6, 7],
                "max_new_tokens": 120, "request_tag": "req-42",
            }, timeout=120)

        t = threading.Thread(target=go)
        t.start()
        deadline = time.time() + 30
        cancelled = False
        while time.time() < deadline and not cancelled:
            c = requests.post(_url(port, "/cancel"),
                              json={"request_tag": "req-42"}, timeout=10)
            cancelled = c.status_code == 200
            time.sleep(0.1)
        assert cancelled
        t.join(timeout=60)
        r = results["r"]
        assert r.status_code == 400 and "cancel" in r.json()["message"]
        deadline = time.time() + 30
        while time.time() < deadline:
            st = requests.get(_url(port, "/health")).json()[
                "loaded_models"][0]["scheduler"]
            if st["active"] == 0:
                break
            time.sleep(0.2)
        assert st["active"] == 0, st

        # unknown tag -> 404
        c = requests.post(_url(port, "/cancel"),
                          json={"request_tag": "nope"}, timeout=10)
        assert c.status_code == 404
    finally:
        agent.service.shutdown()
