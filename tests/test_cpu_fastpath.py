"""CPU fast paths: unrolled layer loop + native int8 GEMV (ops/cpu_gemv.py).

The degraded/fallback platform must not lose to the reference's stock
HF-torch-CPU stack (reference worker/app.py:297-305). Two engine-level
mechanisms make that hold (runtime/engine.py _maybe_unroll_layers):

- per-layer weights as SEPARATE buffers driven by an unrolled Python
  loop (XLA-CPU lowers small-M dots on scan/static slices of stacked
  arrays to scalar kLoop fusions ~7x slower than the dot kernel);
- int8 leaves repacked [dout, din] and streamed by the FFI kernel
  (native/src/qgemv.cc), which keeps the decode reads int8 where
  XLA-CPU's own int8 lowering materializes the f32 dequant first.

Everything here asserts bit-identity against the portable stacked/XLA
paths — the fast paths are layout/kernel changes, never numerics changes
(qgemv reassociates the dot, so int8 comparisons go through the engine's
argmax, not raw float equality).
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inferencing_tpu.models import convert
from distributed_llm_inferencing_tpu.ops import cpu_gemv
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine


def _tiny(quant=None, embed_quant=None, unroll=None, monkeypatch=None):
    import torch
    import transformers
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
        vocab_size=96, n_positions=64, n_embd=32, n_layer=2,
        n_head=4)).eval()
    cfg, params = convert.load_hf_model(hf, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32", name="tiny-fastpath",
                      quant=quant, embed_quant=embed_quant)
    if unroll is not None:
        monkeypatch.setenv("DLI_UNROLL_LAYERS", "1" if unroll else "0")
    return InferenceEngine(cfg, params, max_seq=64)


def test_unrolled_is_default_on_cpu(monkeypatch):
    eng = _tiny()
    assert eng._layers_unrolled
    assert isinstance(eng.params["layers"], list)
    eng_off = _tiny(unroll=False, monkeypatch=monkeypatch)
    assert not eng_off._layers_unrolled


@pytest.mark.parametrize("sp", [SamplingParams.greedy(),
                                SamplingParams(temperature=0.8, top_k=20,
                                               top_p=0.9)])
def test_unrolled_equals_stacked_f32(monkeypatch, sp):
    prompt = [3, 17, 52, 9, 1]
    fast = _tiny(unroll=True, monkeypatch=monkeypatch)
    out_fast = fast.generate([prompt], max_new_tokens=12, sampling=sp,
                             seed=5).tokens[0]
    slow = _tiny(unroll=False, monkeypatch=monkeypatch)
    out_slow = slow.generate([prompt], max_new_tokens=12, sampling=sp,
                             seed=5).tokens[0]
    assert out_fast == out_slow


def test_unrolled_int8_repack_equals_stacked_int8(monkeypatch):
    prompt = [3, 17, 52, 9]
    fast = _tiny(quant="int8", embed_quant="int8", unroll=True,
                 monkeypatch=monkeypatch)
    if cpu_gemv.available():
        # the repack actually engaged (leaves carry the kernel layout)
        leaves = fast.params["layers"][0]
        assert any(isinstance(v, dict) and "qT" in v
                   for v in leaves.values())
    g = SamplingParams.greedy()
    a = fast.generate([prompt], max_new_tokens=12, sampling=g).tokens[0]
    slow = _tiny(quant="int8", embed_quant="int8", unroll=False,
                 monkeypatch=monkeypatch)
    b = slow.generate([prompt], max_new_tokens=12, sampling=g).tokens[0]
    assert a == b


@pytest.mark.skipif(not cpu_gemv.available(),
                    reason="native qgemv not built (no g++ / ffi headers)")
def test_qgemv_matches_dequant_matmul():
    rng = np.random.default_rng(0)
    for m, k, n in ((1, 64, 96), (2, 128, 257), (4, 96, 33)):
        x = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
        wt = jnp.asarray(rng.integers(-127, 128, (n, k)), jnp.int8)
        s = jnp.asarray(rng.random(n) * 0.02 + 1e-3, jnp.float32)
        got = cpu_gemv.qgemv_i8(x, wt, s)
        want = x @ (wt.astype(jnp.float32).T * s[None, :])
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.skipif(not cpu_gemv.available(),
                    reason="native qgemv not built (no g++ / ffi headers)")
def test_qgemv_inside_jit_and_scan():
    rng = np.random.default_rng(1)
    k, n = 32, 48
    wt = jnp.asarray(rng.integers(-127, 128, (n, k)), jnp.int8)
    s = jnp.ones((n,), jnp.float32)

    @jax.jit
    def step(x):
        def body(c, _):
            y = cpu_gemv.qgemv_i8(c, wt, s)
            return y[:, :k] * 0.01, y[0, 0]
        return jax.lax.scan(body, x, length=3)

    x0 = jnp.asarray(rng.standard_normal((1, k)), jnp.float32)
    carry, ys = step(x0)
    # replay eagerly
    c = x0
    for _ in range(3):
        y = cpu_gemv.qgemv_i8(c, wt, s)
        c = y[:, :k] * 0.01
    np.testing.assert_allclose(np.asarray(carry), np.asarray(c), rtol=1e-6)


def test_ffi_unembed_single_device_process():
    """The tied-head int8 unembed takes the FFI path only in a
    single-visible-device CPU process (the degraded bench environment) —
    drive that in a subprocess without the test session's 8-device flag
    and check it against the portable path."""
    src = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp, numpy as np, torch, transformers
from distributed_llm_inferencing_tpu.models import convert
from distributed_llm_inferencing_tpu.ops import cpu_gemv
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine
assert jax.device_count() == 1
torch.manual_seed(0)
hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
    vocab_size=96, n_positions=64, n_embd=32, n_layer=2, n_head=4)).eval()
cfg, params = convert.load_hf_model(hf, dtype=jnp.float32)
cfg = cfg.replace(dtype="float32", name="t", quant="int8",
                  embed_quant="int8")
eng = InferenceEngine(cfg, params, max_seq=64)
a = eng.generate([[3, 17, 52]], max_new_tokens=10,
                 sampling=SamplingParams.greedy()).tokens[0]
import os
os.environ["DLI_UNROLL_LAYERS"] = "0"
cfg2, params2 = convert.load_hf_model(hf, dtype=jnp.float32)
cfg2 = cfg2.replace(dtype="float32", name="t", quant="int8",
                    embed_quant="int8")
eng2 = InferenceEngine(cfg2, params2, max_seq=64)
b = eng2.generate([[3, 17, 52]], max_new_tokens=10,
                  sampling=SamplingParams.greedy()).tokens[0]
assert a == b, (a, b)
print("FFI-UNEMBED-OK", cpu_gemv.available())
"""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, "-c", src], env=env,
                       capture_output=True, text=True, timeout=300,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))))
    assert r.returncode == 0, r.stderr[-2000:]
    assert "FFI-UNEMBED-OK" in r.stdout
