"""CLI entry: python -m distributed_llm_inferencing_tpu <command>.

Replaces the reference's process entrypoints — ``manage.py runserver`` /
gunicorn for the master, ``app.py`` / gunicorn for the worker, and the
``manage.py shard_model`` CLI (reference: master/Dockerfile:44,
worker/Dockerfile:47, shard_model.py:11-14).
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="distributed_llm_inferencing_tpu",
        description="TPU-native distributed LLM inference framework")
    sub = ap.add_subparsers(dest="cmd", required=True)

    w = sub.add_parser("worker", help="run a worker agent (data plane)")
    w.add_argument("--host", default="0.0.0.0")
    w.add_argument("--port", type=int, default=8100)

    m = sub.add_parser("master", help="run the master (control plane)")
    m.add_argument("--host", default="0.0.0.0")
    m.add_argument("--port", type=int, default=8000)
    m.add_argument("--db", default="master.sqlite3")

    p = sub.add_parser("plan", help="compute a placement plan "
                                    "(shard_model equivalent)")
    p.add_argument("--model_name", required=True)
    p.add_argument("--mesh", default="tp=1",
                   help="e.g. 'tp=4,dp=2' or 'pp=4'")
    p.add_argument("--max_seq", type=int, default=2048)
    p.add_argument("--batch", type=int, default=1)

    c = sub.add_parser("convert", help="HF checkpoint -> native sharded "
                                       "checkpoint (models/checkpoint.py)")
    c.add_argument("--checkpoint_path", help="local HF checkpoint dir")
    c.add_argument("--model_name", help="registry name (with "
                                        "--allow_random_init, for testing)")
    c.add_argument("--allow_random_init", action="store_true")
    c.add_argument("--out", required=True)
    c.add_argument("--dtype")

    g = sub.add_parser("generate", help="one-shot local generation")
    g.add_argument("--model_name", default="gpt2")
    g.add_argument("--checkpoint_path")
    g.add_argument("--prompt", required=True)
    g.add_argument("--max_new_tokens", type=int, default=100)
    g.add_argument("--mesh", default="")
    g.add_argument("--allow_random_init", action="store_true")
    g.add_argument("--greedy", action="store_true")

    args = ap.parse_args(argv)

    if args.cmd == "worker":
        from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent
        WorkerAgent().serve(args.host, args.port)
    elif args.cmd == "master":
        from distributed_llm_inferencing_tpu.runtime.master import Master
        Master(args.db).serve(args.host, args.port)
    elif args.cmd == "plan":
        from distributed_llm_inferencing_tpu.parallel.plan import make_plan
        mesh = dict(kv.split("=") for kv in args.mesh.split(",") if kv)
        plan = make_plan(args.model_name, mesh, max_seq=args.max_seq,
                         batch=args.batch)
        json.dump(plan, sys.stdout, indent=2)
        print()
    elif args.cmd == "convert":
        from distributed_llm_inferencing_tpu.models import checkpoint
        if args.checkpoint_path:
            cfg = checkpoint.convert_hf_to_native(
                args.checkpoint_path, args.out, dtype=args.dtype)
        elif args.allow_random_init and args.model_name:
            import jax
            from distributed_llm_inferencing_tpu.models.params import init_params
            from distributed_llm_inferencing_tpu.models.registry import get_config
            cfg = get_config(args.model_name)
            if args.dtype:
                cfg = cfg.replace(dtype=args.dtype)
            checkpoint.save_checkpoint(
                args.out, cfg, init_params(cfg, jax.random.PRNGKey(0)))
        else:
            sys.exit("need --checkpoint_path, or --model_name with "
                     "--allow_random_init")
        print(f"saved native checkpoint for {cfg.name} -> {args.out}")
    elif args.cmd == "generate":
        _generate(args)


def _generate(args):
    import jax.numpy as jnp
    from distributed_llm_inferencing_tpu.models.registry import get_config
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec
    from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine
    from distributed_llm_inferencing_tpu.utils.tokenizer import load_tokenizer

    if args.checkpoint_path:
        from distributed_llm_inferencing_tpu.models.convert import load_hf_model
        cfg, params = load_hf_model(args.checkpoint_path)
    elif args.allow_random_init:
        cfg, params = get_config(args.model_name), None
    else:
        sys.exit("need --checkpoint_path or --allow_random_init")
    mesh = MeshSpec.from_dict(
        dict(kv.split("=") for kv in args.mesh.split(",") if kv))
    eng = InferenceEngine(cfg, params, mesh_spec=mesh)
    tok = load_tokenizer(args.checkpoint_path, cfg.vocab_size)
    sp = SamplingParams.greedy() if args.greedy else SamplingParams()
    res = eng.generate([tok.encode(args.prompt)],
                       max_new_tokens=args.max_new_tokens, sampling=sp,
                       eos_token_id=tok.eos_token_id)
    print(tok.decode(res.tokens[0]))
    print(f"[prefill {res.prefill_ms:.0f}ms, "
          f"decode {res.decode_tokens_per_s:.1f} tok/s]", file=sys.stderr)


if __name__ == "__main__":
    main()
