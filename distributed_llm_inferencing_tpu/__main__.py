"""CLI entry: python -m distributed_llm_inferencing_tpu <command>.

Replaces the reference's process entrypoints — ``manage.py runserver`` /
gunicorn for the master, ``app.py`` / gunicorn for the worker, and the
``manage.py shard_model`` CLI (reference: master/Dockerfile:44,
worker/Dockerfile:47, shard_model.py:11-14).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Mirrors ops/quant.py MODES — kept literal so jax-free subcommands
# (master, admin, --help) never import jax just to build the parser;
# tests/test_quant.py asserts the two stay in sync.
quant_modes = ("int8", "int4")


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="distributed_llm_inferencing_tpu",
        description="TPU-native distributed LLM inference framework")
    ap.add_argument("--platform", dest="global_platform", default=None,
                    help="force the jax platform for ANY subcommand "
                         "(tpu|cpu); also honored via DLI_PLATFORM. "
                         "Unset: worker/generate probe the TPU and degrade "
                         "to cpu if it is unavailable; convert runs on cpu "
                         "(host-side weight transform needs no chip)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    w = sub.add_parser("worker", help="run a worker agent (data plane)")
    w.add_argument("--host", default="0.0.0.0")
    w.add_argument("--port", type=int, default=8100)
    # Multi-host slice (runtime/multihost.py): every host joins one
    # jax.distributed job; process 0 is the lockstep leader serving the
    # public API, the rest co-execute forwarded ops in sequence order.
    w.add_argument("--coordinator", help="host:port of the jax.distributed "
                                         "coordinator (multi-host slices)")
    w.add_argument("--process_id", type=int, default=None)
    w.add_argument("--num_processes", type=int, default=None)
    w.add_argument("--followers",
                   help="leader only: comma-separated follower host:port "
                        "worker addresses (processes 1..N-1)")
    w.add_argument("--latejoin", action="store_true",
                   help="restarted host: record the distributed identity "
                        "(--num_processes/--process_id) WITHOUT joining — "
                        "the old coordinator died with the slice; the "
                        "leader's elastic recovery orders a fresh join "
                        "via /lockstep/reinit_dist")
    w.add_argument("--platform",
                   help="force the jax platform (tpu|cpu) before device "
                        "init — e.g. cpu for transport testing")

    m = sub.add_parser("master", help="run the master (control plane)")
    m.add_argument("--host", default="0.0.0.0")
    m.add_argument("--port", type=int, default=8000)
    m.add_argument("--db", default="master.sqlite3")

    p = sub.add_parser("plan", help="compute a placement plan "
                                    "(shard_model equivalent)")
    p.add_argument("--model_name", required=True)
    p.add_argument("--mesh", default=None,
                   help="e.g. 'tp=4,dp=2' or 'pp=4'; omit to let the "
                        "auto-parallelism planner search this host's "
                        "devices (docs/architecture.md)")
    p.add_argument("--max_seq", type=int, default=2048)
    p.add_argument("--batch", type=int, default=1)

    a = sub.add_parser("admin", help="operate on a running master "
                                     "(≙ reference Django admin, admin.py:4-19)")
    a.add_argument("--master", default="http://127.0.0.1:8000")
    a.add_argument("--auth_key", default=None)
    asub = a.add_subparsers(dest="admin_cmd", required=True)
    asub.add_parser("nodes", help="list nodes with live status")
    an = asub.add_parser("add-node", help="register a worker")
    an.add_argument("--name", required=True)
    an.add_argument("--node_host", required=True)
    an.add_argument("--node_port", type=int, default=8100)
    ar = asub.add_parser("remove-node", help="deregister a worker")
    ar.add_argument("--node_id", type=int, required=True)
    asub.add_parser("requests", help="recent inference requests + counts")
    asub.add_parser("plans", help="list placement plans")
    al = asub.add_parser("load-model", help="load a model on a worker")
    al.add_argument("--model_name", required=True)
    al.add_argument("--node_id", type=int)
    al.add_argument("--native_checkpoint")
    al.add_argument("--checkpoint_path")
    al.add_argument("--serving", choices=["batched"])
    al.add_argument("--allow_random_init", action="store_true")

    c = sub.add_parser("convert", help="HF checkpoint -> native sharded "
                                       "checkpoint (models/checkpoint.py)")
    c.add_argument("--checkpoint_path", help="local HF checkpoint dir")
    c.add_argument("--model_name", help="registry name (with "
                                        "--allow_random_init, for testing)")
    c.add_argument("--allow_random_init", action="store_true")
    c.add_argument("--out", required=True)
    c.add_argument("--dtype")
    c.add_argument("--quantize", choices=list(quant_modes),
                   help="store weight-only quantized weights (ops/quant.py)")
    c.add_argument("--embed_quantize", choices=["int8"], default=None,
                   help="per-row int8 token-embedding table "
                        "(halves the tied-head read and table footprint)")

    g = sub.add_parser("generate", help="one-shot local generation")
    g.add_argument("--model_name", default="gpt2")
    g.add_argument("--checkpoint_path")
    g.add_argument("--prompt", required=True)
    g.add_argument("--max_new_tokens", type=int, default=100)
    g.add_argument("--mesh", default="")
    g.add_argument("--allow_random_init", action="store_true")
    g.add_argument("--greedy", action="store_true")
    g.add_argument("--speculative", choices=["ngram"], default=None,
                   help="prompt-lookup speculative decoding "
                        "(ops/speculative.py; distribution-preserving)")
    g.add_argument("--spec_gamma", type=int, default=4)
    g.add_argument("--quantize", choices=list(quant_modes), default=None)
    g.add_argument("--embed_quantize", choices=["int8"], default=None)
    g.add_argument("--kv_quantize", choices=["int8"], default=None)

    args = ap.parse_args(argv)

    # Platform policy (utils/platform.py): explicit request wins; jax-using
    # commands otherwise probe the accelerator hang-proof and degrade to
    # cpu — a dead/held TPU chip must never hang or crash the CLI
    # (round-1 failure mode: BENCH_r01 rc=1, convert-subprocess hang).
    from distributed_llm_inferencing_tpu.utils.platform import (
        ensure_backend, force_platform)
    requested = (getattr(args, "platform", None) or args.global_platform
                 or os.environ.get("DLI_PLATFORM") or None)
    if args.cmd in ("worker", "generate"):
        info = ensure_backend(requested)
        if info["degraded"]:
            print("warning: TPU backend unavailable, running on cpu",
                  file=sys.stderr)
    elif args.cmd == "convert":
        force_platform(requested or "cpu")
    elif requested:
        force_platform(requested)

    if args.cmd == "worker":
        from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent
        if args.coordinator or args.latejoin:
            from distributed_llm_inferencing_tpu.runtime.multihost import (
                LockstepFollower, LockstepLeader, configure_multihost,
                init_multihost)
            if args.latejoin:
                if args.num_processes is None or args.process_id is None:
                    sys.exit("--latejoin needs --num_processes and "
                             "--process_id")
                configure_multihost(args.num_processes, args.process_id)
                pid, n = args.process_id, args.num_processes
            else:
                pid, n = init_multihost(args.coordinator,
                                        args.num_processes, args.process_id)
            agent = WorkerAgent()
            if pid == 0:
                followers = [f for f in (args.followers or "").split(",") if f]
                if n > 1 and len(followers) != n - 1:
                    sys.exit(f"leader needs --followers with {n - 1} "
                             "worker addresses")
                LockstepLeader(agent, followers,
                               auth_key=os.environ.get("DLI_AUTH_KEY"))
            else:
                LockstepFollower(agent)
            agent.serve(args.host, args.port)
        else:
            WorkerAgent().serve(args.host, args.port)
    elif args.cmd == "master":
        from distributed_llm_inferencing_tpu.runtime.master import Master
        Master(args.db).serve(args.host, args.port)
    elif args.cmd == "plan":
        if args.mesh:
            from distributed_llm_inferencing_tpu.parallel.plan import \
                make_plan
            mesh = dict(kv.split("=") for kv in args.mesh.split(",")
                        if kv)
            plan = make_plan(args.model_name, mesh, max_seq=args.max_seq,
                             batch=args.batch)
        else:
            # no explicit mesh: the auto-parallelism planner searches
            # this host's device inventory (one node class — the
            # fleet-wide search needs the master's measured views and
            # lives behind POST /api/plans/auto)
            import jax
            from distributed_llm_inferencing_tpu.parallel import planner
            devs = []
            for d in jax.devices():
                entry = {"kind": getattr(d, "device_kind", d.platform)}
                try:
                    ms = d.memory_stats()
                    if ms:
                        entry["memory_bytes"] = ms.get("bytes_limit")
                except Exception:
                    pass
                devs.append(entry)
            classes = planner.fit_node_classes(
                [{"id": 0, "devices": devs}])
            decision = planner.search(
                args.model_name, classes,
                max_seq=args.max_seq, batch=args.batch)
            if not decision.get("chosen"):
                print(json.dumps(decision), file=sys.stderr)
                sys.exit(1)
            plan = dict(decision["chosen"]["plan"],
                        planner={"mesh": decision["chosen"]["mesh"],
                                 "candidates": decision["candidates"],
                                 "scored": decision["scored"]})
        json.dump(plan, sys.stdout, indent=2)
        print()
    elif args.cmd == "admin":
        _admin(args)
    elif args.cmd == "convert":
        from distributed_llm_inferencing_tpu.models import checkpoint
        if args.checkpoint_path:
            cfg = checkpoint.convert_hf_to_native(
                args.checkpoint_path, args.out, dtype=args.dtype,
                quantize=args.quantize, embed_quantize=args.embed_quantize)
        elif args.allow_random_init and args.model_name:
            import jax
            from distributed_llm_inferencing_tpu.models.params import init_params
            from distributed_llm_inferencing_tpu.models.registry import get_config
            cfg = get_config(args.model_name)
            if args.dtype:
                cfg = cfg.replace(dtype=args.dtype)
            if args.quantize:
                cfg = cfg.replace(quant=args.quantize)
            if args.embed_quantize:
                cfg = cfg.replace(embed_quant=args.embed_quantize)
            checkpoint.save_checkpoint(
                args.out, cfg, init_params(cfg, jax.random.PRNGKey(0)))
        else:
            sys.exit("need --checkpoint_path, or --model_name with "
                     "--allow_random_init")
        print(f"saved native checkpoint for {cfg.name} -> {args.out}")
    elif args.cmd == "generate":
        _generate(args)


def _admin(args):
    """Thin HTTP client for the master's API — the CRUD surface the
    reference exposed only through Django admin (admin.py:4-19)."""
    import requests
    base = args.master.rstrip("/")
    headers = ({"Authorization": f"Bearer {args.auth_key}"}
               if args.auth_key else {})

    def show(resp):
        try:
            json.dump(resp.json(), sys.stdout, indent=2)
            print()
        except ValueError:
            print(resp.status_code, resp.text[:500])
        if resp.status_code != 200:
            sys.exit(1)

    if args.admin_cmd == "nodes":
        show(requests.get(f"{base}/api/nodes/status", headers=headers,
                          timeout=30))
    elif args.admin_cmd == "add-node":
        show(requests.post(f"{base}/api/nodes/add", headers=headers, json={
            "name": args.name, "host": args.node_host,
            "port": args.node_port}, timeout=30))
    elif args.admin_cmd == "remove-node":
        show(requests.post(f"{base}/api/nodes/remove/{args.node_id}",
                           headers=headers, json={}, timeout=30))
    elif args.admin_cmd == "requests":
        show(requests.get(f"{base}/api/inference/recent", headers=headers,
                          timeout=30))
    elif args.admin_cmd == "plans":
        show(requests.get(f"{base}/api/plans", headers=headers, timeout=30))
    elif args.admin_cmd == "load-model":
        body = {"model_name": args.model_name}
        for k in ("node_id", "native_checkpoint", "checkpoint_path",
                  "serving"):
            if getattr(args, k, None):
                body[k] = getattr(args, k)
        if args.allow_random_init:
            body["allow_random_init"] = True
        show(requests.post(f"{base}/api/models/load", headers=headers,
                           json=body, timeout=600))


def _generate(args):
    import jax.numpy as jnp
    from distributed_llm_inferencing_tpu.models.registry import get_config
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec
    from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine
    from distributed_llm_inferencing_tpu.utils.tokenizer import load_tokenizer

    if args.checkpoint_path and os.path.isdir(
            os.path.join(args.checkpoint_path, "params")):
        # native (Orbax) checkpoint dir, as produced by `convert` — no
        # torch/transformers on this path
        from distributed_llm_inferencing_tpu.models import checkpoint
        cfg, params = checkpoint.load_checkpoint(args.checkpoint_path)
    elif args.checkpoint_path:
        from distributed_llm_inferencing_tpu.models.convert import load_hf_model
        cfg, params = load_hf_model(args.checkpoint_path)
    elif args.allow_random_init:
        cfg, params = get_config(args.model_name), None
    else:
        sys.exit("need --checkpoint_path or --allow_random_init")
    if args.quantize:
        cfg = cfg.replace(quant=args.quantize)
    if args.embed_quantize:
        cfg = cfg.replace(embed_quant=args.embed_quantize)
    if args.kv_quantize:
        cfg = cfg.replace(kv_quant=args.kv_quantize)
    mesh = MeshSpec.from_dict(
        dict(kv.split("=") for kv in args.mesh.split(",") if kv))
    eng = InferenceEngine(cfg, params, mesh_spec=mesh)
    from distributed_llm_inferencing_tpu.utils.tokenizer import has_tokenizer
    tok = load_tokenizer(
        args.checkpoint_path if has_tokenizer(args.checkpoint_path) else None,
        cfg.vocab_size)   # weights-only dirs fall back to byte-level
    sp = SamplingParams.greedy() if args.greedy else SamplingParams()
    res = eng.generate([tok.encode(args.prompt)],
                       max_new_tokens=args.max_new_tokens, sampling=sp,
                       eos_token_id=tok.eos_token_id,
                       speculative=args.speculative,
                       spec_gamma=args.spec_gamma)
    print(tok.decode(res.tokens[0]))
    print(f"[prefill {res.prefill_ms:.0f}ms, "
          f"decode {res.decode_tokens_per_s:.1f} tok/s]", file=sys.stderr)


if __name__ == "__main__":
    main()
