"""Native sharded checkpoints (Orbax): save once, restore device-local.

The reference had no model-artifact management beyond "every worker
downloads from the HF hub into a cache dir" (reference: worker/app.py:19-20,
117-121) and the shard_model CLI's full-size weight copies
(shard_model.py:71-91). Here the persisted artifact is the converted
stacked-layer pytree (models/convert.py) plus its ModelConfig:

- ``save_checkpoint``: one Orbax pytree directory + ``config.json``.
  Convert an HF checkpoint once (CLI: ``python -m
  distributed_llm_inferencing_tpu convert``), then every later load skips
  torch entirely.
- ``load_checkpoint``: host-resident restore, or — given a mesh — a
  *sharded* restore where each device materializes only its own partition
  of every weight (Orbax restores straight into NamedSharding-placed
  arrays). That is the single-controller replacement for the reference's
  per-worker full-model downloads (SURVEY.md §5.4).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from distributed_llm_inferencing_tpu.models.config import ModelConfig

CONFIG_FILE = "config.json"
PARAMS_DIR = "params"


def save_checkpoint(path: str, cfg: ModelConfig, params) -> None:
    """Write ``path/config.json`` + ``path/params/`` (Orbax pytree)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, CONFIG_FILE), "w") as f:
        json.dump(dataclasses.asdict(cfg), f, indent=2)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, PARAMS_DIR), params, force=True)
    ckptr.wait_until_finished()


def load_config(path: str) -> ModelConfig:
    with open(os.path.join(path, CONFIG_FILE)) as f:
        return ModelConfig(**json.load(f))


def load_checkpoint(path: str, *, mesh=None, mesh_spec=None,
                    dtype: Optional[str] = None) -> Tuple[ModelConfig, object]:
    """Restore (cfg, params) from a native checkpoint.

    With ``mesh`` + ``mesh_spec`` (parallel/mesh.MeshSpec), every leaf is
    restored directly into its NamedSharding placement — no host copy of
    the full model, which is what makes 70B-class restores fit. Without a
    mesh, leaves land as ordinary host-backed device arrays.
    """
    import orbax.checkpoint as ocp
    from distributed_llm_inferencing_tpu.models.params import init_params

    path = os.path.abspath(path)
    cfg = load_config(path)
    if dtype:
        cfg = cfg.replace(dtype=dtype)
    target_dtype = jnp.dtype(cfg.dtype)

    abstract = jax.eval_shape(
        lambda: init_params(cfg, jax.random.PRNGKey(0), dtype=target_dtype))

    if mesh is not None:
        from distributed_llm_inferencing_tpu.parallel import sharding as shd
        if mesh_spec is None:
            raise ValueError("mesh_spec is required when mesh is given")
        specs = shd.param_specs(cfg, mesh_spec)
        shardings = shd.named(mesh, specs)
        abstract = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abstract, shardings)
    else:
        # explicit placement: restore must not depend on the sharding
        # recorded at save time (the save may have run on a different
        # topology, e.g. the offline convert CLI on one CPU device)
        dev = jax.sharding.SingleDeviceSharding(jax.local_devices()[0])
        abstract = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=dev),
            abstract)

    ckptr = ocp.StandardCheckpointer()
    params = ckptr.restore(os.path.join(path, PARAMS_DIR), abstract)
    return cfg, params


def convert_hf_to_native(hf_path: str, out_path: str,
                         dtype: Optional[str] = None,
                         quantize: Optional[str] = None,
                         embed_quantize: Optional[str] = None) -> ModelConfig:
    """One-shot HF → native conversion (the ``convert`` CLI verb).

    After this, serving never touches torch/transformers for weights again
    — the reference re-ran its HF load on every worker cold start
    (reference: worker/app.py:117-121). With ``quantize="int8"`` the
    checkpoint itself stores int8 matmul weights (ops/quant.py): half the
    bytes on disk and on restore.
    """
    from distributed_llm_inferencing_tpu.models.convert import load_hf_model
    cfg, params = load_hf_model(hf_path)
    if dtype:
        cfg = cfg.replace(dtype=dtype)
        params = jax.tree.map(
            lambda x: x.astype(jnp.dtype(dtype))
            if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
    if quantize:
        from distributed_llm_inferencing_tpu.ops.quant import maybe_quantize
        cfg = cfg.replace(quant=quantize)
        params = maybe_quantize(params, cfg)
    if embed_quantize:
        from distributed_llm_inferencing_tpu.ops.quant import (
            maybe_quantize_embed)
        cfg = cfg.replace(embed_quant=embed_quantize)
        params = maybe_quantize_embed(params, cfg)
    save_checkpoint(out_path, cfg, params)
    # carry the tokenizer along so the native dir is self-contained (the
    # worker falls back to byte-level tokenization without one)
    try:
        import transformers
        tok = transformers.AutoTokenizer.from_pretrained(
            hf_path, local_files_only=True)
        tok.save_pretrained(out_path)
    except Exception:
        pass   # checkpoint dirs without tokenizer artifacts stay weights-only
    return cfg
