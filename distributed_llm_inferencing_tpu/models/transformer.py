"""Unified causal-transformer forward pass (pure JAX, functional).

One implementation covers GPT-2, OPT, Llama/Mistral and Mixtral via
ModelConfig switches — where the reference dispatched on the HF module tree
(reference: shard_model.py:40-50) and ran vendored torch kernels via
``model.generate()`` (reference: worker/app.py:297-305), this is an explicit
XLA program designed for the TPU:

- **Stacked layer parameters.** Every per-layer weight carries a leading
  layer axis ``[L, ...]`` and the block stack runs under ``lax.scan``: one
  layer gets traced/compiled once regardless of depth, and the layer axis is
  what pipeline parallelism later shards (parallel/pipeline.py).
- **Static shapes everywhere.** Prefill/decode take fixed-size token blocks
  plus explicit positions/lengths; raggedness is masking, never shape.
- **KV cache as scan xs/ys.** The cache's ``[L, ...]`` buffers flow through
  the scan as per-layer slices, so updates stay fused in one program.

Param pytree schema (all leaves jnp arrays; optional leaves absent, never None):

    {"embed": {"tokens": [V,E], "positions": [P,D]?,
               # E = embed_proj_dim or D; projections present iff
               # cfg.embed_proj_dim (opt-350m):
               "project_in": {"w": [E,D]}?, "project_out": {"w": [D,E]}?},
     "layers": {
        "attn_norm": {"scale": [L,D], "bias": [L,D]?},
        "q"|"k"|"v"|"o": {"w": [L,din,dout], "b": [L,dout]?},
        "mlp_norm": {"scale": [L,D], "bias": [L,D]?},
        # dense MLP:
        "up": {"w": [L,D,I], "b"?}, "gate": {"w": [L,D,I]}?, "down": {"w": [L,I,D], "b"?},
        # MoE (cfg.num_experts > 0):
        "router": {"w": [L,D,E]},
        "experts": {"up": {"w": [L,E,D,I]}, "gate": {"w": [L,E,D,I]}, "down": {"w": [L,E,I,D]}},
     },
     "final_norm": {"scale": [D], "bias": [D]?},  # absent when cfg.post_norm
     "lm_head": {"w": [D,V]}?   # absent when tie_word_embeddings
    }
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from distributed_llm_inferencing_tpu.models.config import ModelConfig
from distributed_llm_inferencing_tpu.ops import lora as lora_ops
from distributed_llm_inferencing_tpu.ops.attention import (
    attend_decode, attend_prefill, resolve_backend)
from distributed_llm_inferencing_tpu.ops.kvcache import KVCache, write_block
from distributed_llm_inferencing_tpu.ops.norms import (layer_norm, norm,
                                                       rms_norm)
from distributed_llm_inferencing_tpu.ops.rope import apply_rope


def _qw(p, dt):
    """Quantized weight as compute-dtype levels (scale still pending).
    int8 reads stay int8 in HBM (XLA fuses the convert into the dot);
    int4 via this path materializes the unpack — only the pallas kernel
    keeps the read 4-bit (ops/pallas/quant_matmul.py), so this is the
    fallback for shapes/platforms the kernel doesn't cover."""
    if "p4" in p:
        from distributed_llm_inferencing_tpu.ops.quant import (
            pack_chunks, unpack_int4)
        return unpack_int4(p["p4"], pack_chunks(p)).astype(dt)
    return p["q"].astype(dt)


def _wfull(p, dt):
    """Materialized full-precision weight for leaves used OUTSIDE
    _linear's contraction (MLA's absorbed einsums): float, int8 or int4
    forms; scale applied."""
    if "w" in p:
        return p["w"].astype(dt)
    return _qw(p, dt) * p["scale"].astype(dt)


def _linear(x, p, row_sharded: bool = False):
    if "qT" in p or "wT" in p:
        # CPU-native transposed layouts (ops/cpu_gemv.py): the engine
        # repacks leaves to [dout, din] on the unrolled CPU path so
        # decode streams the stored bytes (f32 / bf16 / int8) through
        # the FFI GEMV — XLA-CPU's dot leaves ~20% of measured GEMV
        # bandwidth unused and its int8 lowering materializes the f32
        # dequant first
        from distributed_llm_inferencing_tpu.ops import cpu_gemv
        lead = x.shape[:-1]
        x2 = x.reshape(-1, x.shape[-1])
        if "qT" in p:
            if x2.shape[0] <= cpu_gemv.MAX_FAST_M:
                y = cpu_gemv.qgemv_i8(x2, p["qT"], p["scale"])
            else:   # prefill-shaped: compute-bound, XLA's GEMM wins
                y = (x2.astype(jnp.float32)
                     @ p["qT"].astype(jnp.float32).T) * p["scale"]
        else:
            if x2.shape[0] <= cpu_gemv.MAX_FAST_M:
                y = cpu_gemv.gemv_w(x2, p["wT"])
            else:
                y = x2.astype(jnp.float32) @ p["wT"].astype(jnp.float32).T
        y = y.reshape(*lead, y.shape[-1])
        if "b" in p:
            y = y + p["b"]
        return y.astype(x.dtype)
    if "p4" in p:   # int4 weight-only: pallas fused-unpack kernel on the
        # decode path, XLA unpack elsewhere (ops/pallas/quant_matmul.py)
        from distributed_llm_inferencing_tpu.ops.pallas.quant_matmul import (
            q4_linear)
        return q4_linear(x, p, row_sharded=row_sharded)
    if "q" in p:   # int8 weight-only (ops/quant.py): per-out-channel scale
        # commutes with the contraction, so it applies to the [.., dout]
        # output — the MXU reads the quantized levels, no dequantized
        # temporary
        y = jnp.einsum("...d,df->...f", x, _qw(p, x.dtype))
        y = y * p["scale"].astype(x.dtype)
    else:
        y = jnp.einsum("...d,df->...f", x, p["w"])
    if "b" in p:
        y = y + p["b"]
    return y.astype(x.dtype)


def _act(x, kind: str):
    if kind == "silu":
        return jax.nn.silu(x)
    if kind == "relu":
        return jax.nn.relu(x)
    if kind == "relu2":   # squared ReLU (nemotron)
        return jnp.square(jax.nn.relu(x))
    if kind == "gelu_exact":   # HF "gelu" (erf form): gpt-neox, falcon
        return jax.nn.gelu(x, approximate=False)
    return jax.nn.gelu(x, approximate=True)  # gpt2 uses gelu_new


def _lora_apply(y, x, lp, name, lora_ids):
    """Add the slot-gathered LoRA delta for projection ``name`` when the
    layer tree carries an adapter pack (``params["layers"]["lora"]``,
    sliced per layer by the scan/unroll like every other leaf).
    ``lora_ids`` [B] selects each row's adapter slot — 0 is the base
    model's all-zero slot, an exact-zero delta. None (the dense/engine
    path, where one adapter serves the whole batch) defaults every row
    to slot 0 of the attached pack. Base trees carry no ``lora`` key, so
    the base program traces no delta code at all."""
    lo = lp.get("lora") if isinstance(lp, dict) else None
    if lo is None or name not in lo:
        return y
    ids = (lora_ids if lora_ids is not None
           else jnp.zeros((x.shape[0],), jnp.int32))
    return y + lora_ops.gathered_delta(x, lo[name], ids)


def _mlp(x, lp, cfg: ModelConfig, lora_ids=None):
    if cfg.gated_mlp:
        h = _act(_lora_apply(_linear(x, lp["gate"]), x, lp, "gate",
                             lora_ids), cfg.activation) \
            * _lora_apply(_linear(x, lp["up"]), x, lp, "up", lora_ids)
    else:
        h = _act(_lora_apply(_linear(x, lp["up"]), x, lp, "up", lora_ids),
                 cfg.activation)
    y = _linear(h, lp["down"], row_sharded=cfg.tp_row_sharded)
    return _lora_apply(y, h, lp, "down", lora_ids)


def _ew(operand, p, eq):
    """Expert einsum with optional int8/int4 weights (scale on output)."""
    if "q" in p or "p4" in p:
        y = jnp.einsum(eq, operand, _qw(p, operand.dtype))
        return y * p["scale"].astype(operand.dtype)
    return jnp.einsum(eq, operand, p["w"])


def _moe_gates(x, lp, cfg: ModelConfig):
    """Router probs → weighted top-k gates [..., E].

    "softmax" (Mixtral convention): softmax first, then top-k, then
    renormalize. "deepseek_v3" (HF modeling_deepseek_v3.py
    DeepseekV3TopkRouter): sigmoid scores; SELECTION ranks scores +
    e_score_correction_bias under group-limited top-k (groups scored by
    their top-2 sum, only the top moe_topk_group groups are eligible);
    WEIGHTS are the unbiased scores, renormalized when moe_norm_topk,
    then scaled by moe_routed_scale. Divergence from HF, deliberate:
    HF zero-fills ineligible groups (masked_fill 0.0), which can admit
    an ineligible expert when every eligible biased score is negative —
    we mask with -inf and keep selection inside the chosen groups."""
    router_logits = jnp.einsum("...d,de->...e", x.astype(jnp.float32),
                               lp["router"]["w"].astype(jnp.float32))
    k = cfg.num_experts_per_tok
    if cfg.moe_router in ("deepseek_v3", "ernie"):
        # ernie (ERNIE-4.5-MoE): softmax scores under the same
        # bias-corrected selection (n_group=1 makes the group stage a
        # no-op); deepseek_v3: sigmoid scores + group-limited top-k
        scores = (jax.nn.sigmoid(router_logits)
                  if cfg.moe_router == "deepseek_v3"
                  else jax.nn.softmax(router_logits, axis=-1))  # [...,E]
        choice = scores + lp["router"]["bias"].astype(jnp.float32)
        G = cfg.moe_n_group
        gs = choice.reshape(*choice.shape[:-1], G, cfg.num_experts // G)
        group_scores = jnp.sum(jax.lax.top_k(gs, 2)[0], axis=-1)  # [...,G]
        gkth = jax.lax.top_k(group_scores,
                             cfg.moe_topk_group)[0][..., -1:]
        gmask = (group_scores >= gkth)[..., None]           # [...,G,1]
        eligible = jnp.broadcast_to(gmask, gs.shape).reshape(choice.shape)
        ranked = jnp.where(eligible, choice, -jnp.inf)
        kth = jax.lax.top_k(ranked, k)[0][..., -1:]
        sel = (ranked >= kth) & eligible
        gate = jnp.where(sel, scores, 0.0)
        if cfg.moe_norm_topk:
            gate = gate / (jnp.sum(gate, axis=-1, keepdims=True) + 1e-20)
        return gate * cfg.moe_routed_scale
    if cfg.moe_router == "topk_softmax":
        # gpt-oss: the router bias is part of the LINEAR (not a
        # selection-only correction); select top-k by the biased logits
        # and softmax over just the selected k values
        logits = router_logits + lp["router"]["bias"].astype(jnp.float32)
        kth = jax.lax.top_k(logits, k)[0][..., -1:]
        sel = logits >= kth
        return jnp.where(
            sel, jax.nn.softmax(jnp.where(sel, logits, -jnp.inf), axis=-1),
            0.0)
    probs = jax.nn.softmax(router_logits, axis=-1)          # [...,E]
    kth = jax.lax.top_k(probs, k)[0][..., -1:]
    gate = jnp.where(probs >= kth, probs, 0.0)
    if cfg.moe_norm_topk:   # dbrx moe_normalize_expert_weights=None
        gate = gate / jnp.sum(gate, axis=-1, keepdims=True)  # skips this
    return gate                                             # [...,E]


def _glu_h(gate, up, cfg: ModelConfig):
    """Expert hidden activation: the standard act(gate) * up, or
    gpt-oss's clamped swish GLU — gate clamped above at
    moe_swiglu_limit, up to ±limit, (up + 1) * gate * sigmoid(alpha *
    gate) (HF modeling_gpt_oss.py GptOssExperts)."""
    if cfg.moe_swiglu_limit is not None:
        lim = cfg.moe_swiglu_limit
        gate = jnp.minimum(gate, lim)
        up = jnp.clip(up, -lim, lim)
        return (up + 1.0) * (gate * jax.nn.sigmoid(
            cfg.moe_swiglu_alpha * gate))
    return _act(gate, cfg.activation) * up


def _moe_dense(x, lp, cfg: ModelConfig):
    """Compute every expert for every token, weight by the gate. E/k× the
    FLOPs of a real dispatch, but no permutation/comm beyond the psum the
    sharded expert axis induces — the right trade at decode batch sizes."""
    gate = _moe_gates(x, lp, cfg)
    ex = lp["experts"]
    g = _ew(x, ex["gate"], "...d,edi->...ei")
    u = _ew(x, ex["up"], "...d,edi->...ei")
    if "b" in ex["gate"]:   # gpt-oss per-expert biases ([E, I]/[E, D])
        g, u = g + ex["gate"]["b"], u + ex["up"]["b"]
    h = _glu_h(g, u, cfg)
    out = _ew(h, ex["down"], "...ei,eid->...ed")  # [...,E,D]
    if "b" in ex["down"]:
        out = out + ex["down"]["b"]
    out = jnp.einsum("...ed,...e->...d", out.astype(jnp.float32), gate)
    return out.astype(x.dtype)


def _moe_capacity(x, lp, cfg: ModelConfig):
    """GShard-style capacity dispatch: each expert processes at most C
    tokens, routed via dispatch/combine einsums (static shapes — XLA turns
    the [N,E,C]×[N,D] contraction into the all-to-all over the sharded
    expert axis; see PAPERS.md GShard/Switch). Tokens beyond an expert's
    capacity are dropped for that expert (their other top-k picks still
    apply); capacity_factor sizes C so drops are rare at balanced load.

    Per token the expert FLOPs are k/E of the dense path — the batched-
    prefill throughput trade (VERDICT round-1 item 8).
    """
    *lead, D = x.shape
    xf = x.reshape(-1, D)
    N = xf.shape[0]
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    C = max(1, int(cfg.moe_capacity_factor * k * N / E))

    gate = _moe_gates(xf, lp, cfg)                          # [N, E] f32
    gate_vals, gate_idx = jax.lax.top_k(gate, k)            # [N, k]
    onehot = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)  # [N, k, E]
    # position of each (token, choice) within its expert's capacity buffer:
    # priority by token order, then by choice slot (flatten to [N*k, E])
    flat = onehot.reshape(N * k, E)
    pos = (jnp.cumsum(flat, axis=0) * flat - 1.0).reshape(N, k, E)
    keep = (pos >= 0) & (pos < C)                           # [N, k, E]
    # combine[n, e, c] = gate weight of token n at expert e, slot c
    slot = jnp.clip(pos, 0, C - 1).astype(jnp.int32)
    combine = jnp.einsum(
        "nke,nkec->nec",
        onehot * gate_vals[..., None] * keep,
        jax.nn.one_hot(slot, C, dtype=jnp.float32))
    dispatch = (combine > 0).astype(x.dtype)                # [N, E, C]

    ex_in = jnp.einsum("nec,nd->ecd", dispatch, xf)         # [E, C, D]
    ex = lp["experts"]
    g = _ew(ex_in, ex["gate"], "ecd,edi->eci")
    u = _ew(ex_in, ex["up"], "ecd,edi->eci")
    if "b" in ex["gate"]:   # gpt-oss per-expert biases, [E, 1, *]
        g, u = g + ex["gate"]["b"][:, None, :], u + ex["up"]["b"][:, None, :]
    h = _glu_h(g, u, cfg)
    out = _ew(h, ex["down"], "eci,eid->ecd")                # [E, C, D]
    if "b" in ex["down"]:
        out = out + ex["down"]["b"][:, None, :]
    y = jnp.einsum("ecd,nec->nd", out.astype(jnp.float32), combine)
    return y.reshape(*lead, D).astype(x.dtype)


# token-count threshold for "auto" dispatch: at/below this the dense path
# (no permutation, no drops) wins; above it capacity dispatch's k/E FLOP
# saving dominates. Decode steps (N = batch <= slots) stay dense.
_MOE_AUTO_DENSE_MAX_TOKENS = 32


def _moe(x, lp, cfg: ModelConfig):
    """Sparse MoE — dispatch strategy per cfg.moe_dispatch, plus the
    always-active DeepSeek shared-experts MLP when the layer carries
    shared_gate/up/down leaves (added OUTSIDE the routed dispatch, HF
    DeepseekV3MoE.forward)."""
    mode = cfg.moe_dispatch
    if mode == "auto":
        n_tokens = 1
        for s in x.shape[:-1]:
            n_tokens *= s
        mode = ("dense" if n_tokens <= _MOE_AUTO_DENSE_MAX_TOKENS
                else "capacity")
    out = (_moe_capacity(x, lp, cfg) if mode == "capacity"
           else _moe_dense(x, lp, cfg))
    if cfg.moe_shared_experts:
        h = _act(_linear(x, lp["shared_gate"]), cfg.activation) * _linear(
            x, lp["shared_up"])
        out = out + _linear(h, lp["shared_down"],
                            row_sharded=cfg.tp_row_sharded)
    return out


def _alibi(cfg: ModelConfig):
    """[H] ALiBi slopes when the config uses them, else None — threaded
    into every attention formulation (trace-time constant).
    cfg.alibi_scale folds in Falcon-RW's extra 1/sqrt(head_dim) (it
    scales scores + bias together where BLOOM scales scores only)."""
    if cfg.position_embedding != "alibi":
        return None
    from distributed_llm_inferencing_tpu.ops.attention import alibi_slopes
    return alibi_slopes(cfg.num_heads) * cfg.alibi_scale


def _cfg_backend(cfg: ModelConfig, n_devices: int, op: str = "dense"):
    """resolve_backend, then force the XLA formulation for per-layer
    windows (the pallas flash/paged kernels take static windows only,
    while the traced ``attn_window`` scalar flows through the XLA masks
    unchanged) and for attention softcapping (the kernels' online
    softmax has no tanh hook)."""
    b = resolve_backend(cfg.attn_backend, n_devices, op=op)
    if b.startswith("pallas") and (cfg.attn_windows is not None
                                   or cfg.attn_softcap is not None
                                   or cfg.attn_sinks or cfg.mla):
        # mla: qk_head_dim (192) is off the kernels' 128-lane tiling and
        # v rides zero-padded — keep the XLA formulation until a
        # dedicated MLA kernel exists
        return "xla"
    return b


def _sinks(cfg: ModelConfig, lp):
    """[H] per-layer attention-sink logits (gpt-oss) — a layer-tree leaf
    like the q/k norms, threaded into every attention formulation."""
    return lp["sinks"] if cfg.attn_sinks else None


def _layer_window(cfg: ModelConfig, lp):
    """Effective attention window for one layer.

    Per-layer windows (cfg.attn_windows, GPT-Neo's alternating
    global/local) ride the layer param tree as an int32 ``attn_window``
    leaf ([L] stacked; -1 == global) — under scan/unroll/pipeline ``lp``
    holds this layer's scalar slice, so every serving path threads it
    with no extra plumbing. Uniform-window families fall through to the
    static cfg.sliding_window."""
    if isinstance(lp, dict) and "attn_window" in lp:
        return lp["attn_window"]
    return cfg.sliding_window


def embed(params, cfg: ModelConfig, tokens, q_positions):
    """Token (+ learned position) embedding. Shared by the scanned forward
    below and the pipelined executor (parallel/pipeline.py)."""
    table = params["embed"]["tokens"]
    if isinstance(table, dict):   # int8 per-row table (cfg.embed_quant):
        # gather whole rows then one scalar multiply per row — the HBM
        # read is s rows of int8, not the float table
        x = jnp.take(table["q8"], tokens, axis=0).astype(jnp.dtype(cfg.dtype))
        x = x * jnp.take(table["rscale"], tokens,
                         axis=0)[..., None].astype(x.dtype)
    else:
        x = jnp.take(table, tokens, axis=0)
    x = x.astype(jnp.dtype(cfg.dtype))
    if cfg.embed_scale is not None:   # gemma: sqrt(D) normalizer on the
        # embedding output only — the tied head reads the raw table
        x = x * jnp.asarray(cfg.embed_scale, x.dtype)
    if "project_in" in params["embed"]:   # opt-350m: embed dim < hidden dim
        x = _linear(x, params["embed"]["project_in"])
    if cfg.position_embedding == "learned":
        # Positions are clipped only as jit-safety; the engine rejects
        # requests whose prompt+max_new_tokens exceed the context window
        # (runtime/engine.py), so clipping never silently engages.
        pos = jnp.take(params["embed"]["positions"],
                       jnp.clip(q_positions, 0, cfg.max_position_embeddings - 1),
                       axis=0)
        x = x + pos.astype(x.dtype)
    if cfg.embed_norm:   # bloom: layernorm on the embedding output
        x = norm(x, params["embed"]["norm"], cfg.norm_type, cfg.norm_eps)
    return x


def unembed(params, cfg: ModelConfig, x):
    """Final norm + logits head, f32. Shared with parallel/pipeline.py.

    Post-LN models (opt-350m) have no final norm — each block already
    normalized its residual output; the embed projection (if any) maps
    back to the embedding dim before the tied head.
    """
    if not cfg.post_norm:
        x = norm(x, params["final_norm"], cfg.norm_type, cfg.norm_eps)
    if "project_out" in params["embed"]:
        x = _linear(x, params["embed"]["project_out"])
    if cfg.tie_word_embeddings:
        table = params["embed"]["tokens"]
        # The tied head is the single largest per-token read; on a
        # single-visible-device CPU process with decode-shaped rows the
        # FFI kernel streams the stored bytes directly (the [V, D] table
        # IS its transposed layout) — int8 rows with the per-row scale
        # (a per-output-channel scale here, it commutes out of the dot),
        # or raw f32/bf16 rows.
        from distributed_llm_inferencing_tpu.ops import cpu_gemv
        b, s, d = x.shape
        if cpu_gemv.usable_for_rows(b * s):
            x2 = x.reshape(b * s, d)
            logits = (cpu_gemv.qgemv_i8(x2, table["q8"], table["rscale"])
                      if isinstance(table, dict)
                      else cpu_gemv.gemv_w(x2, table))
            return _head_post(logits.reshape(b, s, -1), cfg
                              ).astype(jnp.float32)
        if isinstance(table, dict):   # int8 table (cfg.embed_quant)
            logits = jnp.einsum("bsd,vd->bsv", x,
                                table["q8"].astype(x.dtype))
            logits = logits * table["rscale"].astype(x.dtype)
        else:
            logits = jnp.einsum("bsd,vd->bsv", x, table.astype(x.dtype))
    else:
        logits = _linear(x, params["lm_head"])
    return _head_post(logits, cfg).astype(jnp.float32)


def _head_post(logits, cfg: ModelConfig):
    """Head post-processing: Cohere's constant logit scale and Gemma-2's
    final softcap, applied wherever logits leave the model (incl. the
    CPU FFI fast path, which returns early)."""
    if cfg.logit_scale is not None:
        logits = logits * cfg.logit_scale
    if cfg.logit_softcap is not None:
        logits = jnp.tanh(logits / cfg.logit_softcap) * cfg.logit_softcap
    return logits


def _qk_normalize(t, p, cfg: ModelConfig):
    """cfg.qk_norm on projected q or k [B,s,H,hd], pre-RoPE.

    "rms_head"/"ln_head" normalize each head over head_dim (qwen3 /
    cohere use_qk_norm); "rms_full" normalizes the flattened projection
    width (olmo2 applies the norm to the [.., H*hd] projection output
    before the head reshape)."""
    kind = cfg.qk_norm
    if kind == "rms_full":
        B, s, H, hd = t.shape
        return rms_norm(t.reshape(B, s, H * hd), p["scale"],
                        cfg.norm_eps).reshape(B, s, H, hd)
    if kind == "ln_head":   # cohere: bias-free layernorm per head, with
        # DISTINCT per-head scales (stored flat [H*hd])
        H, hd = t.shape[-2:]
        return layer_norm(t, p["scale"].reshape(H, hd),
                          jnp.zeros((), t.dtype), cfg.norm_eps)
    return rms_norm(t, p["scale"], cfg.norm_eps)


def layer_segments(params, cfg: ModelConfig):
    """Execution-ordered layer segments of a (possibly heterogeneous)
    stack: ``[(layers_tree, segment_cfg, start, count)]``.

    A homogeneous model is one segment. DeepSeek's
    ``first_k_dense_replace`` layout (cfg.dense_prefix_layers) is two:
    a dense-MLP prefix (param key ``layers_dense``) ahead of the MoE
    tail (``layers``). Attention and cache layout are identical across
    segments — only the MLP half of the block differs — so callers
    slice their [L, ...]-stacked cache/pool planes by (start, count)
    and run the same block body under each segment's cfg."""
    if "layers_dense" not in params:
        return [(params["layers"], cfg, 0, cfg.num_layers)]
    k = cfg.dense_prefix_layers
    return [(params["layers_dense"], cfg.dense_segment_cfg(), 0, k),
            (params["layers"], cfg, k, cfg.num_layers - k)]


def scan_layer_stack(make_body, x, params, cfg: ModelConfig, xs):
    """Run the block stack over ``x``, segment-aware.

    ``make_body(seg_cfg)`` returns a ``lax.scan`` body
    ``(carry, (lp, *per_layer_xs)) -> (carry, per_layer_out)``;
    ``xs`` is a tuple of [L, ...]-stacked per-layer arrays (cache or
    pool planes). Each segment scans its own stacked tree (or, for the
    engine's CPU-unrolled per-layer buffer lists, loops Python-side);
    per-layer outputs are re-stacked and concatenated back to [L, ...]
    order. Returns (carry, tuple_of_[L,...]_outputs)."""
    seg_outs = []
    for layers_seg, seg_cfg, start, n in layer_segments(params, cfg):
        seg_xs = tuple(p[start:start + n] for p in xs)
        body = make_body(seg_cfg)
        if isinstance(layers_seg, (list, tuple)):
            # unrolled per-layer weight buffers (engine._maybe_unroll_
            # layers): real per-buffer weights get XLA-CPU's dot kernel
            outs = []
            for i, lp in enumerate(layers_seg):
                x, out = body(x, (lp,) + tuple(p[i] for p in seg_xs))
                outs.append(out)
            seg_outs.append(tuple(
                jnp.stack([o[j] for o in outs])
                for j in range(len(outs[0]))))
        else:
            x, co = jax.lax.scan(body, x, (layers_seg,) + seg_xs)
            seg_outs.append(co)
    if len(seg_outs) == 1:
        return x, seg_outs[0]
    cat = tuple(jnp.concatenate([so[j] for so in seg_outs], axis=0)
                for j in range(len(seg_outs[0])))
    return x, cat


def _mla_qkv(h, lp, cfg: ModelConfig, q_positions):
    """DeepSeek-V3 multi-head latent attention projections (HF
    modeling_deepseek_v3.py:327-446). q and kv pass through low-rank
    bottlenecks with an RMSNorm at each bottleneck — the reason MLA
    cannot be pre-expanded into plain q/k/v weights at conversion.

    Layout choices, both score-invariant permutations of HF's:
    - per-head q/k dims are ordered [rope | nope] (HF: [nope | rope]) so
      the RoPE'd slice is contiguous at the front; conversion permutes
      the projection columns to match (models/convert.py deepseek).
    - rope uses the gptj-interleaved pairing when cfg.rope_interleaved
      (HF's apply_rotary_pos_emb_interleave permutes pairs->halves then
      half-rotates; same rotation pairs, different output layout —
      identical q·k scores since q and k transform together).

    k's rope part is computed ONCE from the hidden state (MQA-style) and
    broadcast across heads; v is zero-padded from v_head_dim to head_dim
    so every cache/attention path keeps one head_dim (the block slices
    the attention output back before o). Returns q,k,v [B,s,H,head_dim].
    """
    B, s, _ = h.shape
    H, hd = cfg.num_heads, cfg.head_dim
    rd, vd = cfg.qk_rope_head_dim, cfg.v_head_dim_effective
    r = cfg.kv_lora_rank
    q = _mla_q(h, lp, cfg, q_positions)

    k_rot, c = _mla_kv_latent(h, lp, cfg, q_positions)
    k_nope = _linear(c, lp["kv_b_k"]).reshape(B, s, H, hd - rd)
    v = _linear(c, lp["kv_b_v"]).reshape(B, s, H, vd)
    k = jnp.concatenate(
        [jnp.broadcast_to(k_rot, (B, s, H, rd)), k_nope], axis=-1)
    if vd < hd:
        v = jnp.pad(v, ((0, 0), (0, 0), (0, 0), (0, hd - vd)))
    return q, k, v


def _mla_q(h, lp, cfg: ModelConfig, q_positions):
    """MLA query projection, shared by the materialized and latent
    formulations: [B,s,H,head_dim] with per-head dims [rope | nope],
    RoPE applied to the rope slice."""
    B, s, _ = h.shape
    H, hd, rd = cfg.num_heads, cfg.head_dim, cfg.qk_rope_head_dim
    if cfg.q_lora_rank:
        cq = norm(_linear(h, lp["q_a"]), lp["q_a_norm"], "rmsnorm",
                  cfg.norm_eps)
        q = _linear(cq, lp["q_b"]).reshape(B, s, H, hd)
    else:
        q = _linear(h, lp["q"]).reshape(B, s, H, hd)
    q_rot = apply_rope(q[..., :rd], q_positions, cfg.rope_theta,
                       interleaved=cfg.rope_interleaved,
                       inv_freq=cfg.rope_inv_freq,
                       attn_factor=cfg.rope_attn_factor)
    return jnp.concatenate([q_rot, q[..., rd:]], axis=-1)


def _mla_kv_latent(h, lp, cfg: ModelConfig, q_positions):
    """MLA kv bottleneck, shared by the materialized and latent
    formulations: returns (k_rot [B,s,1,rd] post-RoPE, c [B,s,r]
    normed)."""
    r = cfg.kv_lora_rank
    ckv = _linear(h, lp["kv_a"])                         # [B,s,r+rd]
    k_rot = apply_rope(ckv[..., r:][:, :, None, :], q_positions,
                       cfg.rope_theta,
                       interleaved=cfg.rope_interleaved,
                       inv_freq=cfg.rope_inv_freq,
                       attn_factor=cfg.rope_attn_factor)  # [B,s,1,rd]
    c = norm(ckv[..., :r], lp["kv_a_norm"], "rmsnorm", cfg.norm_eps)
    return k_rot, c


def _mla_latent_attn(h, lp, cfg: ModelConfig, q_positions, cache_k,
                     cache_v, write_starts, new_lengths, is_prefill,
                     backend):
    """MLA attention over the LATENT cache (cfg.mla_latent_cache) for
    the dense-cache serving path.

    The cache's k plane holds one shared row per token —
    [k_rot (rd, post-RoPE) | c (kv_lora_rank, normed)] — and the v plane
    is zero-width. Prefill attends its fresh block with materialized
    per-head K/V (the O(s^2) regime where compute, not cache traffic,
    dominates) while writing only the latent row. Decode runs the
    absorbed formulation: scores q_nope·(W_uk c) == (W_uk^T q_nope)·c
    and outputs W_uv (Σ w c), i.e. MQA over the (rd + r)-wide latent
    with the per-head up-projections folded into q and pulled out of
    the weighted sum — exactly the materialized attention's numbers,
    reassociated. Score scale stays the materialized head_dim's
    (ops/attention.attend ``scale``).

    Returns (attn [B,s,H,v_head_dim], (new_cache_k, cache_v)).
    """
    B, s, _ = h.shape
    H, hd = cfg.num_heads, cfg.head_dim
    rd, r = cfg.qk_rope_head_dim, cfg.kv_lora_rank
    nd, vd = cfg.qk_nope_head_dim, cfg.v_head_dim_effective
    q = _mla_q(h, lp, cfg, q_positions)                  # [B,s,H,hd]
    k_rot, c = _mla_kv_latent(h, lp, cfg, q_positions)
    latent = jnp.concatenate([k_rot, c[:, :, None, :]], axis=-1)
    ck = write_block(cache_k, latent, write_starts)      # [B,S,1,rd+r]

    wk = _wfull(lp["kv_b_k"], q.dtype).reshape(r, H, nd)
    wv = _wfull(lp["kv_b_v"], q.dtype).reshape(r, H, vd)
    if is_prefill:
        # fresh-block attention with materialized per-head K/V — v
        # zero-padded to head_dim for flash-kernel eligibility (same
        # trade as the materialized path), sliced back after
        k_nope = jnp.einsum("bsr,rhn->bshn", c, wk)
        k = jnp.concatenate(
            [jnp.broadcast_to(k_rot, (B, s, H, rd)), k_nope], axis=-1)
        v = jnp.einsum("bsr,rhv->bshv", c, wv)
        if vd < hd:
            v = jnp.pad(v, ((0, 0), (0, 0), (0, 0), (0, hd - vd)))
        attn = attend_prefill(q, k, v, backend=backend)[..., :vd]
    else:
        q_eff = jnp.concatenate(
            [q[..., :rd],
             jnp.einsum("bshn,rhn->bshr", q[..., rd:], wk)], axis=-1)
        ctx = attend_decode(
            q_eff, ck, ck[..., rd:], new_lengths, backend="xla",
            q_positions=q_positions,   # multi-token speculative verify
            # needs per-query causal masks, not the lengths-1 default
            scale=1.0 / float(hd) ** 0.5)                # [B,s,H,r]
        attn = jnp.einsum("bshr,rhv->bshv", ctx, wv)
    return attn, (ck, cache_v)


def _block_body(x, lp, cfg: ModelConfig, q_positions, attend_write,
                mla_latent_attend=None, fused_q_attend=None,
                lora_ids=None):
    """One transformer block: norm → QKV (+RoPE) → attend → norm → MLP/MoE.

    The single definition of the block structure, shared by the dense path
    (_block) and the paged serving paths (paged_decode_step /
    paged_prefill_tail) so the three can never diverge. ``attend_write(q,
    k, v) -> (attn [B,s,H,hd], cache_out)`` owns the regime-specific part:
    cache update + attention formulation.

    ``fused_q_attend(h, k, v) -> (attn, cache_out)`` (DLI_FUSED_DECODE,
    ops/pallas/fused_decode.py): the q projection + RoPE + attention run
    fused inside the callback's single pallas_call — the block computes
    ONLY k/v here (their projections feed the cache write, which the
    kernel reads back). The caller gates eligibility
    (fused_decode.supported); ineligible configs never reach this arm.

    cfg.post_norm flips pre-LN (norm -> sublayer -> residual) to the
    post-LN order opt-350m uses (sublayer -> residual -> norm);
    cfg.parallel_residual is the GPT-NeoX/Phi/Falcon topology — attention
    and MLP both read (norms of) the same block input and share one
    residual add, with cfg.shared_attn_mlp_norm collapsing the two norms
    into one (Phi / Falcon-7B).
    """
    B, s, _ = x.shape
    h = x if (cfg.post_norm or cfg.sublayer_postnorm_only) else norm(
        x, lp["attn_norm"], cfg.norm_type, cfg.norm_eps)
    if mla_latent_attend is not None:
        # dense-cache latent formulation (cfg.mla_latent_cache): the
        # whole attention — projections, cache, absorbed decode — runs
        # inside the callback; output arrives at v_head_dim already
        attn, cache_out = mla_latent_attend(h, q_positions)
        vd = cfg.v_head_dim_effective
        attn = _linear(attn.reshape(B, s, cfg.num_heads * vd), lp["o"],
                       row_sharded=cfg.tp_row_sharded)
        return _block_tail(x, h, attn, cache_out, lp, cfg)
    if fused_q_attend is not None:
        # fused decode arm: project/rotate ONLY k and v (the kernel owns
        # q end-to-end); eligibility (no qk_norm/clip, full-width
        # non-interleaved rope) was gated by the caller
        k = _linear(h, lp["k"]).reshape(B, s, cfg.num_kv_heads,
                                        cfg.head_dim)
        v = _linear(h, lp["v"]).reshape(B, s, cfg.num_kv_heads,
                                        cfg.head_dim)
        if cfg.position_embedding == "rope":
            k = apply_rope(k, q_positions, cfg.rope_theta, cfg.rope_pct,
                           cfg.rope_interleaved,
                           inv_freq=cfg.rope_inv_freq,
                           attn_factor=cfg.rope_attn_factor)
        attn, cache_out = fused_q_attend(h, k, v)
        attn = _linear(attn.reshape(B, s, cfg.num_heads * cfg.head_dim),
                       lp["o"], row_sharded=cfg.tp_row_sharded)
        return _block_tail(x, h, attn, cache_out, lp, cfg)
    if cfg.mla:
        q, k, v = _mla_qkv(h, lp, cfg, q_positions)   # rope applied inside
    else:
        # LoRA deltas on the flat projection outputs (models/lora.py
        # rejects MLA/MoE bases, so the arms above never carry a pack)
        q = _lora_apply(_linear(h, lp["q"]), h, lp, "q", lora_ids) \
            .reshape(B, s, cfg.num_heads, cfg.head_dim)
        k = _lora_apply(_linear(h, lp["k"]), h, lp, "k", lora_ids) \
            .reshape(B, s, cfg.num_kv_heads, cfg.head_dim)
        v = _lora_apply(_linear(h, lp["v"]), h, lp, "v", lora_ids) \
            .reshape(B, s, cfg.num_kv_heads, cfg.head_dim)

        if cfg.qkv_clip is not None:   # dbrx clip_qkv activation clamp
            q = jnp.clip(q, -cfg.qkv_clip, cfg.qkv_clip)
            k = jnp.clip(k, -cfg.qkv_clip, cfg.qkv_clip)
            v = jnp.clip(v, -cfg.qkv_clip, cfg.qkv_clip)

        if cfg.qk_norm and not cfg.qk_norm_after_rope:
            q = _qk_normalize(q, lp["q_norm"], cfg)
            k = _qk_normalize(k, lp["k_norm"], cfg)

        if cfg.position_embedding == "rope":
            q_r = apply_rope(q, q_positions, cfg.rope_theta, cfg.rope_pct,
                             cfg.rope_interleaved,
                             inv_freq=cfg.rope_inv_freq,
                             attn_factor=cfg.rope_attn_factor)
            k_r = apply_rope(k, q_positions, cfg.rope_theta, cfg.rope_pct,
                             cfg.rope_interleaved,
                             inv_freq=cfg.rope_inv_freq,
                             attn_factor=cfg.rope_attn_factor)
            if cfg.rope_layers is not None:
                # per-layer NoPE (smollm3/exaone4): the int32 rope_on
                # leaf rides the layer tree; compute-and-select keeps
                # the scan body uniform
                on = lp["rope_on"].astype(jnp.bool_)
                q, k = jnp.where(on, q_r, q), jnp.where(on, k_r, k)
            else:
                q, k = q_r, k_r

        if cfg.qk_norm and cfg.qk_norm_after_rope:   # hunyuan ordering
            q = _qk_normalize(q, lp["q_norm"], cfg)
            k = _qk_normalize(k, lp["k_norm"], cfg)

    attn, cache_out = attend_write(q, k, v)
    vd = cfg.v_head_dim_effective
    if vd < cfg.head_dim:   # MLA: v rode the cache zero-padded
        attn = attn[..., :vd]
    attn_flat = attn.reshape(B, s, cfg.num_heads * vd)
    attn = _lora_apply(
        _linear(attn_flat, lp["o"], row_sharded=cfg.tp_row_sharded),
        attn_flat, lp, "o", lora_ids)
    return _block_tail(x, h, attn, cache_out, lp, cfg, lora_ids=lora_ids)


def _block_tail(x, h, attn, cache_out, lp, cfg: ModelConfig, lora_ids=None):
    """Post-attention half of the block: residual topology + MLP/MoE
    (shared by the materialized and MLA-latent attention dispatches)."""
    if cfg.post_block_norms:   # gemma2 sandwich: norm BEFORE the residual
        attn = norm(attn, lp["attn_post_norm"], cfg.norm_type, cfg.norm_eps)
    elif cfg.sublayer_postnorm_only:   # olmo2: x + norm(attn(x))
        attn = norm(attn, lp["attn_norm"], cfg.norm_type, cfg.norm_eps)
    if cfg.residual_scale is not None:   # granite residual_multiplier
        attn = attn * cfg.residual_scale

    if cfg.parallel_residual:
        h2 = h if cfg.shared_attn_mlp_norm else norm(
            x, lp["mlp_norm"], cfg.norm_type, cfg.norm_eps)
        mlp_out = _moe(h2, lp, cfg) if cfg.is_moe \
            else _mlp(h2, lp, cfg, lora_ids=lora_ids)
        if cfg.residual_scale is not None:
            mlp_out = mlp_out * cfg.residual_scale
        return x + attn + mlp_out, cache_out

    x = x + attn
    if cfg.post_norm:
        x = norm(x, lp["attn_norm"], cfg.norm_type, cfg.norm_eps)

    h = x if (cfg.post_norm or cfg.sublayer_postnorm_only) else norm(
        x, lp["mlp_norm"], cfg.norm_type, cfg.norm_eps)
    moe_out = _moe(h, lp, cfg) if cfg.is_moe \
        else _mlp(h, lp, cfg, lora_ids=lora_ids)
    if cfg.post_block_norms:
        moe_out = norm(moe_out, lp["mlp_post_norm"], cfg.norm_type,
                       cfg.norm_eps)
    elif cfg.sublayer_postnorm_only:
        moe_out = norm(moe_out, lp["mlp_norm"], cfg.norm_type, cfg.norm_eps)
    if cfg.residual_scale is not None:
        moe_out = moe_out * cfg.residual_scale
    x = x + moe_out
    if cfg.post_norm:
        x = norm(x, lp["mlp_norm"], cfg.norm_type, cfg.norm_eps)
    return x, cache_out


def _block(x, lp, cache_k, cache_v, *, cfg: ModelConfig, q_positions,
           write_starts, new_lengths, is_prefill, backend, mesh=None,
           cache_ks=None, cache_vs=None):
    """One transformer block over the dense cache.

    x: [B,s,D]; cache_k/v: [B,S,Hkv,hd] (this layer's slice);
    write_starts: [B] int32 slot where this token block begins, per sequence.
    Returns (x_out, new_cache_k, new_cache_v[, new_k_scale, new_v_scale]).

    Two attention regimes (ops/attention.py): prefill attends the fresh
    K/V block directly — O(s^2) instead of O(s * max_seq) over the mostly
    empty cache — while decode attends the cache (dequantized at read when
    ``cache_ks``/``cache_vs`` scales are present, ops/kvcache.py).
    """
    quantized = cache_ks is not None
    if cfg.mla_latent_cache:
        # latent-layout cache: attention runs entirely inside the
        # absorbed-formulation callback (engine enables this only on
        # eligible meshes — no sp/pp, no kv_quant)
        def mla_latent_attend(h, qp):
            return _mla_latent_attn(
                h, lp, cfg, qp, cache_k, cache_v, write_starts,
                new_lengths, is_prefill, backend)
        x, cache_out = _block_body(x, lp, cfg, q_positions, None,
                                   mla_latent_attend=mla_latent_attend)
        return (x,) + cache_out

    def attend_write(q, k, v):
        if quantized:
            from distributed_llm_inferencing_tpu.ops.kvcache import (
                dequant_kv, quant_kv)
            k8, ks_new = quant_kv(k)
            v8, vs_new = quant_kv(v)
            ck = write_block(cache_k, k8, write_starts)
            cv = write_block(cache_v, v8, write_starts)
            cks = write_block(cache_ks, ks_new, write_starts)
            cvs = write_block(cache_vs, vs_new, write_starts)
            cache_out = (ck, cv, cks, cvs)
            # decode attends the dequantized view; the convert+scale fuses
            # into the attention matmul (reads stay int8 in HBM)
            ck_at = dequant_kv(ck, cks, x.dtype)
            cv_at = dequant_kv(cv, cvs, x.dtype)
        else:
            ck = write_block(cache_k, k, write_starts)
            cv = write_block(cache_v, v, write_starts)
            cache_out = (ck, cv)
            ck_at, cv_at = ck, cv
        if is_prefill and mesh is not None and mesh.shape.get("sp", 1) > 1:
            # sequence-parallel long-context path: ring attention over sp
            # (parallel/ring.py) — K/V chunks rotate via ppermute, no device
            # ever holds the full sequence
            from distributed_llm_inferencing_tpu.parallel.ring import (
                ring_attend_prefill)
            attn = ring_attend_prefill(
                q, k, v, q_positions, new_lengths, mesh=mesh,
                sliding_window=_layer_window(cfg, lp), alibi=_alibi(cfg), softcap=cfg.attn_softcap, sinks=_sinks(cfg, lp))
        elif is_prefill:
            attn = attend_prefill(q, k, v, sliding_window=_layer_window(cfg, lp),
                                  backend=backend, alibi=_alibi(cfg), softcap=cfg.attn_softcap,
                                  sinks=_sinks(cfg, lp))
        elif mesh is not None and mesh.shape.get("sp", 1) > 1:
            # sp-sharded cache decode: flash-decoding partials per shard +
            # one combine (parallel/ring.py ring_attend_decode) — replaces
            # the dense-under-GSPMD fallback
            from distributed_llm_inferencing_tpu.parallel.ring import (
                ring_attend_decode)
            attn = ring_attend_decode(q, ck_at, cv_at, new_lengths,
                                      mesh=mesh,
                                      sliding_window=_layer_window(cfg, lp),
                                      alibi=_alibi(cfg), softcap=cfg.attn_softcap,
                                      sinks=_sinks(cfg, lp))
        else:
            # quantized caches pin the xla formulation: the dequant fuses
            # into its matmul, while a pallas kernel input would
            # materialize the bf16 copy and forfeit the int8 read
            attn = attend_decode(q, ck_at, cv_at, new_lengths,
                                 sliding_window=_layer_window(cfg, lp),
                                 backend="xla" if quantized else backend,
                                 q_positions=q_positions, alibi=_alibi(cfg), softcap=cfg.attn_softcap,
                                 sinks=_sinks(cfg, lp))
        return attn, cache_out

    x, cache_out = _block_body(x, lp, cfg, q_positions, attend_write)
    return (x,) + cache_out


def forward(
    params,
    cfg: ModelConfig,
    tokens,                      # [B, s] int32 — a block of new tokens
    cache: KVCache,
    write_starts,                # [B] int32 — first cache slot this block occupies
    q_positions,                 # [B, s] int32 — absolute positions of `tokens`
    new_lengths,                 # [B] int32 — cache lengths after this block
    is_prefill: bool = False,    # static: fresh-KV attention regime
    mesh=None,                   # static: enables the sp ring-attention path
) -> Tuple[jax.Array, KVCache]:
    """Run the model over a block of tokens, updating the cache.

    Used for both prefill (s = padded prompt length, write_starts = 0) and
    decode (s = 1, write_starts = current lengths). Returns
    (logits [B,s,V] float32, updated cache).

    Invariant: cache slot index == absolute token position (the engine always
    writes blocks contiguously per sequence), so kv positions are the slot
    index and validity is slot < length.
    """
    B, s = tokens.shape
    x = embed(params, cfg, tokens, q_positions)

    # Conservative device count for 'auto': the engine pins a concrete
    # backend for its own programs; direct callers (tests, dryrun) get
    # pallas only when the whole process sees a single device, since the
    # pallas kernels are single-program (no GSPMD partitioning rule).
    backend = _cfg_backend(cfg, jax.device_count())

    # one body serves both cache layouts: scale planes ride the scan xs
    # only when the cache is quantized. (The unrolled-list and
    # dense-prefix segment dispatch live in scan_layer_stack.)
    def make_body(seg_cfg):
        def body(x, layer_in):
            lp, ck, cv, *scales = layer_in
            out = _block(
                x, lp, ck, cv, cfg=seg_cfg, q_positions=q_positions,
                write_starts=write_starts, new_lengths=new_lengths,
                is_prefill=is_prefill, backend=backend, mesh=mesh,
                cache_ks=scales[0] if scales else None,
                cache_vs=scales[1] if scales else None)
            return out[0], tuple(out[1:])
        return body

    cache_xs = (cache.k, cache.v) + (
        (cache.k_scale, cache.v_scale) if cache.quantized else ())
    x, cache_out = scan_layer_stack(make_body, x, params, cfg, cache_xs)
    logits = unembed(params, cfg, x)
    planes = dict(zip(("k", "v", "k_scale", "v_scale"), cache_out))
    return logits, KVCache(lengths=new_lengths, **planes)


def prefill(params, cfg: ModelConfig, tokens, lengths, cache: KVCache,
            mesh=None):
    """Prefill a right-padded prompt block. tokens [B,S0], lengths [B].

    Padding tokens beyond each sequence's length land in cache slots that the
    validity mask excludes and that later decode steps overwrite in order, so
    ragged batches need no re-packing.

    Pass ``mesh`` (with an sp axis of size > 1) to run attention
    sequence-parallel via ring attention (parallel/ring.py).
    """
    B, s = tokens.shape
    q_pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (B, s))
    return forward(params, cfg, tokens, cache,
                   write_starts=jnp.zeros((B,), jnp.int32),
                   q_positions=q_pos, new_lengths=lengths, is_prefill=True,
                   mesh=mesh)


def decode_step(params, cfg: ModelConfig, tokens, cache: KVCache,
                mesh=None):
    """One decode step. tokens [B,1] — next token per sequence.

    Each sequence writes at its own slot (its current length), so ragged
    batches decode correctly. Lengths advance by 1 for every sequence.

    Pass ``mesh`` (with sp > 1) to attend the sequence-sharded cache via
    the flash-decoding combine (parallel/ring.py ring_attend_decode).
    """
    q_pos = cache.lengths[:, None]  # [B,1] — next position per sequence
    return forward(params, cfg, tokens, cache,
                   write_starts=cache.lengths, q_positions=q_pos,
                   new_lengths=cache.lengths + 1, mesh=mesh)


# ----------------------------------------------------------------------
# Paged-cache forward passes (continuous-batching serving path)
# ----------------------------------------------------------------------

def paged_decode_step(params, cfg: ModelConfig, tokens, paged,
                      block_tables, context_lens, lora_ids=None):
    """One decode step over the paged cache for R serving slots.

    tokens: [R] next token per slot; paged: ops.paged_kvcache.PagedKVCache;
    block_tables: [R, MB] int32; context_lens: [R] — cached tokens per slot
    BEFORE this step (the new token writes at that position).

    Inactive slots must point at a reserved dummy block with context_len 0
    (the batcher guarantees this); their writes land in the dummy block and
    their outputs are discarded. Returns (logits [R, V] f32, new paged).
    """
    from distributed_llm_inferencing_tpu.ops.paged_kvcache import (
        PagedKVCache, paged_attend_decode, write_token)
    from distributed_llm_inferencing_tpu.ops.pallas import fused_decode
    r = tokens.shape[0]
    backend = _cfg_backend(cfg, jax.device_count())
    q_pos = context_lens[:, None]                       # [R, 1]
    x = embed(params, cfg, tokens[:, None], q_pos)      # [R, 1, D]
    quantized = paged.quantized
    # Fused dequant-GEMV -> RoPE -> paged flash attention
    # (ops/pallas/fused_decode.py, DLI_FUSED_DECODE): one pallas_call per
    # layer replaces the q einsum + rope + attention chain — q never
    # round-trips HBM. Interpret mode off-TPU (the differential oracle
    # path the parity suite exercises); the unfused formulation below
    # stays bitwise-authoritative everywhere the gate declines.
    # the fused kernel owns q end-to-end, so a wave carrying LoRA rows —
    # explicit ids, or an adapter pack riding the layer tree — must run
    # the unfused formulation where the q/o deltas have a seam
    has_lora = (isinstance(params.get("layers"), dict)
                and "lora" in params["layers"])
    use_fused = (fused_decode.eligible(cfg, quantized)
                 and lora_ids is None and not has_lora)
    fused_interpret = jax.default_backend() != "tpu"
    rope_cos = rope_sin = None
    if use_fused and cfg.position_embedding == "rope":
        rope_cos, rope_sin = fused_decode.rope_cos_sin(
            cfg, context_lens, cfg.head_dim)

    def make_body(seg_cfg):
        def body(x, layer_in):
            lp, ck, cv, *scales = layer_in              # ck: [NB, bs, Hkv, hd]

            if use_fused and fused_decode.supported(seg_cfg, lp["q"]):
                def fused_q_attend(h, k, v):
                    nk = write_token(ck, k[:, 0], block_tables,
                                     context_lens)
                    nv = write_token(cv, v[:, 0], block_tables,
                                     context_lens)
                    attn = fused_decode.fused_decode_step(
                        h[:, 0], lp["q"], nk, nv, block_tables,
                        context_lens + 1,
                        rope_cos=rope_cos, rope_sin=rope_sin,
                        sliding_window=_layer_window(seg_cfg, lp),
                        interpret=fused_interpret)
                    return attn[:, None], (nk, nv)
                return _block_body(x, lp, seg_cfg, q_pos, None,
                                   fused_q_attend=fused_q_attend)

            def attend_write(q, k, v):
                if quantized:
                    from distributed_llm_inferencing_tpu.ops.kvcache import (
                        quant_kv)
                    cks, cvs = scales
                    k8, ks = quant_kv(k[:, 0])
                    v8, vs = quant_kv(v[:, 0])
                    nk = write_token(ck, k8, block_tables, context_lens)
                    nv = write_token(cv, v8, block_tables, context_lens)
                    nks = write_token(cks, ks, block_tables, context_lens)
                    nvs = write_token(cvs, vs, block_tables, context_lens)
                    attn = paged_attend_decode(
                        q, nk, nv, block_tables, context_lens + 1,
                        sliding_window=_layer_window(seg_cfg, lp),
                        backend=backend,
                        k_scale_layer=nks, v_scale_layer=nvs,
                        alibi=_alibi(seg_cfg), softcap=seg_cfg.attn_softcap,
                        sinks=_sinks(seg_cfg, lp))
                    return attn, (nk, nv, nks, nvs)
                nk = write_token(ck, k[:, 0], block_tables, context_lens)
                nv = write_token(cv, v[:, 0], block_tables, context_lens)
                attn = paged_attend_decode(
                    q, nk, nv, block_tables, context_lens + 1,
                    sliding_window=_layer_window(seg_cfg, lp),
                    backend=backend,
                    alibi=_alibi(seg_cfg), softcap=seg_cfg.attn_softcap,
                    sinks=_sinks(seg_cfg, lp))
                return attn, (nk, nv)

            return _block_body(x, lp, seg_cfg, q_pos, attend_write,
                               lora_ids=lora_ids)
        return body

    xs = (paged.k, paged.v) + (
        (paged.k_scale, paged.v_scale) if quantized else ())
    x, cache_out = scan_layer_stack(make_body, x, params, cfg, xs)
    logits = unembed(params, cfg, x)[:, 0]              # [R, V]
    return logits, PagedKVCache(*cache_out)


# Cap for materializing the whole chunk's pool gather [L, R, P, Hkv, hd]
# up front (see paged_decode_chunk): under it, one gather per chunk; over
# it (long contexts), one transient per-layer gather per step.
_PREGATHER_MAX_BYTES = 256 * 1024 * 1024


def paged_decode_chunk(params, cfg: ModelConfig, k: int, tokens, paged,
                       block_tables, context_lens, seeds, steps0, temps,
                       tks, tps, ds, budget, eos_ids, dummy_block: int,
                       lora_ids=None):
    """Run K decode steps + sampling entirely on device for R serving slots.

    The continuous batcher's throughput lever: one dispatched program
    advances every active slot up to ``k`` tokens, so the host syncs once
    per chunk instead of once per token (the same chunked-scan trade the
    engine makes, runtime/engine.py DECODE_CHUNKS — a per-token host round
    trip is what made the reference's loop unshippable behind a network
    hop, reference worker/app.py:297-305).

    Per-slot lifecycle runs as data inside the scan:
    - ``budget[r]``: how many tokens slot r may still emit (0 = inactive).
      A slot is *alive* until its budget is spent or it samples its eos.
    - ``eos_ids[r]``: per-slot eos token (-1 = none). The eos token itself
      is not emitted (mirrors the host-side scheduler semantics).
    - Dead slots keep running (lax.scan needs static shapes) but their
      cache writes are redirected to the reserved ``dummy_block`` and
      their outputs masked out of ``emits``.

    Sampling folds ``steps0 + t`` into each slot's own PRNG stream, so a
    request's tokens stay a pure function of (params, prompt, seed) —
    bit-identical whether decoded one token or K tokens per dispatch.

    Memory-access structure (the perf-critical part, measured on v5e):
    dynamic scatters into the block pool cost ~60µs each on TPU, so the
    naive per-step write (2 per layer per step) burns ~1.5 ms/step.
    Instead the chunk's fresh K/V accumulates in a small *side buffer*
    [L, R, K, Hkv, hd] (dynamic_update_slice at step index — cheap), each
    step's attention reads ``gather(pool) masked < cl0`` concatenated
    with ``side masked <= t``, and the whole side buffer scatters into
    the pool in ONE op after the scan. The pool is loop-invariant during
    the chunk, which is what makes the split exact.

    tokens: [R] last emitted token per slot; steps0: [R] tokens emitted so
    far. Returns (toks [K, R] int32, emits [K, R] bool, new paged); the
    emitted tokens of slot r are ``toks[:emits[:, r].sum(), r]``.
    """
    from distributed_llm_inferencing_tpu.ops.attention import attend
    from distributed_llm_inferencing_tpu.ops.paged_kvcache import (
        PagedKVCache, gather_seq)
    from distributed_llm_inferencing_tpu.ops.sampling import sample_batch

    from distributed_llm_inferencing_tpu.ops.pallas import fused_decode
    if (_cfg_backend(cfg, jax.device_count(),
                     op="paged").startswith("pallas")
            or fused_decode.eligible(cfg, paged.quantized)):
        # explicit pallas request (A/B and debug escape hatch) or the
        # fused decode kernel (DLI_FUSED_DECODE): the side-buffer
        # formulation below bypasses the paged/fused kernels, so run the
        # stepwise write+attend loop that dispatches to them instead
        return _paged_decode_chunk_stepwise(
            params, cfg, k, tokens, paged, block_tables, context_lens,
            seeds, steps0, temps, tks, tps, ds, budget, eos_ids,
            dummy_block, lora_ids=lora_ids)

    r = tokens.shape[0]
    L = cfg.num_layers
    bs = paged.block_size
    mb = block_tables.shape[1]
    dt = jnp.dtype(cfg.dtype)             # compute dtype (pool may be int8)
    quantized = paged.quantized
    cl0 = context_lens                    # pool horizon, fixed this chunk
    pool_pos = jnp.broadcast_to(jnp.arange(mb * bs, dtype=jnp.int32),
                                (r, mb * bs))
    pool_valid = pool_pos < cl0[:, None]
    side_pos = cl0[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]
    side0 = jnp.zeros((L, r, k, cfg.num_kv_heads, cfg.head_dim), dt)

    # Pool K/V is loop-invariant: gather it ONCE for the whole chunk when
    # the materialization is modest; at long contexts fall back to a
    # per-step per-layer gather (transient, one layer at a time).
    gathered_bytes = 2 * dt.itemsize * L * r * mb * bs \
        * cfg.num_kv_heads * cfg.head_dim
    pre = gathered_bytes <= _PREGATHER_MAX_BYTES
    if pre:
        shape = (L, r, mb * bs, cfg.num_kv_heads, cfg.head_dim)
        pool_k = paged.k[:, block_tables].reshape(shape)
        pool_v = paged.v[:, block_tables].reshape(shape)
        if quantized:
            from distributed_llm_inferencing_tpu.ops.kvcache import dequant_kv
            pool_k = dequant_kv(
                pool_k, paged.k_scale[:, block_tables].reshape(shape[:-1]),
                dt)
            pool_v = dequant_kv(
                pool_v, paged.v_scale[:, block_tables].reshape(shape[:-1]),
                dt)
    else:
        pool_k, pool_v = paged.k, paged.v   # gathered per layer in-loop

    def body(carry, t):
        cur, side_k, side_v, cl, alive = carry
        q_pos = jnp.where(alive, cl, 0)[:, None]
        x = embed(params, cfg, cur[:, None], q_pos)
        # monotone aliveness: a slot alive at t wrote at every i <= t, so
        # the step-index mask alone is exact for rows that matter
        side_valid = jnp.broadcast_to(
            jnp.arange(k, dtype=jnp.int32)[None, :] <= t, (r, k))

        def make_layer(seg_cfg):
            def layer(x, layer_in):
                if pre:
                    lp, sk, sv, kp, vp = layer_in
                elif quantized:
                    from distributed_llm_inferencing_tpu.ops.kvcache import (
                        dequant_kv)
                    lp, sk, sv, ck, cv, cks, cvs = layer_in
                    kp = dequant_kv(gather_seq(ck, block_tables),
                                    gather_seq(cks, block_tables), dt)
                    vp = dequant_kv(gather_seq(cv, block_tables),
                                    gather_seq(cvs, block_tables), dt)
                else:
                    lp, sk, sv, ck, cv = layer_in
                    kp, vp = gather_seq(ck, block_tables), gather_seq(
                        cv, block_tables)

                def attend_write(q, kh, vh):
                    sk2 = jax.lax.dynamic_update_slice(sk, kh.astype(dt),
                                                       (0, t, 0, 0))
                    sv2 = jax.lax.dynamic_update_slice(sv, vh.astype(dt),
                                                       (0, t, 0, 0))
                    attn = attend(
                        q,
                        jnp.concatenate([kp, sk2], axis=1),
                        jnp.concatenate([vp, sv2], axis=1),
                        q_pos,
                        jnp.concatenate([pool_pos, side_pos], axis=1),
                        jnp.concatenate([pool_valid, side_valid], axis=1),
                        sliding_window=_layer_window(seg_cfg, lp),
                        alibi=_alibi(seg_cfg), softcap=seg_cfg.attn_softcap,
                        sinks=_sinks(seg_cfg, lp))
                    return attn, (sk2, sv2)

                x, (sk2, sv2) = _block_body(x, lp, seg_cfg, q_pos,
                                            attend_write,
                                            lora_ids=lora_ids)
                return x, (sk2, sv2)
            return layer

        xs = (side_k, side_v, pool_k, pool_v)
        if quantized and not pre:
            xs = xs + (paged.k_scale, paged.v_scale)
        x2, (side_k, side_v) = scan_layer_stack(make_layer, x, params, cfg,
                                                xs)
        logits = unembed(params, cfg, x2)[:, 0]
        nxt = sample_batch(logits, seeds, steps0 + t, temps, tks, tps, ds)
        is_eos = alive & (eos_ids >= 0) & (nxt == eos_ids)
        emit = alive & ~is_eos
        new_cl = cl + alive.astype(cl.dtype)   # advance iff wrote this step
        new_alive = emit & (t + 1 < budget)
        return (nxt, side_k, side_v, new_cl, new_alive), (nxt, emit, alive)

    (_, side_k, side_v, _, _), (toks, emits, wrote) = jax.lax.scan(
        body, (tokens, side0, side0, context_lens, budget > 0),
        jnp.arange(k, dtype=jnp.int32))

    # ONE scatter of the whole chunk's K/V into the pool (never-written
    # steps of dead/inactive slots land in the reserved dummy block)
    pos = cl0[None, :] + jnp.arange(k, dtype=jnp.int32)[:, None]   # [K, R]
    blk = jnp.take_along_axis(block_tables,
                              jnp.swapaxes(pos // bs, 0, 1), axis=1)
    blk = jnp.where(wrote, jnp.swapaxes(blk, 0, 1), dummy_block)   # [K, R]
    off = pos % bs
    if quantized:
        from distributed_llm_inferencing_tpu.ops.kvcache import quant_kv
        k8, ks = quant_kv(side_k)
        v8, vs = quant_kv(side_v)
        return toks, emits, PagedKVCache(
            k=paged.k.at[:, blk, off].set(jnp.swapaxes(k8, 1, 2)),
            v=paged.v.at[:, blk, off].set(jnp.swapaxes(v8, 1, 2)),
            k_scale=paged.k_scale.at[:, blk, off].set(
                jnp.swapaxes(ks, 1, 2)),
            v_scale=paged.v_scale.at[:, blk, off].set(
                jnp.swapaxes(vs, 1, 2)))
    new_k = paged.k.at[:, blk, off].set(jnp.swapaxes(side_k, 1, 2))
    new_v = paged.v.at[:, blk, off].set(jnp.swapaxes(side_v, 1, 2))
    return toks, emits, PagedKVCache(k=new_k, v=new_v)


def _paged_decode_chunk_stepwise(params, cfg: ModelConfig, k: int, tokens,
                                 paged, block_tables, context_lens, seeds,
                                 steps0, temps, tks, tps, ds, budget,
                                 eos_ids, dummy_block: int, lora_ids=None):
    """K decode steps via per-step ``paged_decode_step`` (pool writes and
    the backend-dispatched paged attention every step). Semantically
    identical to the side-buffer formulation in ``paged_decode_chunk``;
    used when an explicit pallas backend is requested so the paged kernel
    actually runs."""
    from distributed_llm_inferencing_tpu.ops.sampling import sample_batch

    def body(carry, t):
        cur, paged, cl, alive = carry
        bt_eff = jnp.where(alive[:, None], block_tables, dummy_block)
        cl_eff = jnp.where(alive, cl, 0)
        logits, paged = paged_decode_step(params, cfg, cur, paged, bt_eff,
                                          cl_eff, lora_ids=lora_ids)
        nxt = sample_batch(logits, seeds, steps0 + t, temps, tks, tps, ds)
        is_eos = alive & (eos_ids >= 0) & (nxt == eos_ids)
        emit = alive & ~is_eos
        new_cl = cl + alive.astype(cl.dtype)
        new_alive = emit & (t + 1 < budget)
        return (nxt, paged, new_cl, new_alive), (nxt, emit)

    (_, paged, _, _), (toks, emits) = jax.lax.scan(
        body, (tokens, paged, context_lens, budget > 0),
        jnp.arange(k, dtype=jnp.int32))
    return toks, emits, paged


def paged_speculative_chunk(params, cfg: ModelConfig, k: int, gamma: int,
                            tokens, history, paged, block_tables,
                            context_lens, seeds, steps0, temps, tks, tps,
                            ds, budget, eos_ids, dummy_block: int,
                            gammas=None, lora_ids=None):
    """K speculative iterations on device for R serving slots: draft
    gamma tokens per slot by on-device prompt lookup
    (ops/speculative.py propose_ngram_device), score [cur, drafts] in one
    forward block, and keep the prefix the target distribution agrees
    with — up to gamma+1 tokens per slot per iteration, still one host
    sync per chunk.

    The engine's speculative path (ops/speculative.py verify_step) hands
    drafting to the host between steps; behind a dispatch round trip that
    forfeits the entire speedup, so here the token history rides in a
    device buffer and drafting is a compare/gather inside the scan.

    Acceptance (ops/speculative.py accept_rejection_batch): greedy rows
    (``~ds``) accept drafts matching the raw argmax — output is
    bit-identical to plain greedy decode, only faster. Sampling rows run
    exact per-row data-parameterized leave-one-out rejection against the
    warped distribution ``sample_batch`` draws from — the emitted
    distribution is preserved exactly while accepted drafts compress
    iterations, so serving-default do_sample requests speed up too.
    (Rows whose top_k exceeds sampling.PREFIX_K — no realistic serving
    config — fall back to one bit-identical sample per iteration.)

    Cache bookkeeping (the subtle part): every iteration writes K/V for
    all gamma+1 scored tokens into a side buffer at a STATIC offset
    ``t*(gamma+1)`` (dynamic_update_slice — no scatters in the loop),
    with each entry's absolute position recorded in ``side_pos``.
    Rejected entries' positions get re-written by later iterations, so
    validity cannot be position-derived: an ``accepted`` mask carry
    marks entries committed at their own iteration (entry i of the
    block is committed iff i <= n_acc — entry 0 is ``cur``, whose
    position was already owed to the cache). Attention at iteration t
    sees pool(< cl0) + accepted side entries + the current block
    (causally masked); the single post-scan pool scatter writes exactly
    the accepted entries, everything else landing in ``dummy_block``.

    tokens: [R] current token per slot (emitted, not yet cached);
    history: [R, H] all known tokens per slot (prompt + emitted; row r
    valid to context_lens[r] + 1). Block tables must cover
    ``context_lens + k*(gamma+1)`` growth.

    ``gammas`` ([R] int32 in [0, gamma], default gamma) is the per-slot
    draft WIDTH for wave-level speculation: ``gamma`` stays the compiled
    program's static maximum (one compiled program per chunk shape
    regardless of the wave's width mix) while each slot's effective
    width rides as data (ops/speculative.py accept_rejection_batch
    ``widths``). A gamma-0 slot accepts no drafts and emits exactly one
    plain-decode token per iteration — it rides the shared verify pass
    instead of forcing a wave-wide fallback; its gamma_max draft entries
    still occupy (dummy-targeted) scratch, the price of the uniform
    program shape.

    Returns (toks [K, R, gamma+1], keeps [K, R], eos_seen [K, R],
    new paged): iteration t of slot r emitted ``toks[t, r, :keeps[t,r]]``;
    ``eos_seen`` is cumulative per row, so the host can distinguish an
    eos death from simply running out of iterations (1 token/iteration
    when every draft misses covers less than the chunk's token budget).
    """
    from distributed_llm_inferencing_tpu.ops.attention import attend
    from distributed_llm_inferencing_tpu.ops.paged_kvcache import (
        PagedKVCache)
    from distributed_llm_inferencing_tpu.ops.speculative import (
        accept_rejection_batch, propose_ngram_device)

    r = tokens.shape[0]
    L = cfg.num_layers
    bs = paged.block_size
    mb = block_tables.shape[1]
    g1 = gamma + 1
    E = k * g1                       # side-buffer entries per slot
    dt = jnp.dtype(cfg.dtype)
    quantized = paged.quantized
    cl0 = context_lens
    H = history.shape[1]

    pool_pos = jnp.broadcast_to(jnp.arange(mb * bs, dtype=jnp.int32),
                                (r, mb * bs))
    pool_valid = pool_pos < cl0[:, None]
    side0 = jnp.zeros((L, r, E, cfg.num_kv_heads, cfg.head_dim), dt)
    entry_step = jnp.arange(E, dtype=jnp.int32) // g1               # [E]

    gathered_bytes = 2 * dt.itemsize * L * r * mb * bs \
        * cfg.num_kv_heads * cfg.head_dim
    pre = gathered_bytes <= _PREGATHER_MAX_BYTES
    if pre:
        shape = (L, r, mb * bs, cfg.num_kv_heads, cfg.head_dim)
        pool_k = paged.k[:, block_tables].reshape(shape)
        pool_v = paged.v[:, block_tables].reshape(shape)
        if quantized:
            from distributed_llm_inferencing_tpu.ops.kvcache import dequant_kv
            pool_k = dequant_kv(
                pool_k, paged.k_scale[:, block_tables].reshape(shape[:-1]),
                dt)
            pool_v = dequant_kv(
                pool_v, paged.v_scale[:, block_tables].reshape(shape[:-1]),
                dt)
    else:
        from distributed_llm_inferencing_tpu.ops.paged_kvcache import (
            gather_seq)
        pool_k, pool_v = paged.k, paged.v   # gathered per layer in-loop

    def body(carry, t):
        (cur, hist, hist_len, side_k, side_v, side_pos, acc_mask, cl,
         emitted, alive, eos_seen) = carry
        qp0 = jnp.where(alive, cl, 0)
        qp = qp0[:, None] + jnp.arange(g1, dtype=jnp.int32)[None, :]
        drafts, _ = propose_ngram_device(hist, hist_len, gamma)
        toks_in = jnp.concatenate([cur[:, None], drafts], axis=1)  # [R, g1]
        x = embed(params, cfg, toks_in, qp)

        side_pos = jax.lax.dynamic_update_slice(side_pos, qp, (0, t * g1))
        is_cur_block = jnp.broadcast_to(entry_step == t, (r, E))
        side_valid = acc_mask | is_cur_block

        def make_layer(seg_cfg):
            def layer(x, layer_in):
                if pre:
                    lp, sk, sv, kp, vp = layer_in
                elif quantized:
                    from distributed_llm_inferencing_tpu.ops.kvcache import (
                        dequant_kv)
                    lp, sk, sv, ck, cv, cks, cvs = layer_in
                    kp = dequant_kv(gather_seq(ck, block_tables),
                                    gather_seq(cks, block_tables), dt)
                    vp = dequant_kv(gather_seq(cv, block_tables),
                                    gather_seq(cvs, block_tables), dt)
                else:
                    lp, sk, sv, ck, cv = layer_in
                    kp, vp = gather_seq(ck, block_tables), gather_seq(
                        cv, block_tables)

                def attend_write(q, kh, vh):
                    sk2 = jax.lax.dynamic_update_slice(sk, kh.astype(dt),
                                                       (0, t * g1, 0, 0))
                    sv2 = jax.lax.dynamic_update_slice(sv, vh.astype(dt),
                                                       (0, t * g1, 0, 0))
                    attn = attend(
                        q,
                        jnp.concatenate([kp, sk2], axis=1),
                        jnp.concatenate([vp, sv2], axis=1),
                        qp,
                        jnp.concatenate([pool_pos, side_pos], axis=1),
                        jnp.concatenate([pool_valid, side_valid], axis=1),
                        sliding_window=_layer_window(seg_cfg, lp),
                        alibi=_alibi(seg_cfg), softcap=seg_cfg.attn_softcap,
                        sinks=_sinks(seg_cfg, lp))
                    return attn, (sk2, sv2)

                x, (sk2, sv2) = _block_body(x, lp, seg_cfg, qp,
                                            attend_write,
                                            lora_ids=lora_ids)
                return x, (sk2, sv2)
            return layer

        xs = (side_k, side_v, pool_k, pool_v)
        if quantized and not pre:
            xs = xs + (paged.k_scale, paged.v_scale)
        x2, (side_k, side_v) = scan_layer_stack(make_layer, x, params, cfg,
                                                xs)
        logits = unembed(params, cfg, x2)                 # [R, g1, V] f32

        # per-row acceptance (ops/speculative.py): greedy rows accept
        # argmax-matching drafts (bit-identical to plain greedy decode);
        # sampled rows run exact leave-one-out rejection against the same
        # warped distribution sample_batch draws from — real speedups for
        # do_sample requests with the target distribution preserved
        toks_out, n_emit = accept_rejection_batch(
            logits, drafts, seeds, steps0 + emitted, temps, tks, tps, ds,
            widths=gammas)
        idx = jnp.arange(g1, dtype=jnp.int32)[None, :]

        # eos / budget clamping
        emit_sl = idx < n_emit[:, None]
        is_eos = (toks_out == eos_ids[:, None]) & (eos_ids >= 0)[:, None] \
            & emit_sl
        eos_pos = jnp.min(jnp.where(is_eos, idx, g1), axis=1)     # [R]
        rem = budget - emitted
        n_keep = jnp.minimum(jnp.minimum(n_emit, eos_pos), rem)
        n_keep = jnp.where(alive, n_keep, 0)
        # an eos "happened" only if plain decode would have reached it
        # inside this chunk's budget — when the budget clamp cut the run
        # first, the slot must survive and re-derive the tail next chunk
        hit_eos = (eos_pos < n_emit) & (eos_pos < rem)

        # commit: entry i of this block is cache-valid iff i < n_keep
        # (entry 0 = cur at position cl; kept emitted tokens cover
        # positions cl+1..cl+n_keep-1 whose KV is entries 1..n_keep-1;
        # the LAST kept token becomes next cur, its KV unwritten) — and
        # for fully-kept rows entry n_acc's draft was accepted too, so
        # commit i <= min(n_acc, n_keep-1)... conservatively i < n_keep
        # plus entry 0 for alive rows.
        commit = (idx < n_keep[:, None]) | ((idx == 0) & alive[:, None])
        acc_mask = jax.lax.dynamic_update_slice(
            acc_mask, commit, (0, t * g1))

        # history append: kept tokens at h[cl+1 .. cl+n_keep]
        rows = jnp.broadcast_to(jnp.arange(r)[:, None], (r, g1))
        cols = jnp.where(emit_sl & (idx < n_keep[:, None]),
                         cl[:, None] + 1 + idx, H)   # H -> dropped
        hist = hist.at[rows, cols].set(toks_out, mode="drop")
        hist_len = hist_len + n_keep

        new_cl = cl + n_keep
        emitted2 = emitted + n_keep
        eos_seen2 = eos_seen | (hit_eos & alive)
        new_alive = alive & ~hit_eos & (emitted2 < budget)
        new_cur = jnp.where(
            n_keep > 0,
            jnp.take_along_axis(
                toks_out, jnp.maximum(n_keep - 1, 0)[:, None], axis=1)[:, 0],
            cur)
        return ((new_cur, hist, hist_len, side_k, side_v, side_pos,
                 acc_mask, new_cl, emitted2, new_alive, eos_seen2),
                (toks_out, n_keep, eos_seen2))

    hist_len0 = cl0 + 1
    carry0 = (tokens, history, hist_len0, side0, side0,
              jnp.zeros((r, E), jnp.int32), jnp.zeros((r, E), bool),
              cl0, jnp.zeros((r,), jnp.int32), budget > 0,
              jnp.zeros((r,), bool))
    (_, _, _, side_k, side_v, side_pos, acc_mask, _, _, _, _), \
        (toks, keeps, eos_seen) = jax.lax.scan(
            body, carry0, jnp.arange(k, dtype=jnp.int32))

    # single pool scatter of the accepted side entries
    blk = jnp.take_along_axis(block_tables, side_pos // bs, axis=1)  # [R, E]
    blk = jnp.where(acc_mask, blk, dummy_block)
    off = side_pos % bs
    if quantized:
        from distributed_llm_inferencing_tpu.ops.kvcache import quant_kv
        k8, ks = quant_kv(side_k)
        v8, vs = quant_kv(side_v)
        paged = PagedKVCache(
            k=paged.k.at[:, blk, off].set(k8),
            v=paged.v.at[:, blk, off].set(v8),
            k_scale=paged.k_scale.at[:, blk, off].set(ks),
            v_scale=paged.v_scale.at[:, blk, off].set(vs))
    else:
        paged = PagedKVCache(k=paged.k.at[:, blk, off].set(side_k),
                             v=paged.v.at[:, blk, off].set(side_v))
    return toks, keeps, eos_seen, paged


def paged_prefill_tail(params, cfg: ModelConfig, tokens, tail_len,
                       tail_blocks, prefix_blocks, prefix_len, paged,
                       lora_ids=None):
    """Prefill a WAVE of prompt tails into paged blocks, each attending its
    own cached prefix.

    Each row's prefix (``prefix_len[b]`` tokens in ``prefix_blocks[b]``, a
    radix-cache hit) is NOT recomputed — its K/V is gathered from shared
    blocks per layer. Fresh tail K/V is scattered into ``tail_blocks``.
    Batching admissions into one program is what keeps burst TTFT at one
    dispatch round trip instead of one per queued request (the reference
    served admissions fully serialized, worker/app.py:252-330).

    tokens: [B, T] right-padded tails (T a multiple of block_size);
    tail_len: [B] real tail tokens (>= 1; padding rows use 1);
    tail_blocks: [B, T // bs] int32 (padding rows all-dummy; legacy
    unbatched [T // bs] accepted when B == 1);
    prefix_blocks: [B, PB] (dummy-padded); prefix_len: [B].
    Returns (last-token logits [B, V] f32, new paged).
    """
    from distributed_llm_inferencing_tpu.ops.paged_kvcache import (
        PagedKVCache, paged_attend_prefix, write_block_run)
    b, t = tokens.shape
    if tail_blocks.ndim == 1:
        tail_blocks = tail_blocks[None]
    if tail_blocks.shape[0] != b:
        raise ValueError(
            f"tail_blocks batch {tail_blocks.shape[0]} != tokens batch {b}")
    q_pos = prefix_len[:, None] + jnp.broadcast_to(
        jnp.arange(t, dtype=jnp.int32), (b, t))
    tail_valid = jnp.arange(t, dtype=jnp.int32)[None, :] < tail_len[:, None]
    x = embed(params, cfg, tokens, q_pos)
    quantized = paged.quantized

    def make_body(seg_cfg):
        def body(x, layer_in):
            lp, ck, cv, *scales = layer_in

            def attend_write(q, k, v):
                if quantized:
                    # store int8 + scales; the tail attends its own fresh
                    # bf16 K/V plus the dequantized cached prefix
                    from distributed_llm_inferencing_tpu.ops.kvcache import (
                        quant_kv)
                    cks, cvs = scales
                    k8, ks = quant_kv(k)
                    v8, vs = quant_kv(v)
                    nk = write_block_run(ck, k8, tail_blocks)
                    nv = write_block_run(cv, v8, tail_blocks)
                    nks = write_block_run(cks, ks, tail_blocks)
                    nvs = write_block_run(cvs, vs, tail_blocks)
                    attn = paged_attend_prefix(
                        q, k, v, nk, nv, prefix_blocks, prefix_len, q_pos,
                        tail_valid,
                        sliding_window=_layer_window(seg_cfg, lp),
                        k_scale_layer=nks, v_scale_layer=nvs,
                        alibi=_alibi(seg_cfg), softcap=seg_cfg.attn_softcap,
                        sinks=_sinks(seg_cfg, lp))
                    return attn, (nk, nv, nks, nvs)
                nk = write_block_run(ck, k, tail_blocks)
                nv = write_block_run(cv, v, tail_blocks)
                attn = paged_attend_prefix(
                    q, k, v, nk, nv, prefix_blocks, prefix_len, q_pos,
                    tail_valid, sliding_window=_layer_window(seg_cfg, lp),
                    alibi=_alibi(seg_cfg), softcap=seg_cfg.attn_softcap,
                    sinks=_sinks(seg_cfg, lp))
                return attn, (nk, nv)

            return _block_body(x, lp, seg_cfg, q_pos, attend_write,
                               lora_ids=lora_ids)
        return body

    xs = (paged.k, paged.v) + (
        (paged.k_scale, paged.v_scale) if quantized else ())
    x, cache_out = scan_layer_stack(make_body, x, params, cfg, xs)
    new_paged = PagedKVCache(*cache_out)
    # project only the last real position through the vocab head ([D,V] over
    # one row per sequence, not T padded rows)
    last_x = jnp.take_along_axis(
        x, jnp.maximum(tail_len - 1, 0)[:, None, None].astype(jnp.int32),
        axis=1)                                         # [B, 1, D]
    last = unembed(params, cfg, last_x)[:, 0]           # [B, V]
    return last, new_paged
