"""Multi-LoRA adapter format, host store, and device pack building.

An adapter is a set of per-layer low-rank (A, B) pairs for the attention
and MLP projections of a BASE model — rank r in the tens against hidden
sizes in the thousands, so thousands of tenant fine-tunes fit where one
extra dense copy would not. Three layers of machinery live here:

- **Format**: ``LoRAAdapter`` (host numpy), loadable from a checkpoint
  directory (``lora_config.json`` + ``lora.npz``) or synthesized for
  tests/benches (``synthesize`` — deterministic in (cfg, name, seed)).
- **Host store**: ``LoRAHostStore``, a bounded LRU-by-bytes tier
  (``DLI_LORA_HOST_MB``) mirroring the HostKVArena discipline —
  occupancy/hit/eviction accounting, never evicting adapters pinned to
  device slots.
- **Device pack**: ``build_pack`` stacks up to S resident adapters into
  ``[L, S, din, rmax]`` / ``[L, S, rmax, dout]`` arrays per projection.
  Slot 0 is the base model (all zeros — an exact-zero delta), ranks are
  zero-padded to ``rmax`` (padding rows of A contribute nothing), and
  the ``alpha / rank`` scale is folded into B — so the serving delta
  (ops/lora.py gathered_delta) is two einsums with a STATIC shape:
  loading, evicting, or re-mixing adapters changes pack DATA, never the
  compiled program.

Model classes whose projection layout the delta hook does not cover —
MLA (latent-bottleneck attention), MoE (expert-stacked MLP), DeepSeek
dense-prefix hybrids — are rejected at load time: a request must fail
loudly rather than silently serve base weights.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from distributed_llm_inferencing_tpu.models.config import ModelConfig
from distributed_llm_inferencing_tpu.utils import locks

# serving defaults; the DLI_LORA_* knobs (utils/knobs.py) override them
DEFAULT_HOST_MB = 64.0     # DLI_LORA_HOST_MB
DEFAULT_SLOTS = 4          # DLI_LORA_SLOTS (device-resident adapters)
DEFAULT_MAX_RANK = 16      # DLI_LORA_MAX_RANK (pack's static rmax)


def host_mb_from_env() -> float:
    try:
        return float(os.environ.get("DLI_LORA_HOST_MB", DEFAULT_HOST_MB))
    except ValueError:
        return DEFAULT_HOST_MB


def slots_from_env() -> int:
    try:
        return max(1, int(os.environ.get("DLI_LORA_SLOTS", DEFAULT_SLOTS)))
    except ValueError:
        return DEFAULT_SLOTS


def max_rank_from_env() -> int:
    try:
        return max(1, int(os.environ.get("DLI_LORA_MAX_RANK",
                                         DEFAULT_MAX_RANK)))
    except ValueError:
        return DEFAULT_MAX_RANK


def validate_base_model(cfg: ModelConfig):
    """Refuse model classes the delta hook does not cover. Raising here
    (load time) is what keeps the hard rule — a request NEVER silently
    serves base weights — cheap to enforce everywhere downstream."""
    if cfg.mla:
        raise ValueError(
            "LoRA serving does not support MLA attention (the latent "
            "bottleneck replaces the q/k/v projections the delta targets)")
    if cfg.num_experts > 0:
        raise ValueError(
            "LoRA serving does not support MoE MLPs (expert-stacked "
            "weights need a routed delta formulation)")
    if getattr(cfg, "dense_prefix_layers", 0):
        raise ValueError(
            "LoRA serving does not support dense-prefix hybrid stacks "
            "(two layer segments would need two packs)")


def lora_targets(cfg: ModelConfig) -> Tuple[str, ...]:
    """The projections an adapter may target for this architecture."""
    base = ("q", "k", "v", "o", "up", "down")
    return base + ("gate",) if cfg.gated_mlp else base


def target_dims(cfg: ModelConfig, target: str) -> Tuple[int, int]:
    """(din, dout) of the dense projection ``target`` adapts."""
    h, hd = cfg.hidden_size, cfg.head_dim
    dims = {
        "q": (h, cfg.num_heads * hd),
        "k": (h, cfg.num_kv_heads * hd),
        "v": (h, cfg.num_kv_heads * hd),
        "o": (cfg.num_heads * hd, h),
        "gate": (h, cfg.intermediate_size),
        "up": (h, cfg.intermediate_size),
        "down": (cfg.intermediate_size, h),
    }
    if target not in dims or target not in lora_targets(cfg):
        raise ValueError(f"unknown LoRA target {target!r}")
    return dims[target]


@dataclasses.dataclass
class LoRAAdapter:
    """One adapter: per-layer {target: (A [din, r], B [r, dout])} in
    float32 host numpy, plus the metadata routing/packing needs."""
    name: str
    rank: int
    alpha: float
    targets: Tuple[str, ...]
    layers: List[Dict[str, Tuple[np.ndarray, np.ndarray]]]
    nbytes: int = 0

    def __post_init__(self):
        if not self.nbytes:
            self.nbytes = sum(a.nbytes + b.nbytes
                              for lp in self.layers
                              for (a, b) in lp.values())

    @property
    def scale(self) -> float:
        return self.alpha / float(self.rank)


def _check_adapter(cfg: ModelConfig, ad: LoRAAdapter,
                   max_rank: Optional[int] = None) -> LoRAAdapter:
    validate_base_model(cfg)
    cap = max_rank or max_rank_from_env()
    if ad.rank < 1 or ad.rank > cap:
        raise ValueError(f"adapter {ad.name!r} rank {ad.rank} outside "
                         f"[1, {cap}] (DLI_LORA_MAX_RANK)")
    if len(ad.layers) != cfg.num_layers:
        raise ValueError(f"adapter {ad.name!r} has {len(ad.layers)} "
                         f"layers, model has {cfg.num_layers}")
    ok = set(lora_targets(cfg))
    for li, lp in enumerate(ad.layers):
        for t, (a, b) in lp.items():
            if t not in ok:
                raise ValueError(f"adapter {ad.name!r} targets {t!r}, "
                                 f"not a projection of {cfg.name}")
            din, dout = target_dims(cfg, t)
            if a.shape != (din, ad.rank) or b.shape != (ad.rank, dout):
                raise ValueError(
                    f"adapter {ad.name!r} layer {li} target {t!r}: "
                    f"A{a.shape}/B{b.shape} do not match "
                    f"({din}, {ad.rank})/({ad.rank}, {dout})")
    return ad


def synthesize(cfg: ModelConfig, name: str, rank: int = 8,
               alpha: Optional[float] = None, seed: int = 0,
               scale: float = 0.05,
               targets: Optional[Tuple[str, ...]] = None) -> LoRAAdapter:
    """Deterministic test/bench adapter: both A and B non-zero (real
    checkpoints zero-init B; a zero delta would make every differential
    test vacuous), small enough that greedy decoding stays stable."""
    validate_base_model(cfg)
    targets = tuple(targets or lora_targets(cfg))
    rng = np.random.default_rng(
        np.random.SeedSequence([seed, len(name)]
                               + [ord(c) for c in name[:16]]))
    layers = []
    for _ in range(cfg.num_layers):
        lp = {}
        for t in targets:
            din, dout = target_dims(cfg, t)
            a = rng.standard_normal((din, rank)).astype(np.float32)
            a *= scale / np.sqrt(din)
            b = rng.standard_normal((rank, dout)).astype(np.float32)
            b *= scale / np.sqrt(rank)
            lp[t] = (a, b)
        layers.append(lp)
    ad = LoRAAdapter(name=name, rank=rank,
                     alpha=float(alpha if alpha is not None else rank),
                     targets=targets, layers=layers)
    return _check_adapter(cfg, ad)


def save_adapter(ad: LoRAAdapter, path: str):
    """Checkpoint-directory format: lora_config.json + lora.npz with
    ``{layer}.{target}.a/.b`` keys — load_adapter's inverse."""
    os.makedirs(path, exist_ok=True)
    arrays = {}
    for li, lp in enumerate(ad.layers):
        for t, (a, b) in lp.items():
            arrays[f"{li}.{t}.a"] = a
            arrays[f"{li}.{t}.b"] = b
    np.savez(os.path.join(path, "lora.npz"), **arrays)
    with open(os.path.join(path, "lora_config.json"), "w") as f:
        json.dump({"name": ad.name, "rank": ad.rank, "alpha": ad.alpha,
                   "targets": list(ad.targets),
                   "num_layers": len(ad.layers)}, f)


def load_adapter(cfg: ModelConfig, name: str, source: str,
                 max_rank: Optional[int] = None) -> LoRAAdapter:
    """Load one adapter from a checkpoint directory and validate it
    against the base model's shapes. Any problem raises ValueError —
    the caller turns that into a structured 400 / failed request."""
    cfg_path = os.path.join(source, "lora_config.json")
    npz_path = os.path.join(source, "lora.npz")
    if not (os.path.isfile(cfg_path) and os.path.isfile(npz_path)):
        raise ValueError(f"adapter {name!r}: {source!r} is not a LoRA "
                         "checkpoint dir (lora_config.json + lora.npz)")
    with open(cfg_path) as f:
        meta = json.load(f)
    data = np.load(npz_path)
    layers: List[Dict[str, Tuple[np.ndarray, np.ndarray]]] = []
    for li in range(int(meta["num_layers"])):
        lp = {}
        for t in meta["targets"]:
            lp[t] = (np.asarray(data[f"{li}.{t}.a"], np.float32),
                     np.asarray(data[f"{li}.{t}.b"], np.float32))
        layers.append(lp)
    ad = LoRAAdapter(name=name, rank=int(meta["rank"]),
                     alpha=float(meta.get("alpha", meta["rank"])),
                     targets=tuple(meta["targets"]), layers=layers)
    return _check_adapter(cfg, ad, max_rank=max_rank)


def resolve(cfg: ModelConfig, name: str, source: str,
            max_rank: Optional[int] = None) -> LoRAAdapter:
    """Turn a registry ``source`` into a validated adapter: either a
    ``synth:`` URI (``synth:rank=8,seed=3,scale=0.05`` — the bench/test
    path, deterministic in (cfg, name, params)) or a checkpoint
    directory for ``load_adapter``. ValueError on any problem."""
    if source == "synth" or source.startswith("synth:"):
        kw = {}
        spec = source.partition(":")[2]
        for part in filter(None, spec.split(",")):
            k, _, v = part.partition("=")
            if k not in ("rank", "seed", "alpha", "scale"):
                raise ValueError(
                    f"adapter {name!r}: unknown synth param {k!r}")
            kw[k] = float(v) if k in ("alpha", "scale") else int(v)
        return _check_adapter(cfg, synthesize(cfg, name, **kw),
                              max_rank=max_rank)
    return load_adapter(cfg, name, source, max_rank=max_rank)


def build_pack(cfg: ModelConfig, slot_adapters: List[Optional[LoRAAdapter]],
               max_rank: int) -> Dict[str, Dict[str, np.ndarray]]:
    """Stack slot adapters into the device pack: for every target,
    ``{"a": [L, S, din, rmax], "b": [L, S, rmax, dout]}`` float32.
    ``slot_adapters[0]`` must be None (the base model's zero slot);
    empty slots and un-targeted projections are zeros. The alpha/rank
    scale is folded into B here so the hot path never multiplies it."""
    S, L = len(slot_adapters), cfg.num_layers
    pack: Dict[str, Dict[str, np.ndarray]] = {}
    for t in lora_targets(cfg):
        din, dout = target_dims(cfg, t)
        pack[t] = {"a": np.zeros((L, S, din, max_rank), np.float32),
                   "b": np.zeros((L, S, max_rank, dout), np.float32)}
    for s, ad in enumerate(slot_adapters):
        if ad is None:
            continue
        if s == 0:
            raise ValueError("slot 0 is reserved for the base model")
        for li, lp in enumerate(ad.layers):
            for t, (a, b) in lp.items():
                pack[t]["a"][li, s, :, :ad.rank] = a
                pack[t]["b"][li, s, :ad.rank, :] = b * ad.scale
    return pack


class LoRAHostStore:
    """Bounded host-RAM adapter tier: LRU by bytes, HostKVArena
    discipline (runtime/kvtier.py) — occupancy + hit/miss/eviction
    counters, oldest-first eviction under ``put`` pressure, and a
    caller-supplied pinned set (device-slotted adapters with live
    requests) that eviction must skip. A put that cannot fit even
    after evicting every unpinned adapter raises ValueError."""

    def __init__(self, capacity_mb: Optional[float] = None):
        if capacity_mb is None:
            capacity_mb = host_mb_from_env()
        self.capacity_bytes = int(max(0.0, float(capacity_mb)) * 2**20)
        self._adapters: "collections.OrderedDict[str, LoRAAdapter]" = \
            collections.OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._lock = locks.lock("lora.host_store")

    def get(self, name: str) -> Optional[LoRAAdapter]:
        with self._lock:
            ad = self._adapters.get(name)
            if ad is None:
                self.misses += 1
                return None
            self._adapters.move_to_end(name)
            self.hits += 1
            return ad

    def peek(self, name: str) -> Optional[LoRAAdapter]:
        """Lookup WITHOUT touching recency or hit/miss accounting — for
        internal rebuilds (device-pack refresh) that must not distort
        the LRU order serving traffic establishes."""
        with self._lock:
            return self._adapters.get(name)

    def put(self, ad: LoRAAdapter, pinned=()) -> List[str]:
        """Insert (or refresh) an adapter; returns evicted names."""
        if ad.nbytes > self.capacity_bytes:
            raise ValueError(
                f"adapter {ad.name!r} ({ad.nbytes} B) exceeds the host "
                f"store budget ({self.capacity_bytes} B, DLI_LORA_HOST_MB)")
        evicted: List[str] = []
        with self._lock:
            old = self._adapters.pop(ad.name, None)
            if old is not None:
                self._bytes -= old.nbytes
            while self._bytes + ad.nbytes > self.capacity_bytes:
                victim = next((n for n in self._adapters
                               if n not in pinned), None)
                if victim is None:
                    # roll back: nothing unpinned left to evict
                    if old is not None:
                        self._adapters[ad.name] = old
                        self._bytes += old.nbytes
                    raise ValueError(
                        f"adapter {ad.name!r} does not fit: every "
                        "resident adapter is pinned by live requests")
                v = self._adapters.pop(victim)
                self._bytes -= v.nbytes
                self.evictions += 1
                evicted.append(victim)
            self._adapters[ad.name] = ad
            self._bytes += ad.nbytes
        return evicted

    def drop(self, name: str) -> bool:
        with self._lock:
            ad = self._adapters.pop(name, None)
            if ad is None:
                return False
            self._bytes -= ad.nbytes
            return True

    def names(self) -> List[str]:
        with self._lock:
            return list(self._adapters)

    def stats(self) -> dict:
        with self._lock:
            return {"adapters": len(self._adapters), "bytes": self._bytes,
                    "capacity_bytes": self.capacity_bytes,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}
