from distributed_llm_inferencing_tpu.models.config import ModelConfig  # noqa: F401
from distributed_llm_inferencing_tpu.models.registry import get_config, list_models  # noqa: F401
