"""Parameter initialization (random) for the unified transformer.

Used by tests, benchmarks and the dry-run path — real checkpoints come from
models/convert.py. Shapes follow the schema documented in
models/transformer.py; every per-layer leaf is stacked with a leading [L]
axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_llm_inferencing_tpu.models.config import ModelConfig


def init_params(cfg: ModelConfig, key, dtype=None):
    dtype = dtype or jnp.dtype(cfg.dtype)
    if cfg.dense_prefix_layers:
        # deepseek first_k_dense_replace: build the MoE tail and the
        # dense prefix as two independent stacked segments
        # (transformer.layer_segments runs them back to back)
        k1, k2 = jax.random.split(key)
        kd = cfg.dense_prefix_layers
        tail = init_params(
            cfg.replace(dense_prefix_layers=0, dense_intermediate_size=None,
                        num_layers=cfg.num_layers - kd), k1, dtype)
        prefix = init_params(cfg.dense_segment_cfg(), k2, dtype)
        tail["layers_dense"] = prefix["layers"]
        return tail
    L, D, I = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    keys = iter(jax.random.split(key, 64))

    def w(shape, scale=0.02):
        return (jax.random.normal(next(keys), shape, jnp.float32) * scale).astype(dtype)

    def w_q(shape, scale=0.02):
        # cfg.quant="int8"/"int4": emit the linear weight ALREADY
        # quantized — random quant levels with the per-output-channel
        # scale a real quantized checkpoint would carry (ops/quant.py
        # schema). Peak memory is the quantized model itself;
        # init-bf16-then-quantize would transiently need 2-4x, which for
        # the 8B flagship exceeds one chip's HBM. Values are random
        # either way — identical layout, dtypes and compute to a
        # converted quantized checkpoint.
        if cfg.quant == "int4":
            assert shape[-2] % 2 == 0, (
                f"int4 packing needs even din, got {shape[-2]}")
            # draw per-nibble biased levels in [1,15] (values [-7,7]) —
            # quantize_weight_int4 clips to that range, so level -8
            # (biased 0) never appears in a converted checkpoint and must
            # not appear here either
            half = shape[:-2] + (shape[-2] // 2, shape[-1])
            lo = jax.random.randint(next(keys), half, 1, 16, jnp.int32)
            hi = jax.random.randint(next(keys), half, 1, 16, jnp.int32)
            packed = (lo | (hi << 4)).astype(jnp.uint8)
            return {"p4": packed, "scale": jnp.full(
                shape[:-2] + shape[-1:], scale / 7.0, jnp.float32)}
        q = jax.random.randint(next(keys), shape, -127, 128, jnp.int8)
        return {"q": q, "scale": jnp.full(shape[:-2] + shape[-1:],
                                          scale / 127.0, jnp.float32)}

    def zeros(shape):
        return jnp.zeros(shape, dtype)

    def ones(shape):
        return jnp.ones(shape, dtype)

    def norm_p():
        p = {"scale": ones((L, D))}
        if cfg.norm_type == "layernorm":
            p["bias"] = zeros((L, D))
        return p

    quantized = cfg.quant in ("int8", "int4")

    def lin(din, dout, bias):
        p = w_q((L, din, dout)) if quantized else {"w": w((L, din, dout))}
        if bias:
            p["b"] = zeros((L, dout))
        return p

    def ew(shape):
        return w_q(shape) if quantized else {"w": w(shape)}

    if cfg.mla:   # deepseek-v3 latent attention (transformer._mla_qkv)
        H, hd = cfg.num_heads, cfg.head_dim
        r, rd = cfg.kv_lora_rank, cfg.qk_rope_head_dim
        vd = cfg.v_head_dim_effective
        layers = {
            "attn_norm": norm_p(),
            "kv_a": lin(D, r + rd, cfg.attn_bias),
            "kv_a_norm": {"scale": ones((L, r))},
            "kv_b_k": lin(r, H * (hd - rd), False),
            "kv_b_v": lin(r, H * vd, False),
            "o": lin(H * vd, D, cfg.o_bias_effective),
        }
        if cfg.q_lora_rank:
            layers["q_a"] = lin(D, cfg.q_lora_rank, cfg.attn_bias)
            layers["q_a_norm"] = {"scale": ones((L, cfg.q_lora_rank))}
            layers["q_b"] = lin(cfg.q_lora_rank, cfg.q_dim, False)
        else:
            layers["q"] = lin(D, cfg.q_dim, False)
    else:
        layers = {
            "attn_norm": norm_p(),
            "q": lin(D, cfg.q_dim, cfg.attn_bias),
            "k": lin(D, cfg.kv_dim, cfg.attn_bias),
            "v": lin(D, cfg.kv_dim, cfg.attn_bias),
            "o": lin(cfg.q_dim, D, cfg.o_bias_effective),
        }
    if cfg.post_block_norms:   # gemma2 sandwich norms
        layers["attn_post_norm"] = norm_p()
        layers["mlp_post_norm"] = norm_p()
    if cfg.qk_norm:   # qwen3/olmo2/cohere q/k normalization (bias-free)
        # rms_head: ONE [hd] scale shared by every head (qwen3);
        # rms_full/ln_head: full projection width (olmo2 normalizes the
        # flat projection; cohere's ln is per-head but carries DISTINCT
        # per-head scales, stored flat [H*hd] here)
        shared = cfg.qk_norm == "rms_head"
        layers["q_norm"] = {"scale": ones(
            (L, cfg.head_dim if shared else cfg.q_dim))}
        layers["k_norm"] = {"scale": ones(
            (L, cfg.head_dim if shared else cfg.kv_dim))}
    if cfg.attn_windows is not None:
        # per-layer window leaf ([L] int32, -1 == global) — rides the
        # layer scan/unroll/pipeline machinery (transformer._layer_window)
        layers["attn_window"] = jnp.asarray(
            [-1 if w is None else w for w in cfg.attn_windows], jnp.int32)
    if cfg.rope_layers is not None:   # per-layer NoPE (smollm3/exaone4)
        layers["rope_on"] = jnp.asarray(cfg.rope_layers, jnp.int32)
    if cfg.attn_sinks:   # gpt-oss: one learned sink logit per head
        layers["sinks"] = zeros((L, cfg.num_heads))
    if not cfg.shared_attn_mlp_norm:   # phi/falcon-7b: one norm per block
        layers["mlp_norm"] = norm_p()
    if cfg.is_moe:
        E = cfg.num_experts
        layers["router"] = {"w": w((L, D, E))}   # kept float (ops/quant.py)
        if cfg.moe_router in ("deepseek_v3", "ernie", "topk_softmax"):
            # selection-correction bias (deepseek/ernie) or the router
            # linear's real bias (gpt-oss)
            layers["router"]["bias"] = jnp.zeros((L, E), jnp.float32)
        layers["experts"] = {
            "gate": ew((L, E, D, I)),
            "up": ew((L, E, D, I)),
            "down": ew((L, E, I, D)),
        }
        if cfg.mlp_bias:   # gpt-oss: per-expert biases
            layers["experts"]["gate"]["b"] = zeros((L, E, I))
            layers["experts"]["up"]["b"] = zeros((L, E, I))
            layers["experts"]["down"]["b"] = zeros((L, E, D))
        if cfg.moe_shared_experts:   # deepseek always-active shared MLP
            SI = I * cfg.moe_shared_experts
            layers["shared_gate"] = lin(D, SI, cfg.mlp_bias)
            layers["shared_up"] = lin(D, SI, cfg.mlp_bias)
            layers["shared_down"] = lin(SI, D, cfg.mlp_bias)
    else:
        layers["up"] = lin(D, I, cfg.mlp_bias)
        if cfg.gated_mlp:
            layers["gate"] = ew((L, D, I))
        layers["down"] = lin(I, D, cfg.mlp_bias)

    E = cfg.embed_proj_dim or D

    def embed_table():
        if cfg.embed_quant == "int8":
            # direct-to-int8 table (ops/quant.py quantize_embed schema):
            # same reasoning as w_q — never materialize the float table
            q = jax.random.randint(next(keys), (cfg.vocab_size, E),
                                   -127, 128, jnp.int8)
            return {"q8": q, "rscale": jnp.full((cfg.vocab_size,),
                                                0.02 / 127.0, jnp.float32)}
        return w((cfg.vocab_size, E))

    params = {
        "embed": {"tokens": embed_table()},
        "layers": layers,
    }
    if cfg.embed_norm:   # bloom: layernorm on the embedding output
        params["embed"]["norm"] = {"scale": ones((E,)), "bias": zeros((E,))}
    if not cfg.post_norm:   # post-LN models (opt-350m) have no final norm
        params["final_norm"] = (
            {"scale": ones((D,)), "bias": zeros((D,))}
            if cfg.norm_type == "layernorm" else {"scale": ones((D,))})
    if cfg.embed_proj_dim:
        params["embed"]["project_in"] = {"w": w((E, D))}
        params["embed"]["project_out"] = {"w": w((D, E))}
    if cfg.position_embedding == "learned":
        params["embed"]["positions"] = w((cfg.max_position_embeddings, D))
    if not cfg.tie_word_embeddings:
        params["lm_head"] = ew((D, cfg.vocab_size))
        if cfg.lm_head_bias:   # phi
            params["lm_head"]["b"] = zeros((cfg.vocab_size,))
    if cfg.quant:
        # no-op for the leaves w_q already emitted; covers any remaining
        # float linear (and validates the quant mode)
        from distributed_llm_inferencing_tpu.ops.quant import maybe_quantize
        params = maybe_quantize(params, cfg)
    if cfg.embed_quant:
        from distributed_llm_inferencing_tpu.ops.quant import (
            maybe_quantize_embed)
        params = maybe_quantize_embed(params, cfg)   # validates the mode
    return params


def param_count(params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))


def param_bytes(params) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(params))
