"""HF checkpoint -> JAX pytree conversion.

The TPU-native replacement for the reference's model ingestion
(reference: worker/app.py:117-121 ``AutoModelForCausalLM.from_pretrained``
and the shard_model CLI's layer copying, shard_model.py:71-91): we read an
HF checkpoint ONCE into the stacked-layer pytree of models/transformer.py.
Sharding is a PartitionSpec assignment at load time (parallel/sharding.py),
not a file rewrite — no full-size "shards" with random out-of-range weights
(the reference's flaw, SURVEY.md §2.4).

Entry points:
- ``config_from_hf(hf_config)`` — map a transformers config to ModelConfig
- ``convert_state_dict(cfg, state_dict)`` — torch/numpy state dict -> pytree
- ``load_hf_model(path_or_model)`` — local checkpoint dir or in-memory HF
  model -> (ModelConfig, params). Works fully offline.
"""

from __future__ import annotations

import os

import numpy as np
import jax.numpy as jnp

from distributed_llm_inferencing_tpu.models.config import ModelConfig


def _np(t):
    """torch tensor | np array -> float32 numpy."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, dtype=np.float32)


def _qwen2_window(hf_config):
    """Qwen2 windows only layers >= max_window_layers (HF
    configuration_qwen2.py) — a per-layer mix our global
    cfg.sliding_window cannot represent, so accept only the two shapes
    that map exactly and refuse the rest loudly (silently windowing the
    full-attention layers would corrupt long-prompt logits)."""
    if not getattr(hf_config, "use_sliding_window", False):
        return None
    mwl = getattr(hf_config, "max_window_layers", 0) or 0
    if mwl > 0 and mwl < hf_config.num_hidden_layers:
        raise NotImplementedError(
            f"qwen2 with use_sliding_window and 0 < max_window_layers="
            f"{mwl} < num_layers={hf_config.num_hidden_layers}: mixed "
            "full/windowed layers are not supported")
    if mwl >= hf_config.num_hidden_layers:
        return None                       # every layer is full-attention
    return hf_config.sliding_window       # every layer is windowed


def config_from_hf(hf_config) -> ModelConfig:
    mt = hf_config.model_type
    if mt == "gpt2":
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", "gpt2") or "gpt2",
            family="gpt2", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.n_embd,
            intermediate_size=hf_config.n_inner or 4 * hf_config.n_embd,
            num_layers=hf_config.n_layer, num_heads=hf_config.n_head,
            num_kv_heads=hf_config.n_head,
            head_dim=hf_config.n_embd // hf_config.n_head,
            max_position_embeddings=hf_config.n_positions,
            norm_type="layernorm", norm_eps=hf_config.layer_norm_epsilon,
            activation="gelu", gated_mlp=False, position_embedding="learned",
            attn_bias=True, mlp_bias=True, tie_word_embeddings=True)
    if mt == "opt":
        proj = getattr(hf_config, "word_embed_proj_dim", hf_config.hidden_size)
        return ModelConfig(
            embed_proj_dim=proj if proj != hf_config.hidden_size else None,
            post_norm=not getattr(hf_config, "do_layer_norm_before", True),
            name=getattr(hf_config, "name_or_path", "opt") or "opt",
            family="opt", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.ffn_dim,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=hf_config.num_attention_heads,
            head_dim=hf_config.hidden_size // hf_config.num_attention_heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="layernorm", activation="relu", gated_mlp=False,
            position_embedding="learned", attn_bias=True, mlp_bias=True,
            tie_word_embeddings=True)
    if mt in ("llama", "mistral", "mixtral", "qwen2", "gemma"):
        # All share the llama layer layout (model.layers.N.self_attn.*,
        # mlp gate/up/down, input/post_attention layernorms), so one
        # conversion family covers them; the deltas are config switches.
        num_experts = getattr(hf_config, "num_local_experts", 0) if mt == "mixtral" else 0
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="llama", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads",
                                 hf_config.num_attention_heads),
            head_dim=getattr(hf_config, "head_dim", None)
            or hf_config.hidden_size // hf_config.num_attention_heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="rmsnorm", norm_eps=hf_config.rms_norm_eps,
            # gemma: gelu_pytorch_tanh == our default tanh-gelu
            activation="gelu" if mt == "gemma" else "silu",
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            # qwen2: bias on q/k/v only (baked into the HF module, not a
            # config attr), o_proj bias-free
            attn_bias=(True if mt == "qwen2"
                       else getattr(hf_config, "attention_bias", False)),
            o_bias=False if mt == "qwen2" else None,
            mlp_bias=getattr(hf_config, "mlp_bias", False),
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        mt == "gemma"),
            # qwen2 carries sliding_window=4096 in its config but only
            # APPLIES it when use_sliding_window is set (HF default off)
            sliding_window=_qwen2_window(hf_config) if mt == "qwen2"
            else getattr(hf_config, "sliding_window", None),
            num_experts=num_experts,
            num_experts_per_tok=getattr(hf_config, "num_experts_per_tok", 2),
            # gemma: sqrt(D) embedding normalizer + (1+w) norm convention
            embed_scale=(hf_config.hidden_size ** 0.5 if mt == "gemma"
                         else None),
            norm_offset=mt == "gemma")
    raise NotImplementedError(f"unsupported HF model_type {mt!r}")


def _stack(dicts):
    """list of {leaf: np [..]} -> {leaf: np [L, ..]} recursively."""
    out = {}
    for k in dicts[0]:
        if isinstance(dicts[0][k], dict):
            out[k] = _stack([d[k] for d in dicts])
        else:
            out[k] = np.stack([d[k] for d in dicts])
    return out


def convert_state_dict(cfg: ModelConfig, sd, dtype=None):
    """HF state dict (name -> torch tensor/np array) -> our param pytree."""
    dtype = dtype or jnp.dtype(cfg.dtype)
    fam = cfg.family
    D = cfg.hidden_size

    def get(name):
        return _np(sd[name])

    if fam == "gpt2":
        def layer(i):
            p = f"transformer.h.{i}."
            cattn_w = get(p + "attn.c_attn.weight")  # [D, 3D] (Conv1D: in,out)
            cattn_b = get(p + "attn.c_attn.bias")
            return {
                "attn_norm": {"scale": get(p + "ln_1.weight"),
                              "bias": get(p + "ln_1.bias")},
                "q": {"w": cattn_w[:, :D], "b": cattn_b[:D]},
                "k": {"w": cattn_w[:, D:2 * D], "b": cattn_b[D:2 * D]},
                "v": {"w": cattn_w[:, 2 * D:], "b": cattn_b[2 * D:]},
                "o": {"w": get(p + "attn.c_proj.weight"),
                      "b": get(p + "attn.c_proj.bias")},
                "mlp_norm": {"scale": get(p + "ln_2.weight"),
                             "bias": get(p + "ln_2.bias")},
                "up": {"w": get(p + "mlp.c_fc.weight"),
                       "b": get(p + "mlp.c_fc.bias")},
                "down": {"w": get(p + "mlp.c_proj.weight"),
                         "b": get(p + "mlp.c_proj.bias")},
            }
        params = {
            "embed": {"tokens": get("transformer.wte.weight"),
                      "positions": get("transformer.wpe.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("transformer.ln_f.weight"),
                           "bias": get("transformer.ln_f.bias")},
        }
    elif fam == "opt":
        def layer(i):
            p = f"model.decoder.layers.{i}."
            def lin(n):  # torch Linear stores [out, in] -> transpose
                return {"w": get(p + n + ".weight").T, "b": get(p + n + ".bias")}
            return {
                "attn_norm": {"scale": get(p + "self_attn_layer_norm.weight"),
                              "bias": get(p + "self_attn_layer_norm.bias")},
                "q": lin("self_attn.q_proj"),
                "k": lin("self_attn.k_proj"),
                "v": lin("self_attn.v_proj"),
                "o": lin("self_attn.out_proj"),
                "mlp_norm": {"scale": get(p + "final_layer_norm.weight"),
                             "bias": get(p + "final_layer_norm.bias")},
                "up": lin("fc1"),
                "down": lin("fc2"),
            }
        params = {
            "embed": {
                "tokens": get("model.decoder.embed_tokens.weight"),
                # OPT's learned positions are offset by 2 internally
                # (transformers OPTLearnedPositionalEmbedding); slice here so
                # position p indexes row p.
                "positions": get("model.decoder.embed_positions.weight")[2:],
            },
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
        }
        if not cfg.post_norm:   # opt-350m (post-LN) has no final norm
            params["final_norm"] = {
                "scale": get("model.decoder.final_layer_norm.weight"),
                "bias": get("model.decoder.final_layer_norm.bias")}
        if cfg.embed_proj_dim:
            params["embed"]["project_in"] = {
                "w": get("model.decoder.project_in.weight").T}
            params["embed"]["project_out"] = {
                "w": get("model.decoder.project_out.weight").T}
    elif fam == "llama":
        # gemma stores rmsnorm weights in the (1 + w) convention; absorb
        # the offset here so the runtime norm stays plain (config.py
        # norm_offset)
        off = 1.0 if cfg.norm_offset else 0.0

        def layer(i):
            p = f"model.layers.{i}."
            def lin(n):
                out = {"w": get(p + n + ".weight").T}
                if p + n + ".bias" in sd:  # attention_bias / mlp_bias variants
                    out["b"] = get(p + n + ".bias")
                return out
            lp = {
                "attn_norm": {"scale": get(p + "input_layernorm.weight") + off},
                "q": lin("self_attn.q_proj"),
                "k": lin("self_attn.k_proj"),
                "v": lin("self_attn.v_proj"),
                "o": lin("self_attn.o_proj"),
                "mlp_norm": {"scale": get(p + "post_attention_layernorm.weight") + off},
            }
            if cfg.is_moe:
                lp["router"] = {"w": get(p + "block_sparse_moe.gate.weight").T}
                ex = [f"block_sparse_moe.experts.{e}." for e in range(cfg.num_experts)]
                lp["experts"] = {
                    "gate": {"w": np.stack([get(p + e + "w1.weight").T for e in ex])},
                    "down": {"w": np.stack([get(p + e + "w2.weight").T for e in ex])},
                    "up": {"w": np.stack([get(p + e + "w3.weight").T for e in ex])},
                }
            else:
                lp["gate"] = lin("mlp.gate_proj")
                lp["up"] = lin("mlp.up_proj")
                lp["down"] = lin("mlp.down_proj")
            return lp
        params = {
            "embed": {"tokens": get("model.embed_tokens.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("model.norm.weight") + off},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    else:
        raise NotImplementedError(fam)

    return _to_jax(params, dtype)


def _to_jax(tree, dtype):
    if isinstance(tree, dict):
        return {k: _to_jax(v, dtype) for k, v in tree.items()}
    return jnp.asarray(tree, dtype)


def allow_download() -> bool:
    """Hub downloads are opt-in: offline-by-default is the safe serving
    posture (a worker must not silently reach the internet), but the
    reference's download-any-model-by-name capability (worker/app.py:117-121,
    cache dir worker/app.py:19-20) is available behind DLI_ALLOW_DOWNLOAD=1."""
    return os.environ.get("DLI_ALLOW_DOWNLOAD", "") == "1"


def hub_cache_dir() -> str:
    """Where opted-in downloads land (≙ reference MODEL_CACHE_DIR,
    worker/app.py:19-20). Shared across workers via a mounted volume the
    same way the reference's compose file did (docker-compose.yml:12)."""
    return os.environ.get(
        "DLI_MODEL_CACHE", os.path.join(os.path.expanduser("~"),
                                        ".cache", "dli_models"))


def load_hf_model(path_or_model, dtype=None):
    """Load a local HF checkpoint directory, a hub id (opt-in), or an
    in-memory HF model.

    Returns (ModelConfig, params). Offline by default: paths must exist
    locally (the reference relied on HF-hub downloads per worker,
    worker/app.py:117-121; here checkpoint distribution is explicit).
    With ``DLI_ALLOW_DOWNLOAD=1`` a non-local name is fetched from the
    hub into ``hub_cache_dir()`` once and reused thereafter.
    """
    if isinstance(path_or_model, str):
        import transformers
        local_only = not allow_download() or os.path.isdir(path_or_model)
        # redirect the cache only when an actual download is permitted —
        # offline hub-id loads must keep resolving against the standard
        # HF cache a user may already have populated
        kw = ({"cache_dir": hub_cache_dir()}
              if not local_only and not os.path.isdir(path_or_model) else {})
        model = transformers.AutoModelForCausalLM.from_pretrained(
            path_or_model, local_files_only=local_only, **kw)
    else:
        model = path_or_model
    cfg = config_from_hf(model.config)
    params = convert_state_dict(cfg, dict(model.state_dict()), dtype=dtype)
    return cfg, params
