"""HF checkpoint -> JAX pytree conversion.

The TPU-native replacement for the reference's model ingestion
(reference: worker/app.py:117-121 ``AutoModelForCausalLM.from_pretrained``
and the shard_model CLI's layer copying, shard_model.py:71-91): we read an
HF checkpoint ONCE into the stacked-layer pytree of models/transformer.py.
Sharding is a PartitionSpec assignment at load time (parallel/sharding.py),
not a file rewrite — no full-size "shards" with random out-of-range weights
(the reference's flaw, SURVEY.md §2.4).

Entry points:
- ``config_from_hf(hf_config)`` — map a transformers config to ModelConfig
- ``convert_state_dict(cfg, state_dict)`` — torch/numpy state dict -> pytree
- ``load_hf_model(path_or_model)`` — local checkpoint dir or in-memory HF
  model -> (ModelConfig, params). Works fully offline.
"""

from __future__ import annotations

import os

import numpy as np
import jax.numpy as jnp

from distributed_llm_inferencing_tpu.models.config import ModelConfig


def _np(t):
    """torch tensor | np array -> float32 numpy."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, dtype=np.float32)


def _qwen2_window(hf_config):
    """Qwen2 windows only layers >= max_window_layers (HF
    configuration_qwen2.py) — a per-layer mix our global
    cfg.sliding_window cannot represent, so accept only the two shapes
    that map exactly and refuse the rest loudly (silently windowing the
    full-attention layers would corrupt long-prompt logits)."""
    if not getattr(hf_config, "use_sliding_window", False):
        return None
    mwl = getattr(hf_config, "max_window_layers", 0) or 0
    if mwl > 0 and mwl < hf_config.num_hidden_layers:
        raise NotImplementedError(
            f"qwen2 with use_sliding_window and 0 < max_window_layers="
            f"{mwl} < num_layers={hf_config.num_hidden_layers}: mixed "
            "full/windowed layers are not supported")
    if mwl >= hf_config.num_hidden_layers:
        return None                       # every layer is full-attention
    return hf_config.sliding_window       # every layer is windowed


def _yarn_params(rs: dict, dim: int, base: float, max_pos: int):
    """Yarn NTK-by-part rope scaling (HF modeling_rope_utils.py
    _compute_yarn_parameters, arXiv:2309.00071): interpolated and
    extrapolated frequency ladders blended by a per-dim linear ramp
    between the beta_fast/beta_slow correction bounds. Returns
    (inv_freq tuple [dim/2], attention_factor, mscale_all_dim_scale) —
    the last is HF deepseek's separate uniform score multiplier
    (modeling_deepseek_v3.py:372-377), squared there; we fold its square
    into the q weights at conversion."""
    import math
    factor = float(rs["factor"])
    beta_fast = float(rs.get("beta_fast") or 32)
    beta_slow = float(rs.get("beta_slow") or 1)
    orig = int(rs.get("original_max_position_embeddings") or max_pos)
    mscale = rs.get("mscale")
    mscale_all = rs.get("mscale_all_dim")

    def get_mscale(scale, m=1.0):
        return 1.0 if scale <= 1 else 0.1 * m * math.log(scale) + 1.0

    attn_factor = rs.get("attention_factor")
    if attn_factor is None:
        if mscale and mscale_all:
            attn_factor = get_mscale(factor, mscale) / get_mscale(
                factor, mscale_all)
        else:
            attn_factor = get_mscale(factor)

    def corr_dim(rot):
        return (dim * math.log(orig / (rot * 2 * math.pi))
                ) / (2 * math.log(base))
    low, high = corr_dim(beta_fast), corr_dim(beta_slow)
    if rs.get("truncate", True):   # HF floor/ceils unless truncate:false
        low, high = math.floor(low), math.ceil(high)
    low, high = max(low, 0), min(high, dim - 1)
    ramp = np.clip((np.arange(dim // 2, dtype=np.float64) - low)
                   / max(high - low, 1e-3), 0.0, 1.0)
    pos_freqs = base ** (np.arange(0, dim, 2, dtype=np.float64) / dim)
    inv_freq = (1.0 / (factor * pos_freqs)) * ramp \
        + (1.0 / pos_freqs) * (1.0 - ramp)
    score_scale = get_mscale(factor, float(mscale_all or 0.0)) \
        if mscale_all else 1.0
    return tuple(float(f) for f in inv_freq), float(attn_factor), \
        float(score_scale)


def _rope_scaling_params(hf_config, dim: int, what: str):
    """Map an HF ``rope_scaling`` dict to (inv_freq tuple | None,
    attention_factor, score_scale) for cfg.rope_inv_freq /
    cfg.rope_attn_factor (ops/rope.apply_rope). Covers the schemes whose
    effect is a static frequency-ladder rewrite — "yarn" (+ deepseek's
    mscale), "llama3" (Llama 3.1+ NTK-by-part smoothing, HF
    modeling_rope_utils._compute_llama3_parameters), "linear"
    (position-interpolation: uniform /factor), "longrope" (Phi-3.5
    factor sets, static regime pick), "default" — and refuses
    the rest loudly (silently ignoring rope_scaling would corrupt
    long-context logits for every scaled checkpoint)."""
    import math
    rs = getattr(hf_config, "rope_scaling", None)
    if not rs:
        return None, 1.0, 1.0
    kind = rs.get("rope_type", rs.get("type"))
    base = float(getattr(hf_config, "rope_theta", 10000.0))
    if kind == "yarn":
        return _yarn_params(rs, dim, base,
                            hf_config.max_position_embeddings)
    pos_freqs = base ** (np.arange(0, dim, 2, dtype=np.float64) / dim)
    inv_freq = 1.0 / pos_freqs
    if kind in (None, "default"):
        return None, 1.0, 1.0
    if kind == "linear":
        return tuple(float(f) for f in inv_freq / float(rs["factor"])), \
            1.0, 1.0
    if kind == "longrope":
        # Phi-3-style longrope carries TWO per-dim factor sets that HF
        # switches per forward at original_max_position_embeddings. A
        # static conversion must pick ONE regime: we convert for the
        # window the checkpoint ADVERTISES — long factors (plus the
        # attention factor) when max_position_embeddings was extended
        # past the original, short factors otherwise. Exact HF parity
        # within the chosen regime; sequences in the other regime see
        # the divergence HF itself acknowledges when the cache crosses
        # the boundary mid-generation.
        # HF reads original_max_position_embeddings from the CONFIG
        # attribute only (never the rope_scaling dict), deriving the
        # attention-factor base from max/original when present and from
        # rs["factor"] otherwise (modeling_rope_utils.py
        # _compute_longrope_parameters)
        orig = getattr(hf_config, "original_max_position_embeddings",
                       None)
        if orig:
            factor = hf_config.max_position_embeddings / orig
            extended = hf_config.max_position_embeddings > orig
        else:
            orig = hf_config.max_position_embeddings
            factor = float(rs.get("factor") or 1.0)
            extended = False   # no original => HF stays on short factors
        ext = np.asarray(rs["long_factor" if extended else "short_factor"],
                         np.float64)
        if ext.shape != (dim // 2,):
            raise NotImplementedError(
                f"longrope factor set has {ext.shape[0]} entries for "
                f"rotary dim {dim}")
        attn_factor = rs.get("attention_factor")
        if attn_factor is None:
            attn_factor = (1.0 if factor <= 1.0
                           else math.sqrt(1 + math.log(factor)
                                          / math.log(orig)))
        return tuple(float(v) for v in 1.0 / (ext * pos_freqs)), \
            float(attn_factor), 1.0
    if kind == "llama3":
        factor = float(rs["factor"])
        lo_f = float(rs["low_freq_factor"])
        hi_f = float(rs["high_freq_factor"])
        old = float(rs.get("original_max_position_embeddings")
                    or hf_config.max_position_embeddings)
        wavelen = 2 * math.pi / inv_freq
        scaled = np.where(wavelen > old / lo_f, inv_freq / factor, inv_freq)
        smooth = (old / wavelen - lo_f) / (hi_f - lo_f)
        smoothed = (1 - smooth) * scaled / factor + smooth * scaled
        medium = ~(wavelen < old / hi_f) & ~(wavelen > old / lo_f)
        out = np.where(medium, smoothed, scaled)
        return tuple(float(f) for f in out), 1.0, 1.0
    raise NotImplementedError(
        f"{what} rope_scaling type {kind!r} — yarn, llama3, linear and "
        "longrope convert")


def _layer_windows_from_hf(hf_config, require_use_flag: bool = False):
    """Per-layer windows from an HF ``layer_types`` list: returns
    (sliding_window, attn_windows, kinds) ready for the ModelConfig
    kwargs — the uniform case keeps the static sliding_window (pallas
    flash kernels stay eligible), the mixed case emits the per-layer
    tuple. ``require_use_flag``: gate on use_sliding_window (smollm3)
    instead of sliding_window's presence alone."""
    kinds = list(getattr(hf_config, "layer_types", None) or [])
    win = getattr(hf_config, "sliding_window", None)
    enabled = (bool(getattr(hf_config, "use_sliding_window", win))
               if require_use_flag else win is not None)
    wins = tuple(win if (enabled and t == "sliding_attention") else None
                 for t in kinds)
    windowed = any(w is not None for w in wins)
    uniform = not windowed or len(set(wins)) == 1
    return ((wins[0] if windowed and uniform else None),
            (None if uniform else wins), kinds)


# HF hidden_act -> our activation kinds (models/transformer.py _act).
# "gelu" is the erf form; gelu_new/gelu_pytorch_tanh are the tanh approx.
_HF_ACT = {"gelu": "gelu_exact", "gelu_new": "gelu",
           "gelu_pytorch_tanh": "gelu", "silu": "silu", "relu": "relu",
           "relu2": "relu2"}


def _act_from_hf(name: str) -> str:
    if name not in _HF_ACT:
        raise NotImplementedError(f"unsupported hidden_act {name!r}")
    return _HF_ACT[name]


SUPPORTED_MODEL_TYPES = ("gpt2", "opt", "llama", "mistral", "mixtral",
                         "qwen2", "gemma", "gpt_neox", "phi", "falcon",
                         "bloom", "gptj", "mpt", "gpt_bigcode", "stablelm",
                         "codegen", "starcoder2", "olmo", "phi3",
                         "gpt_neo", "gemma2", "cohere", "qwen3",
                         "qwen3_moe", "granite", "olmo2", "glm", "glm4",
                         "nemotron", "deepseek_v3", "ernie4_5", "smollm3",
                         "hunyuan_v1_dense", "exaone4", "dbrx", "glm4_moe",
                         "ernie4_5_moe", "gpt_oss", "hunyuan_v1_moe")


def config_from_hf(hf_config) -> ModelConfig:
    mt = hf_config.model_type
    if mt == "gpt2":
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", "gpt2") or "gpt2",
            family="gpt2", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.n_embd,
            intermediate_size=hf_config.n_inner or 4 * hf_config.n_embd,
            num_layers=hf_config.n_layer, num_heads=hf_config.n_head,
            num_kv_heads=hf_config.n_head,
            head_dim=hf_config.n_embd // hf_config.n_head,
            max_position_embeddings=hf_config.n_positions,
            norm_type="layernorm", norm_eps=hf_config.layer_norm_epsilon,
            activation="gelu", gated_mlp=False, position_embedding="learned",
            attn_bias=True, mlp_bias=True, tie_word_embeddings=True)
    if mt == "opt":
        proj = getattr(hf_config, "word_embed_proj_dim", hf_config.hidden_size)
        return ModelConfig(
            embed_proj_dim=proj if proj != hf_config.hidden_size else None,
            post_norm=not getattr(hf_config, "do_layer_norm_before", True),
            name=getattr(hf_config, "name_or_path", "opt") or "opt",
            family="opt", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.ffn_dim,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=hf_config.num_attention_heads,
            head_dim=hf_config.hidden_size // hf_config.num_attention_heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="layernorm", activation="relu", gated_mlp=False,
            position_embedding="learned", attn_bias=True, mlp_bias=True,
            tie_word_embeddings=True)
    if mt in ("llama", "mistral", "mixtral", "qwen2", "gemma"):
        # All share the llama layer layout (model.layers.N.self_attn.*,
        # mlp gate/up/down, input/post_attention layernorms), so one
        # conversion family covers them; the deltas are config switches.
        num_experts = getattr(hf_config, "num_local_experts", 0) if mt == "mixtral" else 0
        inv_freq, attn_factor, _ = _rope_scaling_params(
            hf_config, getattr(hf_config, "head_dim", None)
            or hf_config.hidden_size // hf_config.num_attention_heads, mt)
        return ModelConfig(
            rope_inv_freq=inv_freq, rope_attn_factor=attn_factor,
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="llama", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads",
                                 hf_config.num_attention_heads),
            head_dim=getattr(hf_config, "head_dim", None)
            or hf_config.hidden_size // hf_config.num_attention_heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="rmsnorm", norm_eps=hf_config.rms_norm_eps,
            # gemma: gelu_pytorch_tanh == our default tanh-gelu
            activation="gelu" if mt == "gemma" else "silu",
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            # qwen2: bias on q/k/v only (baked into the HF module, not a
            # config attr), o_proj bias-free
            attn_bias=(True if mt == "qwen2"
                       else getattr(hf_config, "attention_bias", False)),
            o_bias=False if mt == "qwen2" else None,
            mlp_bias=getattr(hf_config, "mlp_bias", False),
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        mt == "gemma"),
            # qwen2 carries sliding_window=4096 in its config but only
            # APPLIES it when use_sliding_window is set (HF default off)
            sliding_window=_qwen2_window(hf_config) if mt == "qwen2"
            else getattr(hf_config, "sliding_window", None),
            num_experts=num_experts,
            num_experts_per_tok=getattr(hf_config, "num_experts_per_tok", 2),
            # gemma: sqrt(D) embedding normalizer + (1+w) norm convention
            embed_scale=(hf_config.hidden_size ** 0.5 if mt == "gemma"
                         else None),
            norm_offset=mt == "gemma")
    if mt == "gpt_neox":
        # GPT-NeoX / Pythia: parallel-residual blocks (two norms), fused
        # per-head-interleaved QKV, partial rotary (rotary_pct), exact
        # (erf) gelu, untied embed_out head.
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="gpt-neox", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=hf_config.num_attention_heads,
            head_dim=hf_config.hidden_size // hf_config.num_attention_heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="layernorm", norm_eps=hf_config.layer_norm_eps,
            activation=_act_from_hf(hf_config.hidden_act),
            gated_mlp=False, position_embedding="rope",
            rope_theta=getattr(hf_config, "rotary_emb_base", None)
            or getattr(hf_config, "rope_theta", 10000.0),
            rope_pct=getattr(hf_config, "rotary_pct", 1.0),
            attn_bias=getattr(hf_config, "attention_bias", True),
            mlp_bias=True,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False),
            parallel_residual=getattr(hf_config, "use_parallel_residual",
                                      True))
    if mt == "phi":
        # Phi-1/1.5/2: parallel residual with a SINGLE shared layernorm,
        # partial rotary, biases everywhere incl. the untied lm_head.
        if getattr(hf_config, "qk_layernorm", False):
            raise NotImplementedError("phi with qk_layernorm")
        heads = hf_config.num_attention_heads
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="phi", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers, num_heads=heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or heads,
            head_dim=hf_config.hidden_size // heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="layernorm", norm_eps=hf_config.layer_norm_eps,
            activation=_act_from_hf(hf_config.hidden_act),
            gated_mlp=False, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            rope_pct=getattr(hf_config, "partial_rotary_factor", 0.5),
            attn_bias=True, mlp_bias=True, lm_head_bias=True,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False),
            parallel_residual=True, shared_attn_mlp_norm=True)
    if mt == "falcon":
        # Falcon: fused grouped/MQA QKV, exact gelu, no biases. Three
        # shapes map: the 7B layout (multi_query, parallel residual,
        # single shared norm), the new decoder architecture (grouped-KV,
        # ln_attn + ln_mlp parallel norms), and the RW layout (per-head
        # fused QKV, sequential residual, ALiBi positions).
        new_arch = getattr(hf_config, "new_decoder_architecture", False)
        parallel = getattr(hf_config, "parallel_attn", True)
        alibi = getattr(hf_config, "alibi", False)
        if new_arch and getattr(hf_config, "num_ln_in_parallel_attn",
                                None) == 1:
            raise NotImplementedError("falcon new-arch with a single "
                                      "parallel layernorm")
        heads = hf_config.num_attention_heads
        if new_arch:
            kv = getattr(hf_config, "num_kv_heads", None) or heads
        else:
            kv = 1 if getattr(hf_config, "multi_query", True) else heads
        bias = getattr(hf_config, "bias", False)
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="falcon", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=getattr(hf_config, "ffn_hidden_size", None)
            or 4 * hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers, num_heads=heads,
            num_kv_heads=kv,
            head_dim=hf_config.hidden_size // heads,
            max_position_embeddings=getattr(
                hf_config, "max_position_embeddings", 2048),
            norm_type="layernorm",
            norm_eps=getattr(hf_config, "layer_norm_epsilon", 1e-5),
            activation=_act_from_hf(getattr(hf_config, "activation",
                                            "gelu")),
            gated_mlp=False,
            position_embedding="alibi" if alibi else "rope",
            # falcon scales (scores + alibi) by 1/sqrt(hd) together
            alibi_scale=(hf_config.hidden_size // heads) ** -0.5
            if alibi else 1.0,
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            attn_bias=bias, mlp_bias=bias,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        True),
            parallel_residual=parallel,
            shared_attn_mlp_norm=parallel and not new_arch)
    if mt == "bloom":
        # BLOOM: ALiBi positions, layernormed embedding output, per-head
        # interleaved fused QKV, tanh-gelu, tied 250k-vocab head.
        heads = hf_config.n_head
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="bloom", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=4 * hf_config.hidden_size,
            num_layers=hf_config.n_layer, num_heads=heads,
            num_kv_heads=heads,
            head_dim=hf_config.hidden_size // heads,
            max_position_embeddings=getattr(hf_config, "seq_length", None)
            or 2048,
            norm_type="layernorm",
            norm_eps=hf_config.layer_norm_epsilon,
            activation="gelu", gated_mlp=False,
            position_embedding="alibi", embed_norm=True,
            attn_bias=True, mlp_bias=True,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        True))
    if mt == "gptj":
        # GPT-J: parallel residual with ONE shared layernorm, partial
        # INTERLEAVED rotary (rotate_every_two over rotary_dim dims),
        # bias-free attention, biased MLP and untied biased lm_head.
        heads = hf_config.n_head
        hd = hf_config.n_embd // heads
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="gptj", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.n_embd,
            intermediate_size=getattr(hf_config, "n_inner", None)
            or 4 * hf_config.n_embd,
            num_layers=hf_config.n_layer, num_heads=heads,
            num_kv_heads=heads, head_dim=hd,
            max_position_embeddings=hf_config.n_positions,
            norm_type="layernorm",
            norm_eps=hf_config.layer_norm_epsilon,
            activation=_act_from_hf(hf_config.activation_function),
            gated_mlp=False, position_embedding="rope",
            rope_theta=10000.0,
            rope_pct=(getattr(hf_config, "rotary_dim", None) or hd) / hd,
            rope_interleaved=True,
            attn_bias=False, o_bias=False, mlp_bias=True,
            lm_head_bias=True,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False),
            parallel_residual=True, shared_attn_mlp_norm=True)
    if mt == "mpt":
        # MPT: ALiBi (BLOOM-convention slopes for power-of-two heads),
        # straight-concat fused QKV (optionally grouped KV), bias-free
        # layout by default, exact gelu, tied head.
        ac = hf_config.attn_config

        def acget(key, default=None):
            return (ac.get(key, default) if isinstance(ac, dict)
                    else getattr(ac, key, default))
        if not acget("alibi", True):
            raise NotImplementedError("mpt without alibi positions")
        if acget("clip_qkv") or acget("qk_ln", False):
            raise NotImplementedError("mpt with clip_qkv/qk_ln")
        if acget("softmax_scale") is not None:
            raise NotImplementedError(
                "mpt with a custom attn softmax_scale (the runtime always "
                "uses 1/sqrt(head_dim))")
        if acget("alibi_bias_max", 8) != 8:
            raise NotImplementedError("mpt with alibi_bias_max != 8")
        heads = hf_config.n_heads
        if heads & (heads - 1):
            raise NotImplementedError(
                "mpt with non-power-of-two heads: its alibi slope "
                "interpolation differs from the BLOOM convention")
        D = hf_config.d_model
        bias = not getattr(hf_config, "no_bias", True)
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="mpt", vocab_size=hf_config.vocab_size,
            hidden_size=D,
            intermediate_size=int(hf_config.expansion_ratio * D),
            num_layers=hf_config.n_layers, num_heads=heads,
            num_kv_heads=acget("kv_n_heads", None) or heads,
            head_dim=D // heads,
            max_position_embeddings=hf_config.max_seq_len,
            norm_type="layernorm", norm_eps=1e-5,
            activation="gelu_exact", gated_mlp=False,
            position_embedding="alibi",
            attn_bias=bias, mlp_bias=bias,
            tie_word_embeddings=True)
    if mt == "gpt_bigcode":
        # StarCoder / SantaCoder: GPT-2 block layout but nn.Linear (not
        # Conv1D) weights, multi-query attention (1 kv head) by default,
        # tanh-gelu, learned positions, tied head.
        heads = hf_config.n_head
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="gpt_bigcode", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.n_embd,
            intermediate_size=getattr(hf_config, "n_inner", None)
            or 4 * hf_config.n_embd,
            num_layers=hf_config.n_layer, num_heads=heads,
            num_kv_heads=1 if getattr(hf_config, "multi_query", True)
            else heads,
            head_dim=hf_config.n_embd // heads,
            max_position_embeddings=hf_config.n_positions,
            norm_type="layernorm",
            norm_eps=hf_config.layer_norm_epsilon,
            activation=_act_from_hf(getattr(hf_config,
                                            "activation_function",
                                            "gelu_pytorch_tanh")),
            gated_mlp=False, position_embedding="learned",
            attn_bias=True, mlp_bias=True,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        True))
    if mt == "stablelm":
        # StableLM / StableLM-2: llama layer layout with LAYERNORMS
        # (biased) instead of rmsnorm, partial rotary, optional qkv-only
        # bias, untied head.
        if getattr(hf_config, "use_parallel_residual", False):
            raise NotImplementedError("stablelm with use_parallel_residual")
        if getattr(hf_config, "qk_layernorm", False):
            raise NotImplementedError("stablelm with qk_layernorm")
        heads = hf_config.num_attention_heads
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="stablelm", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers, num_heads=heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or heads,
            head_dim=hf_config.hidden_size // heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="layernorm",
            norm_eps=getattr(hf_config, "layer_norm_eps", 1e-5),
            activation=_act_from_hf(getattr(hf_config, "hidden_act",
                                            "silu")),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            rope_pct=getattr(hf_config, "partial_rotary_factor", 0.25),
            attn_bias=getattr(hf_config, "use_qkv_bias", False),
            o_bias=False, mlp_bias=False,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False))
    if mt == "codegen":
        # CodeGen (Salesforce): GPT-J topology — parallel residual with a
        # single shared ln_1, partial INTERLEAVED rotary over rotary_dim,
        # bias-free attention, biased MLP + untied biased lm_head. Only
        # the fused-QKV weight layout differs (mp_num blocks, q|v|k
        # order — see convert_state_dict).
        heads = hf_config.n_head
        hd = hf_config.n_embd // heads
        if heads % 4:
            raise NotImplementedError(
                "codegen with n_head not divisible by mp_num=4 (HF "
                "CodeGenAttention hard-codes 4 TP blocks in the fused "
                "QKV layout)")
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="codegen", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.n_embd,
            intermediate_size=getattr(hf_config, "n_inner", None)
            or 4 * hf_config.n_embd,
            num_layers=hf_config.n_layer, num_heads=heads,
            num_kv_heads=heads, head_dim=hd,
            max_position_embeddings=hf_config.n_positions,
            norm_type="layernorm",
            norm_eps=hf_config.layer_norm_epsilon,
            activation=_act_from_hf(hf_config.activation_function),
            gated_mlp=False, position_embedding="rope",
            rope_theta=10000.0,
            rope_pct=(getattr(hf_config, "rotary_dim", None) or hd) / hd,
            rope_interleaved=True,
            attn_bias=False, o_bias=False, mlp_bias=True,
            lm_head_bias=True,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False),
            parallel_residual=True, shared_attn_mlp_norm=True)
    if mt == "starcoder2":
        # StarCoder2: llama layer layout/names but biased LAYERNORMS, a
        # plain (non-gated) tanh-gelu MLP named c_fc/c_proj, biased
        # linears (use_bias), full rotary, optional sliding window.
        heads = hf_config.num_attention_heads
        bias = getattr(hf_config, "use_bias", True)
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="starcoder2", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers, num_heads=heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or heads,
            head_dim=hf_config.hidden_size // heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="layernorm",
            norm_eps=getattr(hf_config, "norm_epsilon", 1e-5),
            activation=_act_from_hf(getattr(hf_config, "hidden_act",
                                            "gelu_pytorch_tanh")),
            gated_mlp=False, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            attn_bias=bias, mlp_bias=bias,
            sliding_window=getattr(hf_config, "sliding_window", None),
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        True))
    if mt == "olmo":
        # OLMo: llama layout with NON-PARAMETRIC layernorms (no scale or
        # bias — converted as unit-scale/zero-bias leaves so the runtime
        # norm stays uniform), SwiGLU, bias-free linears, full rotary.
        if getattr(hf_config, "clip_qkv", None):
            raise NotImplementedError(
                "olmo with clip_qkv (the runtime applies no QKV "
                "activation clamp)")
        heads = hf_config.num_attention_heads
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="olmo", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers, num_heads=heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or heads,
            head_dim=hf_config.hidden_size // heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            # HF OlmoLayerNorm: F.layer_norm with no affine, eps 1e-5
            norm_type="layernorm", norm_eps=1e-5,
            activation=_act_from_hf(getattr(hf_config, "hidden_act",
                                            "silu")),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            attn_bias=getattr(hf_config, "attention_bias", False),
            mlp_bias=False,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False))
    if mt == "phi3":
        # Phi-3: llama semantics (rmsnorm, SwiGLU, full rotary, GQA,
        # bias-free, untied head) with FUSED qkv_proj ([q|k|v] rows) and
        # gate_up_proj ([gate|up] rows) — split in convert_state_dict.
        # Longrope (Phi-3.5's 128k extension) converts via the static
        # regime pick in _rope_scaling_params.
        heads = hf_config.num_attention_heads
        p3_inv_freq, p3_attn_factor, _ = _rope_scaling_params(
            hf_config,
            int((hf_config.hidden_size // heads)
                * getattr(hf_config, "partial_rotary_factor", 1.0)), mt)
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="phi3", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers, num_heads=heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or heads,
            head_dim=hf_config.hidden_size // heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="rmsnorm", norm_eps=hf_config.rms_norm_eps,
            activation=_act_from_hf(getattr(hf_config, "hidden_act",
                                            "silu")),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            rope_inv_freq=p3_inv_freq, rope_attn_factor=p3_attn_factor,
            # phi-4-mini ships partial rotary; the scaled ladder above is
            # already sized to the partial dim, and rope_pct keeps
            # apply_rope's rotated slice to the same width
            rope_pct=float(getattr(hf_config, "partial_rotary_factor",
                                   1.0)),
            attn_bias=False, mlp_bias=False,
            sliding_window=getattr(hf_config, "sliding_window", None),
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False))
    if mt == "gpt_neo":
        # GPT-Neo: gpt2 topology (learned positions, sequential pre-LN,
        # plain gelu MLP) with two quirks: attention scores are UNSCALED
        # (no 1/sqrt(hd) — folded into the q weights at conversion, the
        # same absorb-at-conversion idiom as gemma's norm offset), and
        # layers alternate global / local-window attention
        # (attention_types) — the per-layer window rides the param tree
        # (config.py attn_windows).
        kinds = list(hf_config.attention_layers)
        if not all(t in ("global", "local") for t in kinds):
            raise NotImplementedError(
                f"gpt_neo attention_types {sorted(set(kinds))!r} — only "
                "global/local convert")
        win = int(getattr(hf_config, "window_size", 256))
        wins = tuple(None if t == "global" else win for t in kinds)
        uniform = len(set(wins)) == 1   # all-global OR all-local: the
        # static uniform path keeps the pallas flash kernels eligible
        heads = hf_config.num_heads
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="gpt_neo", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=getattr(hf_config, "intermediate_size", None)
            or 4 * hf_config.hidden_size,
            num_layers=hf_config.num_layers, num_heads=heads,
            num_kv_heads=heads,
            head_dim=hf_config.hidden_size // heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="layernorm",
            norm_eps=hf_config.layer_norm_epsilon,
            activation=_act_from_hf(getattr(hf_config,
                                            "activation_function",
                                            "gelu_new")),
            gated_mlp=False, position_embedding="learned",
            attn_bias=False, o_bias=True, mlp_bias=True,
            sliding_window=wins[0] if uniform else None,
            attn_windows=None if uniform else wins,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        True))
    if mt == "gemma2":
        # Gemma-2: gemma's rmsnorm/(1+w)/embed-scale conventions plus
        # FOUR norms per block (sandwich, post_block_norms), attention +
        # final logit softcapping, query_pre_attn_scalar replacing the
        # 1/sqrt(hd) score scale (the ratio folds into q at conversion),
        # and alternating sliding/full layers (attn_windows).
        heads = hf_config.num_attention_heads
        kinds = list(getattr(hf_config, "layer_types", None)
                     or ["sliding_attention" if i % 2 == 0
                         else "full_attention"
                         for i in range(hf_config.num_hidden_layers)])
        if not all(t in ("sliding_attention", "full_attention")
                   for t in kinds):
            raise NotImplementedError(
                f"gemma2 layer_types {sorted(set(kinds))!r}")
        win = getattr(hf_config, "sliding_window", None)
        wins = tuple(win if t == "sliding_attention" else None
                     for t in kinds)
        uniform2 = win is None or len(set(wins)) == 1
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="gemma2", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers, num_heads=heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or heads,
            head_dim=getattr(hf_config, "head_dim", None)
            or hf_config.hidden_size // heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="rmsnorm", norm_eps=hf_config.rms_norm_eps,
            activation=_act_from_hf(getattr(hf_config, "hidden_activation",
                                            "gelu_pytorch_tanh")),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            attn_bias=getattr(hf_config, "attention_bias", False),
            mlp_bias=False,
            sliding_window=(wins[0] if uniform2 else None),
            attn_windows=None if uniform2 else wins,
            attn_softcap=getattr(hf_config, "attn_logit_softcapping",
                                 None),
            logit_softcap=getattr(hf_config, "final_logit_softcapping",
                                  None),
            post_block_norms=True,
            query_pre_attn_scalar=float(
                getattr(hf_config, "query_pre_attn_scalar", None)
                or (getattr(hf_config, "head_dim", None)
                    or hf_config.hidden_size // heads)),
            embed_scale=hf_config.hidden_size ** 0.5,
            norm_offset=True,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        True))
    if mt == "cohere":
        # Cohere (Command-R): parallel residual with ONE shared bias-free
        # layernorm, INTERLEAVED full rotary, tied head with a constant
        # logit scale.
        heads = hf_config.num_attention_heads
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="cohere", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers, num_heads=heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or heads,
            head_dim=hf_config.hidden_size // heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="layernorm",
            norm_eps=getattr(hf_config, "layer_norm_eps", 1e-5),
            activation=_act_from_hf(getattr(hf_config, "hidden_act",
                                            "silu")),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            rope_interleaved=True,
            attn_bias=getattr(hf_config, "attention_bias", False),
            mlp_bias=False,
            logit_scale=getattr(hf_config, "logit_scale", None),
            # Command-R+: bias-free per-head layernorm on q/k with
            # distinct per-head scales
            qk_norm=("ln_head" if getattr(hf_config, "use_qk_norm", False)
                     else None),
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        True),
            parallel_residual=True, shared_attn_mlp_norm=True)
    if mt in ("qwen3", "qwen3_moe"):
        # Qwen3 (+ MoE): llama layer layout plus per-head RMS q/k norms
        # (ONE [head_dim] scale shared across heads) and an explicit
        # head_dim decoupled from hidden_size/num_heads. The MoE variant
        # is mixtral-shaped (softmax -> top-k, with norm_topk_prob
        # driving the renormalize — cfg.moe_norm_topk).
        sw, aw, _ = _layer_windows_from_hf(hf_config)
        q3_inv_freq, q3_attn_factor, _ = _rope_scaling_params(
            hf_config, getattr(hf_config, "head_dim", None)
            or hf_config.hidden_size // hf_config.num_attention_heads, mt)
        num_experts = 0
        if mt == "qwen3_moe":
            num_experts = hf_config.num_experts
            if list(getattr(hf_config, "mlp_only_layers", []) or []):
                raise NotImplementedError("qwen3_moe with mlp_only_layers")
            if getattr(hf_config, "decoder_sparse_step", 1) != 1:
                raise NotImplementedError(
                    "qwen3_moe with decoder_sparse_step != 1")
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="llama", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=(hf_config.moe_intermediate_size
                               if num_experts
                               else hf_config.intermediate_size),
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or hf_config.num_attention_heads,
            head_dim=getattr(hf_config, "head_dim", None)
            or hf_config.hidden_size // hf_config.num_attention_heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="rmsnorm", norm_eps=hf_config.rms_norm_eps,
            activation=_act_from_hf(hf_config.hidden_act),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            rope_inv_freq=q3_inv_freq, rope_attn_factor=q3_attn_factor,
            attn_bias=getattr(hf_config, "attention_bias", False),
            mlp_bias=False, qk_norm="rms_head",
            sliding_window=sw, attn_windows=aw,
            num_experts=num_experts,
            num_experts_per_tok=getattr(hf_config, "num_experts_per_tok",
                                        2),
            moe_norm_topk=bool(getattr(hf_config, "norm_topk_prob", True)),
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False))
    if mt == "ernie4_5":
        # ERNIE 4.5 (dense): llama layout with ONE use_bias switch on
        # every linear (attention, o and MLP alike) and an explicit
        # head_dim decoupled from hidden/heads.
        b = bool(getattr(hf_config, "use_bias", False))
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="llama", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or hf_config.num_attention_heads,
            head_dim=getattr(hf_config, "head_dim", None)
            or hf_config.hidden_size // hf_config.num_attention_heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="rmsnorm", norm_eps=hf_config.rms_norm_eps,
            activation=_act_from_hf(hf_config.hidden_act),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            attn_bias=b, o_bias=b, mlp_bias=b,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        True))
    if mt == "smollm3":
        # SmolLM3: llama layout with per-layer NoPE (no_rope_layers: 1 =
        # rotate, 0 = position-free — config.py rope_layers) and
        # optional per-layer sliding windows via layer_types.
        sw, aw, _ = _layer_windows_from_hf(hf_config, require_use_flag=True)
        nope = tuple(int(v) for v in
                     getattr(hf_config, "no_rope_layers", None) or [])
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="llama", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or hf_config.num_attention_heads,
            head_dim=getattr(hf_config, "head_dim", None)
            or hf_config.hidden_size // hf_config.num_attention_heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="rmsnorm", norm_eps=hf_config.rms_norm_eps,
            activation=_act_from_hf(hf_config.hidden_act),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            attn_bias=bool(getattr(hf_config, "attention_bias", False)),
            mlp_bias=bool(getattr(hf_config, "mlp_bias", False)),
            sliding_window=sw, attn_windows=aw,
            rope_layers=(nope if nope and not all(nope) else None),
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        True))
    if mt == "hunyuan_v1_dense":
        # HunYuan-Dense: llama layout + shared [head_dim] q/k RMS norms
        # applied AFTER RoPE (qk_norm_after_rope — qwen3/exaone norm
        # before rotating).
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="llama", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or hf_config.num_attention_heads,
            head_dim=getattr(hf_config, "head_dim", None)
            or hf_config.hidden_size // hf_config.num_attention_heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="rmsnorm", norm_eps=hf_config.rms_norm_eps,
            activation=_act_from_hf(hf_config.hidden_act),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            attn_bias=bool(getattr(hf_config, "attention_bias", False)),
            mlp_bias=False, qk_norm="rms_head", qk_norm_after_rope=True,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False))
    if mt == "exaone4":
        # EXAONE 4.0: the olmo2 sublayer-postnorm topology (x +
        # norm(f(x)), norms named post_attention/post_feedforward) with
        # shared [head_dim] q/k RMS norms, hybrid attention — sliding
        # layers rotate, full-attention layers are NoPE (rope_layers) —
        # and per-layer windows from layer_types.
        sw, aw, kinds = _layer_windows_from_hf(hf_config)
        windowed = sw is not None or aw is not None
        rope_on = (tuple(1 if t == "sliding_attention" else 0
                         for t in kinds) if windowed else None)
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="olmo2", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or hf_config.num_attention_heads,
            head_dim=getattr(hf_config, "head_dim", None)
            or hf_config.hidden_size // hf_config.num_attention_heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="rmsnorm", norm_eps=hf_config.rms_norm_eps,
            activation=_act_from_hf(hf_config.hidden_act),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            attn_bias=False, mlp_bias=False, qk_norm="rms_head",
            sublayer_postnorm_only=True,
            sliding_window=sw, attn_windows=aw, rope_layers=rope_on,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False))
    if mt == "gpt_oss":
        # gpt-oss: llama-shaped attention (GQA, biases, yarn rope,
        # alternating sliding/full layers) plus two mechanisms of its
        # own — learned per-head attention SINKS (a virtual softmax
        # column, config.py attn_sinks / ops/attention.attend) and a
        # clamped-swish expert GLU with per-expert biases
        # (moe_swiglu_limit/alpha, transformer._glu_h) under a
        # top-k-then-softmax router whose bias is part of the linear
        # (moe_router="topk_softmax"). HF modeling_gpt_oss.py.
        hd = (getattr(hf_config, "head_dim", None)
              or hf_config.hidden_size // hf_config.num_attention_heads)
        go_inv_freq, go_attn_factor, _ = _rope_scaling_params(
            hf_config, hd, mt)
        sw, aw, _ = _layer_windows_from_hf(hf_config)
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="gpt_oss", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or hf_config.num_attention_heads,
            head_dim=hd,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="rmsnorm", norm_eps=hf_config.rms_norm_eps,
            activation="silu",   # unused by the clamped GLU, kept sane
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 150000.0),
            rope_inv_freq=go_inv_freq, rope_attn_factor=go_attn_factor,
            attn_bias=bool(getattr(hf_config, "attention_bias", True)),
            mlp_bias=True,   # per-expert biases ride the expert leaves
            sliding_window=sw, attn_windows=aw,
            attn_sinks=True,
            num_experts=hf_config.num_local_experts,
            num_experts_per_tok=getattr(hf_config, "num_experts_per_tok",
                                        4),
            moe_router="topk_softmax",
            moe_swiglu_limit=float(getattr(hf_config, "swiglu_limit",
                                           7.0)),
            moe_swiglu_alpha=1.702,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False))
    if mt == "hunyuan_v1_moe":
        # HunYuan-MoE: the hunyuan dense layout (post-RoPE per-head q/k
        # RMS norms) with mixtral-convention routing (softmax -> top-k
        # -> renormalize) and an always-active shared MLP of the same
        # intermediate width.
        ne = hf_config.num_experts
        tk = getattr(hf_config, "moe_topk", 1)
        if not isinstance(ne, int) or not isinstance(tk, int):
            raise NotImplementedError(
                "hunyuan_v1_moe with per-layer num_experts/moe_topk "
                "lists")
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="llama", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or hf_config.num_attention_heads,
            head_dim=getattr(hf_config, "head_dim", None)
            or hf_config.hidden_size // hf_config.num_attention_heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="rmsnorm", norm_eps=hf_config.rms_norm_eps,
            activation=_act_from_hf(hf_config.hidden_act),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            attn_bias=bool(getattr(hf_config, "attention_bias", False)),
            mlp_bias=False, qk_norm="rms_head", qk_norm_after_rope=True,
            num_experts=ne, num_experts_per_tok=tk,
            moe_shared_experts=1,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False))
    if mt == "ernie4_5_moe":
        # ERNIE 4.5 MoE: the dense ernie4_5 layout with softmax routing
        # under deepseek-style bias-corrected SELECTION (moe_statics.
        # e_score_correction_bias, moe_router="ernie"), shared experts,
        # and a dense prefix (moe_layer_start_index). Every-Nth-layer
        # MoE interleaving (moe_layer_interval > 1) and early MoE end
        # are refused — the segment machinery models prefix+tail only.
        L = hf_config.num_hidden_layers
        if getattr(hf_config, "moe_layer_interval", 1) != 1:
            raise NotImplementedError(
                "ernie4_5_moe with moe_layer_interval != 1")
        if getattr(hf_config, "moe_layer_end_index", L - 1) not in (
                -1, L - 1):
            raise NotImplementedError(
                "ernie4_5_moe with moe_layer_end_index before the last "
                "layer")
        fk = getattr(hf_config, "moe_layer_start_index", 0) or 0
        mixed = 0 < fk < L
        b = bool(getattr(hf_config, "use_bias", False))
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="llama", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.moe_intermediate_size,
            num_layers=L, num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or hf_config.num_attention_heads,
            head_dim=getattr(hf_config, "head_dim", None)
            or hf_config.hidden_size // hf_config.num_attention_heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="rmsnorm", norm_eps=hf_config.rms_norm_eps,
            activation=_act_from_hf(hf_config.hidden_act),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            attn_bias=b, o_bias=b, mlp_bias=b,
            num_experts=hf_config.moe_num_experts,
            num_experts_per_tok=getattr(hf_config, "moe_k", 2),
            moe_router="ernie",
            moe_shared_experts=(getattr(hf_config,
                                        "moe_num_shared_experts", 0)
                                or 0),
            dense_prefix_layers=fk if mixed else 0,
            dense_intermediate_size=(hf_config.intermediate_size
                                     if mixed else None),
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        True))
    if mt == "glm4_moe":
        # GLM-4.5 (MoE): llama block topology with optional per-head
        # q/k RMS norms (pre-rope, qwen3-style), partial half-split
        # rotary, and DeepSeek-V3's exact routing — sigmoid scores,
        # e_score_correction_bias group-limited top-k, shared experts —
        # over a first_k_dense_replace mixed dense/MoE stack (HF
        # modeling_glm4_moe.py Glm4MoeTopkRouter is byte-for-byte
        # deepseek's).
        L = hf_config.num_hidden_layers
        fk = getattr(hf_config, "first_k_dense_replace", 0) or 0
        all_dense = fk >= L
        E = 0 if all_dense else hf_config.n_routed_experts
        mixed = 0 < fk < L
        hd = (getattr(hf_config, "head_dim", None)
              or hf_config.hidden_size // hf_config.num_attention_heads)
        pct = float(getattr(hf_config, "partial_rotary_factor", 1.0))
        gm_inv_freq, gm_attn_factor, _ = _rope_scaling_params(
            hf_config, int(hd * pct), mt)
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="llama", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=(hf_config.intermediate_size if all_dense
                               else hf_config.moe_intermediate_size),
            num_layers=L, num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or hf_config.num_attention_heads,
            head_dim=hd,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="rmsnorm", norm_eps=hf_config.rms_norm_eps,
            activation=_act_from_hf(hf_config.hidden_act),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            rope_pct=pct,
            rope_inv_freq=gm_inv_freq, rope_attn_factor=gm_attn_factor,
            attn_bias=bool(getattr(hf_config, "attention_bias", False)),
            o_bias=False, mlp_bias=False,
            qk_norm=("rms_head" if getattr(hf_config, "use_qk_norm",
                                           False) else None),
            num_experts=E,
            num_experts_per_tok=getattr(hf_config, "num_experts_per_tok",
                                        8),
            moe_router="deepseek_v3" if E else "softmax",
            moe_n_group=getattr(hf_config, "n_group", 1) or 1,
            moe_topk_group=getattr(hf_config, "topk_group", 1) or 1,
            moe_routed_scale=float(getattr(hf_config,
                                           "routed_scaling_factor", 1.0)),
            moe_norm_topk=bool(getattr(hf_config, "norm_topk_prob", True)),
            moe_shared_experts=(getattr(hf_config, "n_shared_experts", 0)
                                or 0) if E else 0,
            dense_prefix_layers=fk if mixed else 0,
            dense_intermediate_size=(hf_config.intermediate_size
                                     if mixed else None),
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False))
    if mt == "dbrx":
        # DBRX: the standard pre-LN sequential block under unusual
        # naming (norm_attn_norm.norm_1/norm_2 ≡ attn/mlp pre-norms,
        # bias-free LayerNorms), fused Wqkv with the clip_qkv activation
        # clamp (config.py qkv_clip), and a 16-expert GLU MoE whose
        # router renormalizes top-k weights by their p-norm —
        # p=1 over softmax weights == our renorm; None == no renorm
        # (moe_norm_topk); other p values are refused.
        ac, fc = hf_config.attn_config, hf_config.ffn_config
        p = getattr(fc, "moe_normalize_expert_weights", 1.0)
        if p is not None and float(p) != 1.0:
            raise NotImplementedError(
                f"dbrx moe_normalize_expert_weights={p} — only 1.0 "
                "(L1 over positive softmax weights == renormalize) or "
                "None convert")
        act = getattr(fc, "ffn_act_fn", None) or {}
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="dbrx", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.d_model,
            intermediate_size=fc.ffn_hidden_size,
            num_layers=hf_config.n_layers, num_heads=hf_config.n_heads,
            num_kv_heads=ac.kv_n_heads,
            head_dim=hf_config.d_model // hf_config.n_heads,
            max_position_embeddings=hf_config.max_seq_len,
            norm_type="layernorm",
            activation=_act_from_hf(act.get("name", "silu")),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(ac, "rope_theta", 10000.0),
            attn_bias=False, mlp_bias=False,
            qkv_clip=(float(ac.clip_qkv) if getattr(ac, "clip_qkv", None)
                      else None),
            num_experts=fc.moe_num_experts,
            num_experts_per_tok=fc.moe_top_k,
            moe_norm_topk=p is not None,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False))
    if mt == "deepseek_v3":
        # DeepSeek-V3: llama residual topology with multi-head latent
        # attention (low-rank q/kv bottlenecks with mid-stack RMSNorms,
        # decoupled shared-rope head — config.py kv_lora_rank and
        # transformer._mla_qkv) and sigmoid/group-limited MoE routing
        # with always-active shared experts (transformer._moe_gates
        # "deepseek_v3"). HF: modeling_deepseek_v3.py.
        nd = hf_config.qk_nope_head_dim
        rd = hf_config.qk_rope_head_dim
        # the rope ladder spans only the decoupled rope head (dim=rd —
        # HF's DeepseekV3Config sets head_dim accordingly)
        inv_freq, attn_factor, score_scale = _rope_scaling_params(
            hf_config, rd, mt)
        # yarn's mscale_all_dim multiplier scales SCORES uniformly by
        # score_scale**2 (HF modeling_deepseek_v3.py:372-377); fold it
        # into the q weights via the query_pre_attn_scalar absorption
        # (conversion scales q by sqrt(hd/qpas) — pick qpas so that
        # equals score_scale**2)
        qpas = None
        if score_scale != 1.0:
            qpas = (nd + rd) / score_scale ** 4
        L = hf_config.num_hidden_layers
        fk = getattr(hf_config, "first_k_dense_replace", 0) or 0
        # fk >= L: every layer dense (num_experts=0). 0 < fk < L: the
        # shipped V3/V2 layout — a dense prefix segment ahead of the MoE
        # tail (config.py dense_prefix_layers; the layer scans run the
        # two stacked segments back to back, transformer.layer_segments)
        all_dense = fk >= L
        E = 0 if all_dense else hf_config.n_routed_experts
        mixed = 0 < fk < L
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="deepseek", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=(hf_config.intermediate_size if all_dense
                               else hf_config.moe_intermediate_size),
            num_layers=L, num_heads=hf_config.num_attention_heads,
            num_kv_heads=hf_config.num_attention_heads,
            head_dim=nd + rd,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="rmsnorm", norm_eps=hf_config.rms_norm_eps,
            activation=_act_from_hf(hf_config.hidden_act),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            rope_interleaved=bool(getattr(hf_config, "rope_interleave",
                                          True)),
            rope_inv_freq=inv_freq, rope_attn_factor=attn_factor,
            query_pre_attn_scalar=qpas,
            attn_bias=bool(getattr(hf_config, "attention_bias", False)),
            mlp_bias=False,
            q_lora_rank=getattr(hf_config, "q_lora_rank", None),
            kv_lora_rank=hf_config.kv_lora_rank,
            qk_nope_head_dim=nd, qk_rope_head_dim=rd,
            v_head_dim=hf_config.v_head_dim,
            num_experts=E,
            num_experts_per_tok=getattr(hf_config, "num_experts_per_tok",
                                        8),
            moe_router="deepseek_v3" if E else "softmax",
            moe_n_group=getattr(hf_config, "n_group", 1) or 1,
            moe_topk_group=getattr(hf_config, "topk_group", 1) or 1,
            moe_routed_scale=float(getattr(hf_config,
                                           "routed_scaling_factor", 1.0)),
            moe_norm_topk=bool(getattr(hf_config, "norm_topk_prob", True)),
            moe_shared_experts=(getattr(hf_config, "n_shared_experts", 0)
                                or 0) if E else 0,
            dense_prefix_layers=fk if mixed else 0,
            dense_intermediate_size=(hf_config.intermediate_size
                                     if mixed else None),
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False))
    if mt == "granite":
        # Granite 3.x: llama layout with four scalar multipliers, all
        # absorbed into existing mechanisms — embedding_multiplier ->
        # embed_scale, attention_multiplier -> query_pre_attn_scalar
        # (HF scales scores by am == qpas**-0.5, so qpas = am**-2; the
        # ratio folds into the q weights at conversion),
        # residual_multiplier -> residual_scale, and 1/logits_scaling ->
        # logit_scale.
        am = float(getattr(hf_config, "attention_multiplier", 1.0))
        ls = float(getattr(hf_config, "logits_scaling", 1.0))
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="llama", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or hf_config.num_attention_heads,
            head_dim=hf_config.hidden_size
            // hf_config.num_attention_heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="rmsnorm", norm_eps=hf_config.rms_norm_eps,
            activation=_act_from_hf(hf_config.hidden_act),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            attn_bias=getattr(hf_config, "attention_bias", False),
            mlp_bias=getattr(hf_config, "mlp_bias", False),
            embed_scale=float(getattr(hf_config, "embedding_multiplier",
                                      1.0)),
            query_pre_attn_scalar=am ** -2,
            residual_scale=float(getattr(hf_config,
                                         "residual_multiplier", 1.0)),
            logit_scale=1.0 / ls,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False))
    if mt == "olmo2":
        # OLMo-2: llama dims, but norms move to the sublayer OUTPUTS
        # (x + norm(f(x)), no pre-norms) and full-width RMS q/k norms on
        # the projections.
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="olmo2", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or hf_config.num_attention_heads,
            head_dim=hf_config.hidden_size
            // hf_config.num_attention_heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="rmsnorm", norm_eps=hf_config.rms_norm_eps,
            activation=_act_from_hf(hf_config.hidden_act),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            attn_bias=getattr(hf_config, "attention_bias", False),
            mlp_bias=False, qk_norm="rms_full",
            sublayer_postnorm_only=True,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False))
    if mt in ("glm", "glm4"):
        # GLM-4 lineage: llama dims with a fused gate_up MLP (split at
        # conversion), INTERLEAVED rotary over the first
        # partial_rotary_factor of head_dim (GPT-J pairing — HF glm's
        # local rotate_half is the 0::2/1::2 stack), q/k/v bias without
        # o bias, explicit head_dim. glm4 additionally sandwiches each
        # sublayer with post norms (post_self_attn/post_mlp_layernorm ->
        # post_block_norms).
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="glm", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or hf_config.num_attention_heads,
            head_dim=getattr(hf_config, "head_dim", None)
            or hf_config.hidden_size // hf_config.num_attention_heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="rmsnorm", norm_eps=hf_config.rms_norm_eps,
            activation=_act_from_hf(hf_config.hidden_act),
            gated_mlp=True, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            rope_pct=float(getattr(hf_config, "partial_rotary_factor",
                                   0.5)),
            rope_interleaved=True,
            attn_bias=bool(getattr(hf_config, "attention_bias", True)),
            o_bias=False, mlp_bias=False,
            post_block_norms=(mt == "glm4"),
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False))
    if mt == "nemotron":
        # Nemotron: ungated squared-ReLU MLP, LayerNorm1P ((1+w) scale,
        # absorbed at conversion like gemma's rmsnorm offset), partial
        # non-interleaved rotary, untied head, no biases.
        return ModelConfig(
            name=getattr(hf_config, "name_or_path", mt) or mt,
            family="nemotron", vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None)
            or hf_config.num_attention_heads,
            head_dim=getattr(hf_config, "head_dim", None)
            or hf_config.hidden_size // hf_config.num_attention_heads,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_type="layernorm",
            norm_eps=getattr(hf_config, "norm_eps", 1e-5),
            activation=_act_from_hf(hf_config.hidden_act),
            gated_mlp=False, position_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            rope_pct=float(getattr(hf_config, "partial_rotary_factor",
                                   0.5)),
            attn_bias=bool(getattr(hf_config, "attention_bias", False)),
            mlp_bias=bool(getattr(hf_config, "mlp_bias", False)),
            norm_offset=True,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings",
                                        False))
    raise NotImplementedError(
        f"unsupported HF model_type {mt!r}; supported: "
        f"{', '.join(SUPPORTED_MODEL_TYPES)}")


def _stack(dicts):
    """list of {leaf: np [..]} -> {leaf: np [L, ..]} recursively."""
    out = {}
    for k in dicts[0]:
        if isinstance(dicts[0][k], dict):
            out[k] = _stack([d[k] for d in dicts])
        else:
            out[k] = np.stack([d[k] for d in dicts])
    return out


def convert_state_dict(cfg: ModelConfig, sd, dtype=None):
    """HF state dict (name -> torch tensor/np array) -> our param pytree."""
    dtype = dtype or jnp.dtype(cfg.dtype)
    fam = cfg.family
    D = cfg.hidden_size

    def get(name):
        return _np(sd[name])

    if fam == "gpt2":
        def layer(i):
            p = f"transformer.h.{i}."
            cattn_w = get(p + "attn.c_attn.weight")  # [D, 3D] (Conv1D: in,out)
            cattn_b = get(p + "attn.c_attn.bias")
            return {
                "attn_norm": {"scale": get(p + "ln_1.weight"),
                              "bias": get(p + "ln_1.bias")},
                "q": {"w": cattn_w[:, :D], "b": cattn_b[:D]},
                "k": {"w": cattn_w[:, D:2 * D], "b": cattn_b[D:2 * D]},
                "v": {"w": cattn_w[:, 2 * D:], "b": cattn_b[2 * D:]},
                "o": {"w": get(p + "attn.c_proj.weight"),
                      "b": get(p + "attn.c_proj.bias")},
                "mlp_norm": {"scale": get(p + "ln_2.weight"),
                             "bias": get(p + "ln_2.bias")},
                "up": {"w": get(p + "mlp.c_fc.weight"),
                       "b": get(p + "mlp.c_fc.bias")},
                "down": {"w": get(p + "mlp.c_proj.weight"),
                         "b": get(p + "mlp.c_proj.bias")},
            }
        params = {
            "embed": {"tokens": get("transformer.wte.weight"),
                      "positions": get("transformer.wpe.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("transformer.ln_f.weight"),
                           "bias": get("transformer.ln_f.bias")},
        }
    elif fam == "opt":
        def layer(i):
            p = f"model.decoder.layers.{i}."
            def lin(n):  # torch Linear stores [out, in] -> transpose
                return {"w": get(p + n + ".weight").T, "b": get(p + n + ".bias")}
            return {
                "attn_norm": {"scale": get(p + "self_attn_layer_norm.weight"),
                              "bias": get(p + "self_attn_layer_norm.bias")},
                "q": lin("self_attn.q_proj"),
                "k": lin("self_attn.k_proj"),
                "v": lin("self_attn.v_proj"),
                "o": lin("self_attn.out_proj"),
                "mlp_norm": {"scale": get(p + "final_layer_norm.weight"),
                             "bias": get(p + "final_layer_norm.bias")},
                "up": lin("fc1"),
                "down": lin("fc2"),
            }
        params = {
            "embed": {
                "tokens": get("model.decoder.embed_tokens.weight"),
                # OPT's learned positions are offset by 2 internally
                # (transformers OPTLearnedPositionalEmbedding); slice here so
                # position p indexes row p.
                "positions": get("model.decoder.embed_positions.weight")[2:],
            },
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
        }
        if not cfg.post_norm:   # opt-350m (post-LN) has no final norm
            params["final_norm"] = {
                "scale": get("model.decoder.final_layer_norm.weight"),
                "bias": get("model.decoder.final_layer_norm.bias")}
        if cfg.embed_proj_dim:
            params["embed"]["project_in"] = {
                "w": get("model.decoder.project_in.weight").T}
            params["embed"]["project_out"] = {
                "w": get("model.decoder.project_out.weight").T}
    elif fam == "llama":
        # gemma stores rmsnorm weights in the (1 + w) convention; absorb
        # the offset here so the runtime norm stays plain (config.py
        # norm_offset)
        off = 1.0 if cfg.norm_offset else 0.0
        # granite: attention_multiplier replaces the 1/sqrt(hd) score
        # scale via query_pre_attn_scalar — fold the ratio into q (same
        # absorption as the gemma2 branch)
        qs = (cfg.head_dim / (cfg.query_pre_attn_scalar
                              or cfg.head_dim)) ** 0.5

        def layer(i, moe):
            p = f"model.layers.{i}."
            def lin(n, scale=1.0):
                out = {"w": get(p + n + ".weight").T * scale}
                if p + n + ".bias" in sd:  # attention_bias / mlp_bias variants
                    out["b"] = get(p + n + ".bias") * scale
                return out
            lp = {
                "attn_norm": {"scale": get(p + "input_layernorm.weight") + off},
                # under qk_norm the q RMS-normalize erases any weight
                # scale, so the qs fold moves to the q_norm scale below
                "q": lin("self_attn.q_proj", 1.0 if cfg.qk_norm else qs),
                "k": lin("self_attn.k_proj"),
                "v": lin("self_attn.v_proj"),
                "o": lin("self_attn.o_proj"),
                "mlp_norm": {"scale": get(p + "post_attention_layernorm.weight") + off},
            }
            if cfg.qk_norm:   # shared [head_dim] rms scales — qwen3
                # names them q_norm/k_norm, hunyuan query_layernorm/
                # key_layernorm
                qn = ("self_attn.q_norm.weight"
                      if p + "self_attn.q_norm.weight" in sd
                      else "self_attn.query_layernorm.weight")
                kn = ("self_attn.k_norm.weight"
                      if p + "self_attn.k_norm.weight" in sd
                      else "self_attn.key_layernorm.weight")
                lp["q_norm"] = {"scale": get(p + qn) * qs}
                lp["k_norm"] = {"scale": get(p + kn)}
            rn = next((c for c in ("mlp.gate.weight", "mlp.gate.wg.weight")
                       if p + c in sd), None)
            if moe and rn:
                # qwen3_moe / glm4_moe name the router mlp.gate,
                # hunyuan_v1_moe wraps it as mlp.gate.wg; experts are
                # mlp.experts.N.{gate,up,down}_proj either way
                lp["router"] = {"w": get(p + rn).T}
                if cfg.moe_router in ("deepseek_v3", "ernie"):
                    # glm4_moe names the bias under the gate; ernie
                    # under moe_statics (shape [1, E] — squeeze)
                    bn = p + "mlp.gate.e_score_correction_bias"
                    if bn in sd:
                        lp["router"]["bias"] = get(bn)
                    else:
                        lp["router"]["bias"] = get(
                            p + "mlp.moe_statics.e_score_correction_bias"
                        ).reshape(-1)
                ex = [f"mlp.experts.{e}." for e in range(cfg.num_experts)]
                lp["experts"] = {
                    "gate": {"w": np.stack([get(p + e + "gate_proj.weight").T for e in ex])},
                    "up": {"w": np.stack([get(p + e + "up_proj.weight").T for e in ex])},
                    "down": {"w": np.stack([get(p + e + "down_proj.weight").T for e in ex])},
                }
                if p + ex[0] + "gate_proj.bias" in sd:
                    # ernie4_5_moe use_bias=True: per-expert biases
                    for nm, pj in (("gate", "gate_proj"), ("up", "up_proj"),
                                   ("down", "down_proj")):
                        lp["experts"][nm]["b"] = np.stack(
                            [get(p + e + f"{pj}.bias") for e in ex])
                if cfg.moe_shared_experts:
                    s = ("mlp.shared_experts."
                         if p + "mlp.shared_experts.gate_proj.weight" in sd
                         else "mlp.shared_mlp.")   # hunyuan_v1_moe
                    lp["shared_gate"] = lin(s + "gate_proj")
                    lp["shared_up"] = lin(s + "up_proj")
                    lp["shared_down"] = lin(s + "down_proj")
            elif moe:
                lp["router"] = {"w": get(p + "block_sparse_moe.gate.weight").T}
                ex = [f"block_sparse_moe.experts.{e}." for e in range(cfg.num_experts)]
                lp["experts"] = {
                    "gate": {"w": np.stack([get(p + e + "w1.weight").T for e in ex])},
                    "down": {"w": np.stack([get(p + e + "w2.weight").T for e in ex])},
                    "up": {"w": np.stack([get(p + e + "w3.weight").T for e in ex])},
                }
            else:
                lp["gate"] = lin("mlp.gate_proj")
                lp["up"] = lin("mlp.up_proj")
                lp["down"] = lin("mlp.down_proj")
            return lp
        pref = cfg.dense_prefix_layers
        params = {
            "embed": {"tokens": get("model.embed_tokens.weight")},
            "layers": _stack([layer(i, cfg.is_moe)
                              for i in range(pref, cfg.num_layers)]),
            "final_norm": {"scale": get("model.norm.weight") + off},
        }
        if pref:   # glm4_moe first_k_dense_replace: dense prefix segment
            params["layers_dense"] = _stack(
                [layer(i, False) for i in range(pref)])
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    elif fam == "dbrx":
        # transformer.blocks.N.norm_attn_norm.{norm_1, attn.Wqkv,
        # attn.out_proj, norm_2} + ffn.{router.layer, experts.mlp.
        # {w1,v1,w2}}; LayerNorms are bias-free (zero bias is the exact
        # parametric equivalent), experts are FUSED [E*I, D] stacks —
        # w1/v1 contract transposed (gate/up), w2 contracts as stored
        # (down, HF DbrxExpertGLU.forward).
        D = cfg.hidden_size
        E, I = cfg.num_experts, cfg.intermediate_size
        kvd = cfg.num_kv_heads * cfg.head_dim
        zb = np.zeros((D,), np.float32)

        def layer(i):
            p = f"transformer.blocks.{i}."
            qkv = get(p + "norm_attn_norm.attn.Wqkv.weight").T  # [D,D+2kvd]
            w1 = get(p + "ffn.experts.mlp.w1").reshape(E, I, D)
            v1 = get(p + "ffn.experts.mlp.v1").reshape(E, I, D)
            w2 = get(p + "ffn.experts.mlp.w2").reshape(E, I, D)
            return {
                "attn_norm": {
                    "scale": get(p + "norm_attn_norm.norm_1.weight"),
                    "bias": zb},
                "q": {"w": qkv[:, :D]},
                "k": {"w": qkv[:, D:D + kvd]},
                "v": {"w": qkv[:, D + kvd:]},
                "o": {"w": get(p + "norm_attn_norm.attn.out_proj.weight").T},
                "mlp_norm": {
                    "scale": get(p + "norm_attn_norm.norm_2.weight"),
                    "bias": zb},
                "router": {"w": get(p + "ffn.router.layer.weight").T},
                "experts": {
                    "gate": {"w": np.swapaxes(w1, 1, 2)},   # [E, D, I]
                    "up": {"w": np.swapaxes(v1, 1, 2)},
                    "down": {"w": w2},                      # [E, I, D]
                },
            }
        params = {
            "embed": {"tokens": get("transformer.wte.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("transformer.norm_f.weight"),
                           "bias": zb},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    elif fam == "gpt_oss":
        # llama projection names with biases + self_attn.sinks per
        # layer; fused-interleaved expert stacks: gate_up_proj
        # [E, D, 2I] with gate at even and up at odd columns (HF
        # GptOssExperts gate_up[..., ::2]/[..., 1::2]); down_proj
        # [E, I, D] contracts as stored; router is mlp.router (a real
        # linear with bias).
        def layer(i):
            p = f"model.layers.{i}."

            def lin(n):
                out = {"w": get(p + n + ".weight").T}
                if p + n + ".bias" in sd:
                    out["b"] = get(p + n + ".bias")
                return out
            gu = get(p + "mlp.experts.gate_up_proj")        # [E, D, 2I]
            gub = get(p + "mlp.experts.gate_up_proj_bias")  # [E, 2I]
            return {
                "attn_norm": {"scale": get(p + "input_layernorm.weight")},
                "q": lin("self_attn.q_proj"),
                "k": lin("self_attn.k_proj"),
                "v": lin("self_attn.v_proj"),
                "o": lin("self_attn.o_proj"),
                "sinks": get(p + "self_attn.sinks"),
                "mlp_norm": {
                    "scale": get(p + "post_attention_layernorm.weight")},
                "router": {"w": get(p + "mlp.router.weight").T,
                           "bias": get(p + "mlp.router.bias")},
                "experts": {
                    "gate": {"w": gu[..., 0::2], "b": gub[..., 0::2]},
                    "up": {"w": gu[..., 1::2], "b": gub[..., 1::2]},
                    "down": {"w": get(p + "mlp.experts.down_proj"),
                             "b": get(p + "mlp.experts.down_proj_bias")},
                },
            }
        params = {
            "embed": {"tokens": get("model.embed_tokens.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("model.norm.weight")},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    elif fam == "deepseek":
        # MLA projections (HF modeling_deepseek_v3.py:327-446). Our
        # runtime orders per-head q/k dims [rope | nope] (HF: [nope |
        # rope]) so the rope slice is contiguous where apply_rope
        # rotates — a score-invariant permutation applied here to the q
        # projection columns (k is assembled in that order at runtime:
        # kv_a's rope slice + kv_b's nope columns, transformer._mla_qkv).
        H, hd = cfg.num_heads, cfg.head_dim
        nd, rd = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
        vd = cfg.v_head_dim_effective
        # yarn mscale_all_dim: HF multiplies scores by score_scale**2
        # uniformly; config_from_hf encoded score_scale**2 as the
        # query_pre_attn_scalar absorption (qs == sqrt(hd/qpas)) — the
        # scalar commutes with the projection AND the rope rotation, so
        # scaling q here is exact
        qs = (hd / (cfg.query_pre_attn_scalar or hd)) ** 0.5

        def q_permute(w):
            """[din, H*hd] with per-head [nope|rope] -> [rope|nope]."""
            w = w.reshape(-1, H, hd)
            return np.concatenate([w[..., nd:], w[..., :nd]],
                                  axis=-1).reshape(-1, H * hd) * qs

        def layer(i, moe):
            p = f"model.layers.{i}."

            def lin(n):
                out = {"w": get(p + n + ".weight").T}
                if p + n + ".bias" in sd:   # attention_bias variants
                    out["b"] = get(p + n + ".bias")
                return out
            kv_b = get(p + "self_attn.kv_b_proj.weight").T  # [r, H*(nd+vd)]
            kv_b = kv_b.reshape(-1, H, nd + vd)
            lp = {
                "attn_norm": {"scale": get(p + "input_layernorm.weight")},
                "kv_a": lin("self_attn.kv_a_proj_with_mqa"),
                "kv_a_norm": {
                    "scale": get(p + "self_attn.kv_a_layernorm.weight")},
                "kv_b_k": {"w": kv_b[..., :nd].reshape(-1, H * nd)},
                "kv_b_v": {"w": kv_b[..., nd:].reshape(-1, H * vd)},
                "o": lin("self_attn.o_proj"),
                "mlp_norm": {
                    "scale": get(p + "post_attention_layernorm.weight")},
            }
            if cfg.q_lora_rank:
                lp["q_a"] = lin("self_attn.q_a_proj")
                lp["q_a_norm"] = {
                    "scale": get(p + "self_attn.q_a_layernorm.weight")}
                lp["q_b"] = {
                    "w": q_permute(get(p + "self_attn.q_b_proj.weight").T)}
            else:
                lp["q"] = {
                    "w": q_permute(get(p + "self_attn.q_proj.weight").T)}
            if moe:
                lp["router"] = {
                    "w": get(p + "mlp.gate.weight").T,
                    "bias": get(p + "mlp.gate.e_score_correction_bias"),
                }
                ex = [f"mlp.experts.{e}." for e in range(cfg.num_experts)]
                lp["experts"] = {
                    "gate": {"w": np.stack(
                        [get(p + e + "gate_proj.weight").T for e in ex])},
                    "up": {"w": np.stack(
                        [get(p + e + "up_proj.weight").T for e in ex])},
                    "down": {"w": np.stack(
                        [get(p + e + "down_proj.weight").T for e in ex])},
                }
                if cfg.moe_shared_experts:
                    s = "mlp.shared_experts."
                    lp["shared_gate"] = lin(s + "gate_proj")
                    lp["shared_up"] = lin(s + "up_proj")
                    lp["shared_down"] = lin(s + "down_proj")
            else:
                lp["gate"] = lin("mlp.gate_proj")
                lp["up"] = lin("mlp.up_proj")
                lp["down"] = lin("mlp.down_proj")
            return lp
        pref = cfg.dense_prefix_layers
        params = {
            "embed": {"tokens": get("model.embed_tokens.weight")},
            "layers": _stack([layer(i, cfg.is_moe)
                              for i in range(pref, cfg.num_layers)]),
            "final_norm": {"scale": get("model.norm.weight")},
        }
        if pref:   # first_k_dense_replace: dense-MLP prefix segment
            params["layers_dense"] = _stack(
                [layer(i, False) for i in range(pref)])
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    elif fam == "gpt-neox":
        H, hd = cfg.num_heads, cfg.head_dim

        def layer(i):
            p = f"gpt_neox.layers.{i}."
            # fused QKV, per-head interleaved: out-row h*3*hd + j*hd + d
            # holds head h, kind j (q,k,v), dim d (HF GPTNeoXAttention
            # views [.., heads, 3*head_size] then splits the last axis)
            qkv_w = get(p + "attention.query_key_value.weight")  # [3Hhd, D]
            qkv_b = get(p + "attention.query_key_value.bias")
            w3 = qkv_w.reshape(H, 3, hd, D)
            b3 = qkv_b.reshape(H, 3, hd)

            def proj(j):
                return {"w": w3[:, j].reshape(H * hd, D).T,
                        "b": b3[:, j].reshape(H * hd)}
            return {
                "attn_norm": {"scale": get(p + "input_layernorm.weight"),
                              "bias": get(p + "input_layernorm.bias")},
                "q": proj(0), "k": proj(1), "v": proj(2),
                "o": {"w": get(p + "attention.dense.weight").T,
                      "b": get(p + "attention.dense.bias")},
                "mlp_norm": {
                    "scale": get(p + "post_attention_layernorm.weight"),
                    "bias": get(p + "post_attention_layernorm.bias")},
                "up": {"w": get(p + "mlp.dense_h_to_4h.weight").T,
                       "b": get(p + "mlp.dense_h_to_4h.bias")},
                "down": {"w": get(p + "mlp.dense_4h_to_h.weight").T,
                         "b": get(p + "mlp.dense_4h_to_h.bias")},
            }
        params = {
            "embed": {"tokens": get("gpt_neox.embed_in.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {
                "scale": get("gpt_neox.final_layer_norm.weight"),
                "bias": get("gpt_neox.final_layer_norm.bias")},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("embed_out.weight").T}
    elif fam == "phi":
        def layer(i):
            p = f"model.layers.{i}."

            def lin(n):
                return {"w": get(p + n + ".weight").T,
                        "b": get(p + n + ".bias")}
            # single shared layernorm (cfg.shared_attn_mlp_norm): no
            # mlp_norm leaf
            return {
                "attn_norm": {"scale": get(p + "input_layernorm.weight"),
                              "bias": get(p + "input_layernorm.bias")},
                "q": lin("self_attn.q_proj"),
                "k": lin("self_attn.k_proj"),
                "v": lin("self_attn.v_proj"),
                "o": lin("self_attn.dense"),
                "up": lin("mlp.fc1"),
                "down": lin("mlp.fc2"),
            }
        params = {
            "embed": {"tokens": get("model.embed_tokens.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("model.final_layernorm.weight"),
                           "bias": get("model.final_layernorm.bias")},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T,
                                 "b": get("lm_head.bias")}
    elif fam == "falcon":
        H, hd, KV = cfg.num_heads, cfg.head_dim, cfg.num_kv_heads
        g = H // KV
        two_norms = not cfg.shared_attn_mlp_norm   # new decoder arch

        def layer(i):
            p = f"transformer.h.{i}."
            # fused QKV, grouped per kv head: [KV, g + 2, hd] out rows —
            # g query heads then k then v per group (HF Falcon
            # _split_heads; the 7B MQA layout is the KV == 1 case)
            qkv_w = get(p + "self_attention.query_key_value.weight")
            wg = qkv_w.reshape(KV, g + 2, hd, D)
            bg = (get(p + "self_attention.query_key_value.bias"
                      ).reshape(KV, g + 2, hd) if cfg.attn_bias else None)

            def proj(sel, rows):
                out = {"w": wg[:, sel].reshape(rows * hd, D).T}
                if bg is not None:
                    out["b"] = bg[:, sel].reshape(rows * hd)
                return out

            def lin(n, bias):
                out = {"w": get(p + n + ".weight").T}
                if bias:
                    out["b"] = get(p + n + ".bias")
                return out
            lp = {
                "q": proj(slice(0, g), H),
                "k": proj(slice(g, g + 1), KV),
                "v": proj(slice(g + 1, g + 2), KV),
                "o": lin("self_attention.dense", cfg.o_bias_effective),
                "up": lin("mlp.dense_h_to_4h", cfg.mlp_bias),
                "down": lin("mlp.dense_4h_to_h", cfg.mlp_bias),
            }
            if two_norms:
                # new decoder arch names them ln_attn/ln_mlp; the RW
                # sequential layout reuses the llama-style pair
                if p + "ln_attn.weight" in sd:
                    attn_n, mlp_n = "ln_attn", "ln_mlp"
                else:
                    attn_n, mlp_n = ("input_layernorm",
                                     "post_attention_layernorm")
                lp["attn_norm"] = {"scale": get(p + attn_n + ".weight"),
                                   "bias": get(p + attn_n + ".bias")}
                lp["mlp_norm"] = {"scale": get(p + mlp_n + ".weight"),
                                  "bias": get(p + mlp_n + ".bias")}
            else:
                lp["attn_norm"] = {
                    "scale": get(p + "input_layernorm.weight"),
                    "bias": get(p + "input_layernorm.bias")}
            return lp
        params = {
            "embed": {"tokens": get("transformer.word_embeddings.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("transformer.ln_f.weight"),
                           "bias": get("transformer.ln_f.bias")},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    elif fam == "bloom":
        H, hd = cfg.num_heads, cfg.head_dim

        def layer(i):
            p = f"transformer.h.{i}."
            # fused QKV, per-head interleaved [H, 3, hd] (HF
            # BloomAttention._reshape)
            w3 = get(p + "self_attention.query_key_value.weight"
                     ).reshape(H, 3, hd, D)
            b3 = get(p + "self_attention.query_key_value.bias"
                     ).reshape(H, 3, hd)

            def proj(j):
                return {"w": w3[:, j].reshape(H * hd, D).T,
                        "b": b3[:, j].reshape(H * hd)}

            def lin(n):
                return {"w": get(p + n + ".weight").T,
                        "b": get(p + n + ".bias")}
            return {
                "attn_norm": {"scale": get(p + "input_layernorm.weight"),
                              "bias": get(p + "input_layernorm.bias")},
                "q": proj(0), "k": proj(1), "v": proj(2),
                "o": lin("self_attention.dense"),
                "mlp_norm": {
                    "scale": get(p + "post_attention_layernorm.weight"),
                    "bias": get(p + "post_attention_layernorm.bias")},
                "up": lin("mlp.dense_h_to_4h"),
                "down": lin("mlp.dense_4h_to_h"),
            }
        params = {
            "embed": {
                "tokens": get("transformer.word_embeddings.weight"),
                "norm": {
                    "scale": get(
                        "transformer.word_embeddings_layernorm.weight"),
                    "bias": get(
                        "transformer.word_embeddings_layernorm.bias")},
            },
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("transformer.ln_f.weight"),
                           "bias": get("transformer.ln_f.bias")},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    elif fam == "gptj":
        def layer(i):
            p = f"transformer.h.{i}."

            def lin(n, bias):
                out = {"w": get(p + n + ".weight").T}
                if bias:
                    out["b"] = get(p + n + ".bias")
                return out
            # single shared ln_1 (cfg.shared_attn_mlp_norm): no mlp_norm
            return {
                "attn_norm": {"scale": get(p + "ln_1.weight"),
                              "bias": get(p + "ln_1.bias")},
                "q": lin("attn.q_proj", False),
                "k": lin("attn.k_proj", False),
                "v": lin("attn.v_proj", False),
                "o": lin("attn.out_proj", False),
                "up": lin("mlp.fc_in", True),
                "down": lin("mlp.fc_out", True),
            }
        params = {
            "embed": {"tokens": get("transformer.wte.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("transformer.ln_f.weight"),
                           "bias": get("transformer.ln_f.bias")},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T,
                                 "b": get("lm_head.bias")}
    elif fam == "mpt":
        qd, kvd = cfg.q_dim, cfg.kv_dim

        def layer(i):
            p = f"transformer.blocks.{i}."

            def norm_leaf(n):
                # no_bias MPT norms carry weight only; a zero bias is the
                # exact equivalent of HF's bias=None layer_norm
                return {"scale": get(p + n + ".weight"),
                        "bias": get(p + n + ".bias")
                        if p + n + ".bias" in sd
                        else np.zeros((D,), np.float32)}

            def lin(n):
                out = {"w": get(p + n + ".weight").T}
                if p + n + ".bias" in sd:
                    out["b"] = get(p + n + ".bias")
                return out
            # straight-concat fused QKV: rows [q | k | v]
            wqkv = get(p + "attn.Wqkv.weight")          # [qd+2*kvd, D]
            lp = {
                "attn_norm": norm_leaf("norm_1"),
                "q": {"w": wqkv[:qd].T},
                "k": {"w": wqkv[qd:qd + kvd].T},
                "v": {"w": wqkv[qd + kvd:].T},
                "o": lin("attn.out_proj"),
                "mlp_norm": norm_leaf("norm_2"),
                "up": lin("ffn.up_proj"),
                "down": lin("ffn.down_proj"),
            }
            if p + "attn.Wqkv.bias" in sd:
                bqkv = get(p + "attn.Wqkv.bias")
                lp["q"]["b"] = bqkv[:qd]
                lp["k"]["b"] = bqkv[qd:qd + kvd]
                lp["v"]["b"] = bqkv[qd + kvd:]
            return lp
        params = {
            "embed": {"tokens": get("transformer.wte.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {
                "scale": get("transformer.norm_f.weight"),
                "bias": get("transformer.norm_f.bias")
                if "transformer.norm_f.bias" in sd
                else np.zeros((D,), np.float32)},
        }
    elif fam == "gpt_bigcode":
        # StarCoder: gpt2 block layout, nn.Linear (out-major) weights.
        # Fused c_attn rows: MQA stores [q (D) | k (hd) | v (hd)]
        # straight; the MHA variant is PER-HEAD interleaved
        # [q_h | k_h | v_h] per head (HF GPTBigCodeAttention views
        # [heads, 3*head_dim] before splitting).
        H, hd = cfg.num_heads, cfg.head_dim
        mqa = cfg.num_kv_heads == 1

        def layer(i):
            p = f"transformer.h.{i}."
            ca_w = get(p + "attn.c_attn.weight")
            ca_b = get(p + "attn.c_attn.bias")
            if mqa:
                qw, kw, vw = (ca_w[:D], ca_w[D:D + hd], ca_w[D + hd:])
                qb, kb, vb = (ca_b[:D], ca_b[D:D + hd], ca_b[D + hd:])
            else:
                w3 = ca_w.reshape(H, 3, hd, D)
                b3 = ca_b.reshape(H, 3, hd)
                qw, kw, vw = (w3[:, j].reshape(H * hd, D)
                              for j in range(3))
                qb, kb, vb = (b3[:, j].reshape(H * hd) for j in range(3))

            def lin(n):
                return {"w": get(p + n + ".weight").T,
                        "b": get(p + n + ".bias")}
            return {
                "attn_norm": {"scale": get(p + "ln_1.weight"),
                              "bias": get(p + "ln_1.bias")},
                "q": {"w": qw.T, "b": qb},
                "k": {"w": kw.T, "b": kb},
                "v": {"w": vw.T, "b": vb},
                "o": lin("attn.c_proj"),
                "mlp_norm": {"scale": get(p + "ln_2.weight"),
                             "bias": get(p + "ln_2.bias")},
                "up": lin("mlp.c_fc"),
                "down": lin("mlp.c_proj"),
            }
        params = {
            "embed": {"tokens": get("transformer.wte.weight"),
                      "positions": get("transformer.wpe.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("transformer.ln_f.weight"),
                           "bias": get("transformer.ln_f.bias")},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    elif fam == "stablelm":
        def layer(i):
            p = f"model.layers.{i}."

            def lin(n):
                out = {"w": get(p + n + ".weight").T}
                if p + n + ".bias" in sd:   # use_qkv_bias variants
                    out["b"] = get(p + n + ".bias")
                return out
            return {
                "attn_norm": {"scale": get(p + "input_layernorm.weight"),
                              "bias": get(p + "input_layernorm.bias")},
                "q": lin("self_attn.q_proj"),
                "k": lin("self_attn.k_proj"),
                "v": lin("self_attn.v_proj"),
                "o": lin("self_attn.o_proj"),
                "mlp_norm": {
                    "scale": get(p + "post_attention_layernorm.weight"),
                    "bias": get(p + "post_attention_layernorm.bias")},
                "gate": lin("mlp.gate_proj"),
                "up": lin("mlp.up_proj"),
                "down": lin("mlp.down_proj"),
            }
        params = {
            "embed": {"tokens": get("model.embed_tokens.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("model.norm.weight"),
                           "bias": get("model.norm.bias")},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    elif fam == "codegen":
        # Fused QKV in mp_num=4 TP blocks; within each block the order is
        # q | v | k (HF CodeGenAttention splits query, value, key), and
        # block m holds global heads [m*H/4, (m+1)*H/4) — so kind j's
        # rows, concatenated across blocks, are already in global head
        # order.
        mp = 4
        local = 3 * D // mp  # block width: q+v+k for H/4 heads

        def layer(i):
            p = f"transformer.h.{i}."

            def lin(n, bias):
                out = {"w": get(p + n + ".weight").T}
                if bias:
                    out["b"] = get(p + n + ".bias")
                return out
            wb = get(p + "attn.qkv_proj.weight").reshape(mp, local, D)

            def proj(j):  # j: 0=q, 1=v, 2=k
                third = local // 3
                return {"w": wb[:, j * third:(j + 1) * third]
                        .reshape(D, D).T}
            return {
                "attn_norm": {"scale": get(p + "ln_1.weight"),
                              "bias": get(p + "ln_1.bias")},
                "q": proj(0), "v": proj(1), "k": proj(2),
                "o": lin("attn.out_proj", False),
                "up": lin("mlp.fc_in", True),
                "down": lin("mlp.fc_out", True),
            }
        params = {
            "embed": {"tokens": get("transformer.wte.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("transformer.ln_f.weight"),
                           "bias": get("transformer.ln_f.bias")},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T,
                                 "b": get("lm_head.bias")}
    elif fam == "starcoder2":
        def layer(i):
            p = f"model.layers.{i}."

            def lin(n, bias):
                out = {"w": get(p + n + ".weight").T}
                if bias:
                    out["b"] = get(p + n + ".bias")
                return out
            return {
                "attn_norm": {"scale": get(p + "input_layernorm.weight"),
                              "bias": get(p + "input_layernorm.bias")},
                "q": lin("self_attn.q_proj", cfg.attn_bias),
                "k": lin("self_attn.k_proj", cfg.attn_bias),
                "v": lin("self_attn.v_proj", cfg.attn_bias),
                "o": lin("self_attn.o_proj", cfg.o_bias_effective),
                "mlp_norm": {
                    "scale": get(p + "post_attention_layernorm.weight"),
                    "bias": get(p + "post_attention_layernorm.bias")},
                "up": lin("mlp.c_fc", cfg.mlp_bias),
                "down": lin("mlp.c_proj", cfg.mlp_bias),
            }
        params = {
            "embed": {"tokens": get("model.embed_tokens.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("model.norm.weight"),
                           "bias": get("model.norm.bias")},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    elif fam == "olmo":
        # Non-parametric norms: HF OlmoLayerNorm has no weights at all —
        # unit scale / zero bias is its exact parametric equivalent.
        unit_norm = {"scale": np.ones((D,), np.float32),
                     "bias": np.zeros((D,), np.float32)}

        def layer(i):
            p = f"model.layers.{i}."

            def lin(n):
                out = {"w": get(p + n + ".weight").T}
                if p + n + ".bias" in sd:
                    out["b"] = get(p + n + ".bias")
                return out
            return {
                "attn_norm": dict(unit_norm),
                "q": lin("self_attn.q_proj"),
                "k": lin("self_attn.k_proj"),
                "v": lin("self_attn.v_proj"),
                "o": lin("self_attn.o_proj"),
                "mlp_norm": dict(unit_norm),
                "gate": lin("mlp.gate_proj"),
                "up": lin("mlp.up_proj"),
                "down": lin("mlp.down_proj"),
            }
        params = {
            "embed": {"tokens": get("model.embed_tokens.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": dict(unit_norm),
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    elif fam == "phi3":
        qd = cfg.num_heads * cfg.head_dim
        kvd = cfg.num_kv_heads * cfg.head_dim
        I = cfg.intermediate_size

        def layer(i):
            p = f"model.layers.{i}."
            wqkv = get(p + "self_attn.qkv_proj.weight")     # [q|k|v, D]
            wgu = get(p + "mlp.gate_up_proj.weight")        # [gate|up, D]
            return {
                "attn_norm": {"scale": get(p + "input_layernorm.weight")},
                "q": {"w": wqkv[:qd].T},
                "k": {"w": wqkv[qd:qd + kvd].T},
                "v": {"w": wqkv[qd + kvd:].T},
                "o": {"w": get(p + "self_attn.o_proj.weight").T},
                "mlp_norm": {
                    "scale": get(p + "post_attention_layernorm.weight")},
                "gate": {"w": wgu[:I].T},
                "up": {"w": wgu[I:].T},
                "down": {"w": get(p + "mlp.down_proj.weight").T},
            }
        params = {
            "embed": {"tokens": get("model.embed_tokens.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("model.norm.weight")},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    elif fam == "gpt_neo":
        # HF GPTNeo computes UNSCALED attention scores; our attend always
        # multiplies by 1/sqrt(hd), so scale q by sqrt(hd) here — exact
        # (the scalar commutes with the projection).
        qs = float(cfg.head_dim) ** 0.5

        def layer(i):
            p = f"transformer.h.{i}."

            def lin(n, bias):
                out = {"w": get(p + n + ".weight").T}
                if bias:
                    out["b"] = get(p + n + ".bias")
                return out
            lp = {
                "attn_norm": {"scale": get(p + "ln_1.weight"),
                              "bias": get(p + "ln_1.bias")},
                "q": {"w": get(p + "attn.attention.q_proj.weight").T * qs},
                "k": lin("attn.attention.k_proj", False),
                "v": lin("attn.attention.v_proj", False),
                "o": lin("attn.attention.out_proj", True),
                "mlp_norm": {"scale": get(p + "ln_2.weight"),
                             "bias": get(p + "ln_2.bias")},
                "up": lin("mlp.c_fc", True),
                "down": lin("mlp.c_proj", True),
            }
            return lp
        params = {
            "embed": {"tokens": get("transformer.wte.weight"),
                      "positions": get("transformer.wpe.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("transformer.ln_f.weight"),
                           "bias": get("transformer.ln_f.bias")},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    elif fam == "gemma2":
        # (1 + w) rmsnorm convention absorbed on ALL five norm kinds;
        # query_pre_attn_scalar**-0.5 replaces attend's 1/sqrt(hd) score
        # scale, so fold the ratio sqrt(hd / qpas) into q here — exact,
        # the scalar commutes with the projection (q_proj is bias-free).
        hd = cfg.head_dim
        qs = (hd / (cfg.query_pre_attn_scalar or hd)) ** 0.5

        def layer(i):
            p = f"model.layers.{i}."

            def nrm(n):
                return {"scale": get(p + n + ".weight") + 1.0}

            def lin(n, scale=1.0):
                out = {"w": get(p + n + ".weight").T * scale}
                if p + n + ".bias" in sd:   # attention_bias variants —
                    # the q fold scales bias with weight (commutes)
                    out["b"] = get(p + n + ".bias") * scale
                return out
            return {
                "attn_norm": nrm("input_layernorm"),
                "q": lin("self_attn.q_proj", qs),
                "k": lin("self_attn.k_proj"),
                "v": lin("self_attn.v_proj"),
                "o": lin("self_attn.o_proj"),
                "attn_post_norm": nrm("post_attention_layernorm"),
                "mlp_norm": nrm("pre_feedforward_layernorm"),
                "gate": lin("mlp.gate_proj"),
                "up": lin("mlp.up_proj"),
                "down": lin("mlp.down_proj"),
                "mlp_post_norm": nrm("post_feedforward_layernorm"),
            }
        params = {
            "embed": {"tokens": get("model.embed_tokens.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("model.norm.weight") + 1.0},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    elif fam == "cohere":
        # CohereLayerNorm has no bias — zero bias is its exact parametric
        # equivalent under our layer_norm.
        zb = np.zeros((D,), np.float32)

        def layer(i):
            p = f"model.layers.{i}."

            def lin(n):
                out = {"w": get(p + n + ".weight").T}
                if p + n + ".bias" in sd:   # attention_bias variants
                    out["b"] = get(p + n + ".bias")
                return out
            lp = {
                "attn_norm": {"scale": get(p + "input_layernorm.weight"),
                              "bias": zb},
                "q": lin("self_attn.q_proj"),
                "k": lin("self_attn.k_proj"),
                "v": lin("self_attn.v_proj"),
                "o": lin("self_attn.o_proj"),
                "gate": lin("mlp.gate_proj"),
                "up": lin("mlp.up_proj"),
                "down": lin("mlp.down_proj"),
            }
            if cfg.qk_norm:   # use_qk_norm: [H, hd] per-head scales,
                # stored flat (params.py layers["q_norm"])
                lp["q_norm"] = {"scale": get(
                    p + "self_attn.q_norm.weight").reshape(-1)}
                lp["k_norm"] = {"scale": get(
                    p + "self_attn.k_norm.weight").reshape(-1)}
            return lp
        params = {
            "embed": {"tokens": get("model.embed_tokens.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("model.norm.weight"), "bias": zb},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    elif fam == "olmo2":
        # llama tensor names for the projections, but the two block
        # norms are the post-sublayer norms (sublayer_postnorm_only) and
        # q/k carry full-projection-width rms norms.
        def layer(i):
            p = f"model.layers.{i}."

            def lin(n):
                out = {"w": get(p + n + ".weight").T}
                if p + n + ".bias" in sd:
                    out["b"] = get(p + n + ".bias")
                return out
            return {
                "attn_norm": {
                    "scale": get(p + "post_attention_layernorm.weight")},
                "mlp_norm": {
                    "scale": get(p + "post_feedforward_layernorm.weight")},
                "q": lin("self_attn.q_proj"),
                "k": lin("self_attn.k_proj"),
                "v": lin("self_attn.v_proj"),
                "o": lin("self_attn.o_proj"),
                "q_norm": {"scale": get(p + "self_attn.q_norm.weight")},
                "k_norm": {"scale": get(p + "self_attn.k_norm.weight")},
                "gate": lin("mlp.gate_proj"),
                "up": lin("mlp.up_proj"),
                "down": lin("mlp.down_proj"),
            }
        params = {
            "embed": {"tokens": get("model.embed_tokens.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("model.norm.weight")},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    elif fam == "glm":
        # Fused gate_up like phi3 ([gate|up, D], split here); glm4's two
        # extra per-block norms map onto the gemma2 sandwich leaves.
        I = cfg.intermediate_size

        def layer(i):
            p = f"model.layers.{i}."

            def lin(n):
                out = {"w": get(p + n + ".weight").T}
                if p + n + ".bias" in sd:   # q/k/v bias, o bias-free
                    out["b"] = get(p + n + ".bias")
                return out
            wgu = get(p + "mlp.gate_up_proj.weight")        # [gate|up, D]
            lp = {
                "attn_norm": {"scale": get(p + "input_layernorm.weight")},
                "q": lin("self_attn.q_proj"),
                "k": lin("self_attn.k_proj"),
                "v": lin("self_attn.v_proj"),
                "o": lin("self_attn.o_proj"),
                "mlp_norm": {
                    "scale": get(p + "post_attention_layernorm.weight")},
                "gate": {"w": wgu[:I].T},
                "up": {"w": wgu[I:].T},
                "down": {"w": get(p + "mlp.down_proj.weight").T},
            }
            if cfg.post_block_norms:   # glm4
                lp["attn_post_norm"] = {
                    "scale": get(p + "post_self_attn_layernorm.weight")}
                lp["mlp_post_norm"] = {
                    "scale": get(p + "post_mlp_layernorm.weight")}
            return lp
        params = {
            "embed": {"tokens": get("model.embed_tokens.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("model.norm.weight")},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    elif fam == "nemotron":
        # LayerNorm1P: (1 + w) * x̂ + b — absorb the +1 into the stored
        # scale (norm_offset), biases kept as-is.
        def layer(i):
            p = f"model.layers.{i}."

            def lin(n):
                out = {"w": get(p + n + ".weight").T}
                if p + n + ".bias" in sd:
                    out["b"] = get(p + n + ".bias")
                return out
            return {
                "attn_norm": {
                    "scale": get(p + "input_layernorm.weight") + 1.0,
                    "bias": get(p + "input_layernorm.bias")},
                "q": lin("self_attn.q_proj"),
                "k": lin("self_attn.k_proj"),
                "v": lin("self_attn.v_proj"),
                "o": lin("self_attn.o_proj"),
                "mlp_norm": {
                    "scale": get(p + "post_attention_layernorm.weight")
                    + 1.0,
                    "bias": get(p + "post_attention_layernorm.bias")},
                "up": lin("mlp.up_proj"),
                "down": lin("mlp.down_proj"),
            }
        params = {
            "embed": {"tokens": get("model.embed_tokens.weight")},
            "layers": _stack([layer(i) for i in range(cfg.num_layers)]),
            "final_norm": {"scale": get("model.norm.weight") + 1.0,
                           "bias": get("model.norm.bias")},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"w": get("lm_head.weight").T}
    else:
        raise NotImplementedError(fam)

    # Per-layer attention windows ride the param tree (transformer.
    # _layer_window) — emitted HERE, once, for every family whose config
    # carries them (gpt_neo's alternating global/local, gemma2, qwen3's
    # mixed layer_types through the shared llama branch, ...); no family
    # branch emits its own copy. sharding.param_specs expects the leaf
    # whenever cfg.attn_windows is set.
    if cfg.attn_windows is not None:
        params["layers"]["attn_window"] = np.asarray(
            [-1 if w is None else w for w in cfg.attn_windows], np.int32)
    if cfg.rope_layers is not None:   # per-layer NoPE (smollm3/exaone4)
        params["layers"]["rope_on"] = np.asarray(cfg.rope_layers, np.int32)

    return _to_jax(params, dtype)


def _to_jax(tree, dtype):
    if isinstance(tree, dict):
        return {k: (jnp.asarray(v, jnp.int32)
                    if k in ("attn_window", "rope_on")
                    else _to_jax(v, dtype))
                for k, v in tree.items()}
    return jnp.asarray(tree, dtype)


def allow_download() -> bool:
    """Hub downloads are opt-in: offline-by-default is the safe serving
    posture (a worker must not silently reach the internet), but the
    reference's download-any-model-by-name capability (worker/app.py:117-121,
    cache dir worker/app.py:19-20) is available behind DLI_ALLOW_DOWNLOAD=1."""
    return os.environ.get("DLI_ALLOW_DOWNLOAD", "") == "1"


def hub_cache_dir() -> str:
    """Where opted-in downloads land (≙ reference MODEL_CACHE_DIR,
    worker/app.py:19-20). Shared across workers via a mounted volume the
    same way the reference's compose file did (docker-compose.yml:12)."""
    return os.environ.get(
        "DLI_MODEL_CACHE", os.path.join(os.path.expanduser("~"),
                                        ".cache", "dli_models"))


def load_hf_model(path_or_model, dtype=None):
    """Load a local HF checkpoint directory, a hub id (opt-in), or an
    in-memory HF model.

    Returns (ModelConfig, params). Offline by default: paths must exist
    locally (the reference relied on HF-hub downloads per worker,
    worker/app.py:117-121; here checkpoint distribution is explicit).
    With ``DLI_ALLOW_DOWNLOAD=1`` a non-local name is fetched from the
    hub into ``hub_cache_dir()`` once and reused thereafter.
    """
    if isinstance(path_or_model, str):
        import transformers
        local_only = not allow_download() or os.path.isdir(path_or_model)
        # redirect the cache only when an actual download is permitted —
        # offline hub-id loads must keep resolving against the standard
        # HF cache a user may already have populated
        kw = ({"cache_dir": hub_cache_dir()}
              if not local_only and not os.path.isdir(path_or_model) else {})
        model = transformers.AutoModelForCausalLM.from_pretrained(
            path_or_model, local_files_only=local_only, **kw)
    else:
        model = path_or_model
    cfg = config_from_hf(model.config)
    params = convert_state_dict(cfg, dict(model.state_dict()), dtype=dtype)
    return cfg, params
