"""Model configuration.

One dataclass covers every supported family (GPT-2, OPT, Llama/Mistral,
Mixtral); the fields are the union of what those architectures need. The
reference framework had no config object at all — architecture handling was
an attribute sniff on the HF module tree (reference: shard_model.py:40-50);
here the config is the single source of truth for shapes, partitioning and
weight conversion.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    # Identity
    name: str = "gpt2"
    family: str = "gpt2"  # gpt2 | opt | llama | mixtral

    # Core dimensions
    vocab_size: int = 50257
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: int = 12  # < num_heads => GQA
    head_dim: int = 64
    max_position_embeddings: int = 1024

    # Architecture switches
    norm_type: str = "layernorm"  # layernorm | rmsnorm
    norm_eps: float = 1e-5
    # gelu (tanh approx) | gelu_exact | silu | relu | relu2 (squared
    # ReLU, Nemotron)
    activation: str = "gelu"
    gated_mlp: bool = False  # llama-style SwiGLU (gate+up) vs plain fc
    # learned | rope | alibi (BLOOM/Falcon-RW: linear attention bias,
    # position-free K/V — the cache layout matches the RoPE families')
    position_embedding: str = "learned"
    # Multiplier on the ALiBi slopes: BLOOM adds the bias to the SCALED
    # scores (1.0); Falcon-RW scales (scores + bias) together, i.e. the
    # bias carries an extra 1/sqrt(head_dim).
    alibi_scale: float = 1.0
    rope_theta: float = 10000.0
    # Partial rotary (GPT-NeoX rotary_pct / Phi partial_rotary_factor):
    # only the first rope_pct * head_dim dims rotate, the rest pass
    # through position-free.
    rope_pct: float = 1.0
    # GPT-J rotate_every_two convention: frequency i rotates dims
    # (2i, 2i+1) instead of HF-llama's (i, i + rot/2) halves.
    rope_interleaved: bool = False
    # Context-extension override of the rope frequency ladder ([rot/2]
    # floats, e.g. yarn's NTK-by-part interpolation) — computed ONCE at
    # conversion (models/convert.py _yarn_inv_freq) and carried here so
    # checkpoints roundtrip it through config.json. None => the plain
    # theta ladder.
    rope_inv_freq: Optional[Tuple[float, ...]] = None
    # yarn attention_factor: multiplies cos/sin (ops/rope.apply_rope),
    # i.e. scores scale by its square over the rotated dims. The
    # separate mscale_all_dim score multiplier (uniform over ALL dims)
    # is folded into the q weights at conversion via
    # query_pre_attn_scalar instead.
    rope_attn_factor: float = 1.0
    # BLOOM: layernorm applied to the embedding output.
    embed_norm: bool = False
    attn_bias: bool = True
    # Qwen2-style asymmetric attention bias: q/k/v carry bias, the output
    # projection does not. None => o follows attn_bias.
    o_bias: Optional[bool] = None
    mlp_bias: bool = True
    # Phi-style bias on the untied lm_head projection.
    lm_head_bias: bool = False
    tie_word_embeddings: bool = True
    # GPT-NeoX / Phi / Falcon block topology: attention and MLP both read
    # (norms of) the SAME block input and share one residual add —
    # x + attn(norm1(x)) + mlp(norm2(x)) — instead of the sequential
    # two-residual layout.
    parallel_residual: bool = False
    # Phi / Falcon-7B: ONE layernorm feeds both attention and MLP (layer
    # params then carry no mlp_norm). Only meaningful with
    # parallel_residual.
    shared_attn_mlp_norm: bool = False
    sliding_window: Optional[int] = None  # Mistral-style local attention
    # Per-LAYER attention windows (GPT-Neo alternating global/local-256):
    # a full per-layer tuple, entries None => global. Mutually exclusive
    # with the uniform ``sliding_window``. Threaded through the runtime
    # as an int32 leaf ``attn_window`` ([L], -1 == global) in the layer
    # param tree (models/params.py, convert.py), so every scan / unroll /
    # pipeline-stage / sharding path carries it without special cases;
    # attention reads it as a traced scalar (ops/attention.py). Forces
    # the XLA attention formulation — the pallas flash kernels take
    # static windows only (models/transformer.py).
    attn_windows: Optional[Tuple[Optional[int], ...]] = None
    # Gemma-2 logit softcapping: scores/logits squashed to
    # cap * tanh(x / cap). ``attn_softcap`` applies to attention scores
    # (pre-mask; forces the XLA attention formulation — the flash
    # kernels' online softmax has no tanh hook); ``logit_softcap`` to
    # the final vocab logits.
    attn_softcap: Optional[float] = None
    logit_softcap: Optional[float] = None
    # Cohere: tied-head logits multiplied by a constant scale.
    logit_scale: Optional[float] = None
    # Gemma-2 block topology: sandwich norms — attention/MLP outputs are
    # normed BEFORE their residual add (attn_post_norm/mlp_post_norm
    # leaves), in addition to the usual pre-norms.
    post_block_norms: bool = False
    # Gemma-2 query_pre_attn_scalar: HF scales scores by qpas**-0.5
    # instead of head_dim**-0.5. Conversion absorbs the ratio
    # sqrt(head_dim / qpas) into the q weights (models/convert.py) so
    # the runtime score scale stays uniform; like norm_offset, this
    # field only drives that conversion step.
    query_pre_attn_scalar: Optional[float] = None
    # Gemma-style sqrt(hidden_size) embedding normalizer, applied to the
    # embedding OUTPUT only (the tied head reads the raw table).
    embed_scale: Optional[float] = None
    # Gemma's RMSNorm convention is (1 + w) * x̂. Conversion absorbs the
    # +1 into the stored scale (models/convert.py) so the runtime norm
    # stays plain; this flag only drives that conversion step (and
    # random-init's ones() is already the absorbed identity).
    norm_offset: bool = False
    # Q/K normalization applied to the projected q and k BEFORE RoPE:
    # None | "rms_head" (RMSNorm over head_dim, per head — Qwen3 /
    # Qwen3-MoE) | "rms_full" (RMSNorm over the full projection width —
    # OLMo2) | "ln_head" (bias-free LayerNorm over head_dim — Cohere
    # use_qk_norm). Adds q_norm/k_norm scale leaves to the layer tree.
    qk_norm: Optional[str] = None
    # OLMo2 block topology: NO pre-norms; the attn/mlp norm leaves apply
    # to the sublayer OUTPUT before its residual add — x + norm(f(x)).
    # (Distinct from post_norm, which norms after the add, and from
    # post_block_norms, which sandwiches pre- AND post-norms.)
    sublayer_postnorm_only: bool = False
    # HunYuan-Dense: the q/k norms apply AFTER RoPE (Qwen3/Exaone norm
    # then rotate; HunYuan rotates then norms). Only meaningful with
    # qk_norm.
    qk_norm_after_rope: bool = False
    # DBRX clip_qkv: the fused qkv projection output is clamped to
    # ±this before heads split — a runtime nonlinearity on activations
    # (clamping after our separate q/k/v projections is identical).
    qkv_clip: Optional[float] = None
    # Per-LAYER rope on/off (SmolLM3 no_rope_layers: every Nth layer is
    # NoPE; Exaone4 hybrid: full-attention layers skip rope while
    # sliding layers rotate). A full per-layer tuple of 1/0; None => all
    # layers rotate. Rides the layer param tree as an int32 ``rope_on``
    # leaf ([L]) like attn_windows, so every scan/unroll/pipeline path
    # carries it; the block computes the rotation and selects per layer.
    rope_layers: Optional[Tuple[int, ...]] = None
    # Granite residual_multiplier: sublayer outputs scaled by this before
    # their residual add. (Granite's other multipliers map onto existing
    # fields: embedding_multiplier -> embed_scale, attention_multiplier
    # -> query_pre_attn_scalar absorption, 1/logits_scaling ->
    # logit_scale.)
    residual_scale: Optional[float] = None
    # OPT-350m specifics (reference's second arch family, shard_model.py:46):
    # token embeds live in a smaller space with linear project_in/out...
    embed_proj_dim: Optional[int] = None
    # ...and blocks normalize AFTER the residual add (do_layer_norm_before
    # = False), with no final norm before the head.
    post_norm: bool = False

    # DeepSeek-V3 multi-head latent attention (MLA, HF
    # modeling_deepseek_v3.py DeepseekV3Attention): q and kv project
    # through low-rank bottlenecks with an RMSNorm at each bottleneck
    # (which is why MLA cannot be folded into plain q/k/v weights at
    # conversion), per-head q/k dims split into a position-free "nope"
    # part and a RoPE'd part whose k side is computed ONCE and shared
    # across heads. kv_lora_rank non-None switches the block to MLA;
    # head_dim must equal qk_nope_head_dim + qk_rope_head_dim, and
    # num_kv_heads == num_heads (k/v are materialized per head — the
    # correctness-first formulation; a latent-cache kernel can later cut
    # the cache to kv_lora_rank + rope per token).
    q_lora_rank: Optional[int] = None     # None => full-rank q projection
    kv_lora_rank: Optional[int] = None
    qk_nope_head_dim: int = 0
    qk_rope_head_dim: int = 0
    # MLA value head width; v is zero-padded to head_dim inside the block
    # so every cache/attention path keeps a single head_dim, and the
    # attention output is sliced back before the o projection. None =>
    # head_dim (all non-MLA families).
    v_head_dim: Optional[int] = None
    # MLA's actual point: cache ONE shared latent row per token —
    # [k_rot (qk_rope_head_dim, post-RoPE) | c (kv_lora_rank, normed)] —
    # instead of materialized per-head K/V, and decode via the absorbed
    # formulation (scores q_nope·(W_uk c) == (W_uk^T q_nope)·c; outputs
    # W_uv (Σ w c)), i.e. MQA over the latent with per-head up/down
    # projections folded around the attention (transformer.
    # _mla_latent_attn). Cuts dense-cache bytes by
    # 2·H·head_dim / (kv_lora_rank + qk_rope_head_dim) (~19x on the
    # deepseek-proxy, ~85x on real V3 pre-tp). The engine auto-enables
    # it on eligible meshes (no sp/pp, no kv_quant) — DLI_MLA_LATENT=0
    # opts out; the paged batcher keeps the materialized layout.
    mla_latent_cache: bool = False

    # Mixture-of-experts (Mixtral). num_experts == 0 => dense MLP.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # Router convention: "softmax" (Mixtral/Qwen3-MoE: softmax -> top-k
    # -> renormalize) | "topk_softmax" (gpt-oss: select by raw biased
    # logits, weights = softmax over the selected k logits) | "ernie" (ERNIE-4.5-MoE: softmax scores under the
    # deepseek-style bias-corrected SELECTION, unbiased weights) |
    # "deepseek_v3" (sigmoid scores; selection by
    # scores + e_score_correction_bias under group-limited top-k —
    # moe_n_group groups scored by their top-2 sum, top moe_topk_group
    # groups kept; weights are the UNbiased scores, renormalized when
    # moe_norm_topk, then scaled by moe_routed_scale).
    moe_router: str = "softmax"
    moe_n_group: int = 1
    moe_topk_group: int = 1
    moe_routed_scale: float = 1.0
    moe_norm_topk: bool = True
    # DeepSeek shared experts: a dense SwiGLU MLP of width
    # moe_shared_experts * (per-expert intermediate), always active,
    # added to the routed output (layer tree leaves shared_gate/up/down).
    moe_shared_experts: int = 0
    # gpt-oss expert GLU: gate clamped to (-inf, limit], up to ±limit,
    # glu = gate * sigmoid(alpha * gate), output (up + 1) * glu — with
    # per-expert BIASES on gate/up/down (leaves carry "b"). None =>
    # the standard act(gate) * up.
    moe_swiglu_limit: Optional[float] = None
    moe_swiglu_alpha: float = 1.702
    # gpt-oss attention sinks: one learned logit per head ([H] ``sinks``
    # leaf in the layer tree) appended to every softmax as a virtual
    # column and dropped after normalization — the sink only inflates
    # the denominator (ops/attention.attend).
    attn_sinks: bool = False
    # DeepSeek first_k_dense_replace: the first k layers run a plain
    # dense MLP (width dense_intermediate_size) instead of the MoE. The
    # param tree then carries a second stacked segment ``layers_dense``
    # ([k, ...]) ahead of the MoE ``layers`` ([L-k, ...]) — the layer
    # scans run the two segments back to back
    # (models/transformer.py layer_segments). Attention/cache layout is
    # identical across segments, so the KV cache stays one [L, ...]
    # stack.
    dense_prefix_layers: int = 0
    dense_intermediate_size: Optional[int] = None
    # Dispatch strategy (models/transformer.py _moe): "dense" computes all
    # experts for every token (right trade at decode batch sizes);
    # "capacity" does GShard-style top-k einsum dispatch with a fixed
    # per-expert capacity (right trade for batched prefill throughput);
    # "auto" picks by token count.
    moe_dispatch: str = "auto"
    moe_capacity_factor: float = 1.25

    # Numerics
    dtype: str = "bfloat16"  # activation/weight dtype on device
    # Weight-only quantization (ops/quant.py): None | "int8" | "int4".
    # int8 halves the HBM weight traffic of decode and doubles
    # fit-per-chip at negligible accuracy cost; int4 (nibble-packed)
    # halves it again — the throughput mode, measurably lossier.
    quant: Optional[str] = None
    # Token-embedding-table quantization: None | "int8" (per-row scales,
    # ops/quant.py quantize_embed). The tied-head lever: gpt2-family
    # unembed streams the whole [V, D] table per decode step, and
    # llama's table is ~1 GB bf16 of footprint. Opt-in separately from
    # ``quant`` because embeddings are the most accuracy-sensitive table.
    embed_quant: Optional[str] = None
    # KV-cache quantization: None | "int8" (per-token-per-head symmetric
    # scales, ops/kvcache.py quant_kv). Halves cache traffic/footprint —
    # the long-context decode lever on top of weight int8. Attention
    # dequantizes at read; XLA fuses the int8->bf16 convert+scale into the
    # attention matmuls so the HBM read stays int8.
    kv_quant: Optional[str] = None

    # Attention kernel backend: auto | xla | pallas | pallas_interpret
    # (trace-time static; see ops/attention.py resolve_backend)
    attn_backend: str = "auto"

    # Pinned by the engine at init (like attn_backend's resolution): True
    # when the enclosing GSPMD program shards linear weights over tp, so
    # row-parallel (din-sharded: o/down) int4 leaves keep the XLA unpack
    # instead of the pallas kernel, whose partitioning rule shards only
    # the output axis (ops/pallas/quant_matmul.py supported()). Local-
    # view (shard_map) callers keep False: their weights arrive pre-
    # sliced and the kernel is a plain local matmul.
    tp_row_sharded: bool = False

    def __post_init__(self):
        assert self.num_heads % self.num_kv_heads == 0, (
            f"num_heads={self.num_heads} must be divisible by "
            f"num_kv_heads={self.num_kv_heads}"
        )
        if self.rope_inv_freq is not None:
            # normalize (checkpoint config.json roundtrips tuple -> list)
            object.__setattr__(self, "rope_inv_freq",
                               tuple(float(f) for f in self.rope_inv_freq))
        if self.attn_windows is not None:
            # normalize (checkpoint config.json roundtrips tuple -> list)
            object.__setattr__(self, "attn_windows",
                               tuple(self.attn_windows))
            assert len(self.attn_windows) == self.num_layers, (
                f"attn_windows has {len(self.attn_windows)} entries for "
                f"{self.num_layers} layers")
            assert self.sliding_window is None, (
                "attn_windows and sliding_window are mutually exclusive")
        if self.rope_layers is not None:
            object.__setattr__(self, "rope_layers",
                               tuple(int(v) for v in self.rope_layers))
            assert len(self.rope_layers) == self.num_layers, (
                f"rope_layers has {len(self.rope_layers)} entries for "
                f"{self.num_layers} layers")
            assert self.position_embedding == "rope", (
                "rope_layers only makes sense with rope positions")
        assert not (self.post_block_norms
                    and (self.parallel_residual or self.post_norm)), (
            "post_block_norms (sandwich) excludes parallel_residual and "
            "post_norm topologies")
        assert not (self.parallel_residual and self.post_norm), (
            "parallel_residual and post_norm are mutually exclusive")
        assert not (self.sublayer_postnorm_only
                    and (self.parallel_residual or self.post_norm
                         or self.post_block_norms)), (
            "sublayer_postnorm_only (olmo2) excludes parallel_residual, "
            "post_norm and post_block_norms topologies")
        assert self.qk_norm in (None, "rms_head", "rms_full", "ln_head"), (
            f"unknown qk_norm {self.qk_norm!r}")
        assert not (self.shared_attn_mlp_norm
                    and not self.parallel_residual), (
            "shared_attn_mlp_norm requires parallel_residual")
        if self.kv_lora_rank is not None:
            assert self.head_dim == (self.qk_nope_head_dim
                                     + self.qk_rope_head_dim), (
                "MLA: head_dim must equal qk_nope_head_dim + "
                "qk_rope_head_dim")
            assert self.num_kv_heads == self.num_heads, (
                "MLA materializes k/v per head: num_kv_heads == num_heads")
            assert self.position_embedding == "rope" and self.qk_norm is None
        if self.mla_latent_cache:
            assert self.mla, "mla_latent_cache requires an MLA config"
            assert self.kv_quant is None, (
                "mla_latent_cache and kv_quant are mutually exclusive "
                "(the latent row is already the compressed representation)")
            assert (self.sliding_window is None
                    and self.attn_windows is None
                    and self.attn_softcap is None), (
                "mla_latent_cache's absorbed attention does not thread "
                "sliding windows or score softcapping (no MLA "
                "architecture uses them); serve such a config with the "
                "materialized layout (DLI_MLA_LATENT=0)")
        assert self.moe_router in ("softmax", "deepseek_v3", "ernie",
                                   "topk_softmax"), (
            f"unknown moe_router {self.moe_router!r}")
        if self.dense_prefix_layers:
            assert 0 < self.dense_prefix_layers < self.num_layers, (
                f"dense_prefix_layers={self.dense_prefix_layers} must be "
                f"in (0, num_layers={self.num_layers}); an all-dense "
                "model is just num_experts=0")
            assert self.num_experts > 0, (
                "dense_prefix_layers describes a dense prefix AHEAD of "
                "MoE layers; set num_experts")
            assert self.dense_intermediate_size, (
                "dense_prefix_layers needs dense_intermediate_size (the "
                "prefix MLP width differs from the per-expert width)")
        if self.moe_router in ("deepseek_v3", "ernie") and self.num_experts:
            E, G = self.num_experts, self.moe_n_group
            assert G >= 1 and E % G == 0, (
                f"deepseek routing: num_experts={E} must divide into "
                f"moe_n_group={G} groups")
            assert E // G >= 2, (
                f"deepseek routing scores each group by its top-2 sum: "
                f"need >= 2 experts per group, got {E // G}")
            assert 1 <= self.moe_topk_group <= G, (
                f"moe_topk_group={self.moe_topk_group} must be in "
                f"[1, moe_n_group={G}]")
            assert self.moe_topk_group * (E // G) >= self.num_experts_per_tok, (
                f"top-{self.num_experts_per_tok} routing needs at least "
                f"that many eligible experts, but moe_topk_group="
                f"{self.moe_topk_group} groups expose only "
                f"{self.moe_topk_group * (E // G)}")

    @property
    def mla(self) -> bool:
        return self.kv_lora_rank is not None

    def dense_segment_cfg(self, num_layers: Optional[int] = None
                          ) -> "ModelConfig":
        """The per-segment config of the dense-MLP prefix of a mixed
        stack: MoE fields cleared, MLP width = dense_intermediate_size.
        The ONE derivation shared by execution
        (transformer.layer_segments), init (params.init_params) and
        sharding (param_specs) — a field zeroed here is zeroed
        everywhere."""
        return self.replace(
            num_experts=0, moe_shared_experts=0, moe_router="softmax",
            dense_prefix_layers=0, dense_intermediate_size=None,
            intermediate_size=self.dense_intermediate_size,
            num_layers=(self.dense_prefix_layers if num_layers is None
                        else num_layers))

    @property
    def v_head_dim_effective(self) -> int:
        return self.head_dim if self.v_head_dim is None else self.v_head_dim

    # Dense-cache plane shapes (ops/kvcache.init_cache, sharding.
    # cache_specs): the latent layout stores ONE shared
    # [k_rot | c] row per token in the k plane and nothing in the v
    # plane (attention reads v as a slice of k — the c part).
    @property
    def cache_kv_heads(self) -> int:
        return 1 if self.mla_latent_cache else self.num_kv_heads

    @property
    def cache_head_dim(self) -> int:
        if self.mla_latent_cache:
            return self.qk_rope_head_dim + self.kv_lora_rank
        return self.head_dim

    @property
    def cache_v_head_dim(self) -> int:
        return 0 if self.mla_latent_cache else self.head_dim

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def o_bias_effective(self) -> bool:
        return self.attn_bias if self.o_bias is None else self.o_bias

    def replace(self, **kw) -> "ModelConfig":
        return dataclasses.replace(self, **kw)
