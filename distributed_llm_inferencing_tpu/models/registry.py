"""Model registry: name -> ModelConfig.

Replaces the reference's implicit "whatever string you type into the
dashboard goes to AutoModelForCausalLM" model selection
(reference: worker/app.py:117-121, inference.html:22) with an explicit
registry. HF checkpoints are still ingested (models/convert.py) — the
registry also knows how to derive a ModelConfig from an HF config object so
arbitrary local HF checkpoints of a supported family load too.
"""

from __future__ import annotations

from typing import Dict

from distributed_llm_inferencing_tpu.models.config import ModelConfig

_REGISTRY: Dict[str, ModelConfig] = {}


def register(cfg: ModelConfig) -> ModelConfig:
    _REGISTRY[cfg.name] = cfg
    return cfg


def get_config(name: str) -> ModelConfig:
    if name not in _REGISTRY:
        raise KeyError(
            f"unknown model {name!r}; known: {sorted(_REGISTRY)}. "
            "Use models.convert.config_from_hf for local HF checkpoints."
        )
    return _REGISTRY[name]


def list_models():
    return sorted(_REGISTRY)


def _gpt2(name, hidden, layers, heads, ctx=1024):
    return ModelConfig(
        name=name, family="gpt2", vocab_size=50257, hidden_size=hidden,
        intermediate_size=4 * hidden, num_layers=layers, num_heads=heads,
        num_kv_heads=heads, head_dim=hidden // heads,
        max_position_embeddings=ctx, norm_type="layernorm", activation="gelu",
        gated_mlp=False, position_embedding="learned", attn_bias=True,
        mlp_bias=True, tie_word_embeddings=True,
    )


def _opt(name, hidden, inter, layers, heads, ctx=2048):
    # OPT family (reference's second supported arch, shard_model.py:46-50):
    # learned positions, ReLU->gelu approx not needed: OPT uses ReLU; we keep
    # gelu/silu switch minimal and add relu.
    return ModelConfig(
        name=name, family="opt", vocab_size=50272, hidden_size=hidden,
        intermediate_size=inter, num_layers=layers, num_heads=heads,
        num_kv_heads=heads, head_dim=hidden // heads,
        max_position_embeddings=ctx, norm_type="layernorm", activation="relu",
        gated_mlp=False, position_embedding="learned", attn_bias=True,
        mlp_bias=True, tie_word_embeddings=True,
    )


def _llama(name, hidden, inter, layers, heads, kv_heads, vocab=128256,
           ctx=8192, theta=500000.0, window=None):
    return ModelConfig(
        name=name, family="llama", vocab_size=vocab, hidden_size=hidden,
        intermediate_size=inter, num_layers=layers, num_heads=heads,
        num_kv_heads=kv_heads, head_dim=hidden // heads,
        max_position_embeddings=ctx, norm_type="rmsnorm", norm_eps=1e-5,
        activation="silu", gated_mlp=True, position_embedding="rope",
        rope_theta=theta, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, sliding_window=window,
    )


# --- GPT-2 family (reference default model, inference.html:22) ---
register(_gpt2("gpt2", 768, 12, 12))
register(_gpt2("gpt2-medium", 1024, 24, 16))
register(_gpt2("gpt2-large", 1280, 36, 20))
register(_gpt2("gpt2-xl", 1600, 48, 25))

# --- OPT family (reference: facebook/opt-350m hint, inference.html:23) ---
register(_opt("opt-125m", 768, 3072, 12, 12))
register(_opt("opt-350m", 1024, 4096, 24, 16).replace(
    embed_proj_dim=512, post_norm=True))
register(_opt("opt-1.3b", 2048, 8192, 24, 32))

# --- Llama 3 family (BASELINE.md configs 2 & 5) ---
register(_llama("llama-3-8b", 4096, 14336, 32, 32, 8))
register(_llama("llama-3-70b", 8192, 28672, 80, 64, 8))

# --- Mistral (BASELINE.md config 3): llama arch + sliding window ---
register(_llama("mistral-7b", 4096, 14336, 32, 32, 8, vocab=32000,
                ctx=32768, theta=10000.0, window=4096))

# --- Mixtral (BASELINE.md config 4): Mistral + 8-expert MoE ---
register(_llama("mixtral-8x7b", 4096, 14336, 32, 32, 8, vocab=32000,
                ctx=32768, theta=1000000.0).replace(
                    name="mixtral-8x7b", num_experts=8, num_experts_per_tok=2))

# --- Qwen2: llama layout + bias on q/k/v only (models/convert.py) ---
register(_llama("qwen2-7b", 3584, 18944, 28, 28, 4, vocab=152064,
                ctx=32768, theta=1000000.0).replace(
                    name="qwen2-7b", attn_bias=True, o_bias=False))
register(_llama("qwen2-0.5b", 896, 4864, 24, 14, 2, vocab=151936,
                ctx=32768, theta=1000000.0).replace(
                    name="qwen2-0.5b", attn_bias=True, o_bias=False,
                    tie_word_embeddings=True))

# --- Gemma: llama layout + tanh-gelu, sqrt(D) embed normalizer, wide
# head_dim (256 > hidden/heads), tied 256k-vocab head ---
register(_llama("gemma-7b", 3072, 24576, 28, 16, 16, vocab=256000,
                ctx=8192, theta=10000.0).replace(
                    name="gemma-7b", head_dim=256, activation="gelu",
                    tie_word_embeddings=True, embed_scale=3072 ** 0.5,
                    norm_eps=1e-6, norm_offset=True))
register(_llama("gemma-2b", 2048, 16384, 18, 8, 1, vocab=256000,
                ctx=8192, theta=10000.0).replace(
                    name="gemma-2b", head_dim=256, activation="gelu",
                    tie_word_embeddings=True, embed_scale=2048 ** 0.5,
                    norm_eps=1e-6, norm_offset=True))

# --- MoE proxy (BASELINE.md config 4's measurable stand-in): Mixtral
# itself cannot fit one v5e chip even int4, so this 8-expert ~2.6B-total
# (~0.8B active) llama-layout MoE makes the dense-vs-capacity dispatch
# trade measurable on the real chip (bench.py moe_* keys). ---
register(_llama("moe-proxy-8e", 1536, 4096, 16, 12, 4, vocab=32000,
                ctx=4096, theta=10000.0).replace(
                    name="moe-proxy-8e", num_experts=8,
                    num_experts_per_tok=2))

# --- DeepSeek proxy: V3's mechanisms (MLA latent attention + sigmoid
# group-limited routing + shared experts) at a scale one chip serves —
# the real 671B is a multi-pod deployment. Dims follow V3's ratios
# (kv_lora_rank ≈ D/14, rope head = nope/2, v = nope). ---
register(ModelConfig(
    name="deepseek-proxy", family="deepseek", vocab_size=32000,
    hidden_size=1024, intermediate_size=512, num_layers=12, num_heads=16,
    num_kv_heads=16, head_dim=96, qk_nope_head_dim=64,
    qk_rope_head_dim=32, v_head_dim=64, q_lora_rank=384, kv_lora_rank=128,
    max_position_embeddings=4096, norm_type="rmsnorm", activation="silu",
    gated_mlp=True, position_embedding="rope", rope_theta=10000.0,
    rope_interleaved=True, attn_bias=False, mlp_bias=False,
    tie_word_embeddings=False, num_experts=8, num_experts_per_tok=2,
    moe_router="deepseek_v3", moe_n_group=4, moe_topk_group=2,
    moe_routed_scale=2.5, moe_shared_experts=1,
    dense_prefix_layers=1, dense_intermediate_size=2048))

# --- GPT-NeoX / Pythia: parallel residual, partial rotary, exact gelu ---
register(ModelConfig(
    name="pythia-6.9b", family="gpt-neox", vocab_size=50432,
    hidden_size=4096, intermediate_size=16384, num_layers=32, num_heads=32,
    num_kv_heads=32, head_dim=128, max_position_embeddings=2048,
    norm_type="layernorm", activation="gelu_exact", gated_mlp=False,
    position_embedding="rope", rope_theta=10000.0, rope_pct=0.25,
    attn_bias=True, mlp_bias=True, tie_word_embeddings=False,
    parallel_residual=True))
register(ModelConfig(
    name="pythia-1.4b", family="gpt-neox", vocab_size=50304,
    hidden_size=2048, intermediate_size=8192, num_layers=24, num_heads=16,
    num_kv_heads=16, head_dim=128, max_position_embeddings=2048,
    norm_type="layernorm", activation="gelu_exact", gated_mlp=False,
    position_embedding="rope", rope_theta=10000.0, rope_pct=0.25,
    attn_bias=True, mlp_bias=True, tie_word_embeddings=False,
    parallel_residual=True))

# --- Phi-2: parallel residual + single shared norm, biased lm_head ---
register(ModelConfig(
    name="phi-2", family="phi", vocab_size=51200, hidden_size=2560,
    intermediate_size=10240, num_layers=32, num_heads=32, num_kv_heads=32,
    head_dim=80, max_position_embeddings=2048, norm_type="layernorm",
    activation="gelu", gated_mlp=False, position_embedding="rope",
    rope_theta=10000.0, rope_pct=0.4, attn_bias=True, mlp_bias=True,
    lm_head_bias=True, tie_word_embeddings=False, parallel_residual=True,
    shared_attn_mlp_norm=True))

# --- Falcon-7B: MQA fused QKV, parallel residual + shared norm ---
register(ModelConfig(
    name="falcon-7b", family="falcon", vocab_size=65024, hidden_size=4544,
    intermediate_size=18176, num_layers=32, num_heads=71, num_kv_heads=1,
    head_dim=64, max_position_embeddings=2048, norm_type="layernorm",
    activation="gelu_exact", gated_mlp=False, position_embedding="rope",
    rope_theta=10000.0, attn_bias=False, mlp_bias=False,
    tie_word_embeddings=True, parallel_residual=True,
    shared_attn_mlp_norm=True))

# --- BLOOM: ALiBi positions, layernormed embedding, tied 250k head ---
register(ModelConfig(
    name="bloom-7b1", family="bloom", vocab_size=250880, hidden_size=4096,
    intermediate_size=16384, num_layers=30, num_heads=32, num_kv_heads=32,
    head_dim=128, max_position_embeddings=2048, norm_type="layernorm",
    activation="gelu", gated_mlp=False, position_embedding="alibi",
    embed_norm=True, attn_bias=True, mlp_bias=True,
    tie_word_embeddings=True))

# --- Falcon-RW-1B: ALiBi + sequential residual (the RW layout) ---
register(ModelConfig(
    name="falcon-rw-1b", family="falcon", vocab_size=50304,
    hidden_size=2048, intermediate_size=8192, num_layers=24, num_heads=32,
    num_kv_heads=32, head_dim=64, max_position_embeddings=2048,
    norm_type="layernorm", activation="gelu_exact", gated_mlp=False,
    position_embedding="alibi", alibi_scale=64 ** -0.5,
    attn_bias=True, mlp_bias=True, tie_word_embeddings=True))

# --- MPT-7B: ALiBi, bias-free straight-concat fused QKV, tied head ---
register(ModelConfig(
    name="mpt-7b", family="mpt", vocab_size=50432, hidden_size=4096,
    intermediate_size=16384, num_layers=32, num_heads=32, num_kv_heads=32,
    head_dim=128, max_position_embeddings=2048, norm_type="layernorm",
    activation="gelu_exact", gated_mlp=False, position_embedding="alibi",
    attn_bias=False, mlp_bias=False, tie_word_embeddings=True))

# --- GPT-J-6B: interleaved partial rotary, shared-norm parallel block ---
register(ModelConfig(
    name="gpt-j-6b", family="gptj", vocab_size=50400, hidden_size=4096,
    intermediate_size=16384, num_layers=28, num_heads=16, num_kv_heads=16,
    head_dim=256, max_position_embeddings=2048, norm_type="layernorm",
    activation="gelu", gated_mlp=False, position_embedding="rope",
    rope_theta=10000.0, rope_pct=0.25, rope_interleaved=True,
    attn_bias=False, o_bias=False, mlp_bias=True, lm_head_bias=True,
    tie_word_embeddings=False, parallel_residual=True,
    shared_attn_mlp_norm=True))

# --- Tiny configs for tests/dryrun (not real checkpoints) ---
register(ModelConfig(
    name="tiny-gpt2", family="gpt2", vocab_size=256, hidden_size=64,
    intermediate_size=256, num_layers=4, num_heads=4, num_kv_heads=4,
    head_dim=16, max_position_embeddings=128, norm_type="layernorm",
    activation="gelu", gated_mlp=False, position_embedding="learned",
    attn_bias=True, mlp_bias=True, tie_word_embeddings=True))
register(ModelConfig(
    name="tiny-llama", family="llama", vocab_size=256, hidden_size=64,
    intermediate_size=128, num_layers=4, num_heads=8, num_kv_heads=4,
    head_dim=8, max_position_embeddings=128, norm_type="rmsnorm",
    activation="silu", gated_mlp=True, position_embedding="rope",
    attn_bias=False, mlp_bias=False, tie_word_embeddings=False))
register(ModelConfig(
    # tiny-llama with a 1k context: the disaggregation bench's workload
    # model (bench.py --scenario disagg) — long prompts need prefill
    # that costs real compute relative to a decode step, which the
    # 128-token tiny-llama cannot express
    name="tiny-llama-long", family="llama", vocab_size=256, hidden_size=64,
    intermediate_size=128, num_layers=4, num_heads=8, num_kv_heads=4,
    head_dim=8, max_position_embeddings=1024, norm_type="rmsnorm",
    activation="silu", gated_mlp=True, position_embedding="rope",
    attn_bias=False, mlp_bias=False, tie_word_embeddings=False))
register(ModelConfig(
    name="tiny-mixtral", family="llama", vocab_size=256, hidden_size=64,
    intermediate_size=128, num_layers=2, num_heads=8, num_kv_heads=4,
    head_dim=8, max_position_embeddings=128, norm_type="rmsnorm",
    activation="silu", gated_mlp=True, position_embedding="rope",
    attn_bias=False, mlp_bias=False, tie_word_embeddings=False,
    num_experts=4, num_experts_per_tok=2))
register(ModelConfig(
    name="tiny-deepseek", family="deepseek", vocab_size=256,
    hidden_size=64, intermediate_size=32, num_layers=3, num_heads=8,
    num_kv_heads=8, head_dim=24, qk_nope_head_dim=16, qk_rope_head_dim=8,
    v_head_dim=16, q_lora_rank=32, kv_lora_rank=16,
    max_position_embeddings=128, norm_type="rmsnorm", activation="silu",
    gated_mlp=True, position_embedding="rope", rope_interleaved=True,
    attn_bias=False, mlp_bias=False, tie_word_embeddings=False,
    num_experts=4, num_experts_per_tok=2, moe_router="deepseek_v3",
    moe_n_group=2, moe_topk_group=1, moe_routed_scale=2.5,
    moe_shared_experts=1,
    # the shipped first_k_dense_replace layout: one dense-MLP layer
    # ahead of the MoE tail (its own stacked segment, layers_dense)
    dense_prefix_layers=1, dense_intermediate_size=48))
