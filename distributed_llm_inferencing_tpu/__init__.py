"""distributed_llm_inferencing_tpu — a TPU-native distributed LLM inference framework.

A from-scratch re-design of the capabilities of
MihirPanpatil/Distributed-LLM-Inferencing (a Django-master / Flask-worker
HTTP-sharded HF-inference platform — see SURVEY.md) built TPU-first:

- compute path: pure-JAX causal LMs, jitted prefill/decode with a static-shape
  KV cache, XLA-compiled sampling, Pallas kernels for the hot ops
- parallelism: ``jax.sharding.Mesh`` + ``NamedSharding`` (tensor / data /
  pipeline / sequence / expert axes) with XLA collectives over ICI — replacing
  the reference's file-level shard copies and per-hop HTTP
  (reference: master/dashboard/management/commands/shard_model.py,
  worker/app.py:332-372)
- control plane: a dependency-free master service (node registry, request
  queue, dashboard) + per-host worker agents speaking the same lifecycle RPC
  surface as the reference worker (worker/app.py:49-413)
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("DLI_PLATFORM"):
    # Select the JAX backend per process (e.g. DLI_PLATFORM=cpu for a
    # control-plane process that must not claim a TPU). Done via jax.config
    # rather than JAX_PLATFORMS because environments that preload jax at
    # interpreter start (sitecustomize TPU plugins) read the env var too
    # early for user code to set it.
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["DLI_PLATFORM"])

from distributed_llm_inferencing_tpu.models.config import ModelConfig  # noqa: F401
from distributed_llm_inferencing_tpu.models.registry import get_config, list_models  # noqa: F401
