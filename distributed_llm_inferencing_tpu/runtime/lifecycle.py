"""Declared request-lifecycle state machine — the protocol contract
behind every ``UPDATE requests SET status=...`` in runtime/state.py.

Until this table existed, the request state machine lived only in
reviewer memory: which function may write which status, from which
source states, whether the write must sit behind the group-commit
durability barrier, and which transitions burn the attempt budget.
``tools/dlilint/check_lifecycle.py`` verifies every status-write site
in ``state.py`` against this table — an undeclared transition, a
terminal status written without the declared durability mechanism, or a
WHERE-guard that doesn't match the declared source set fails CI — and
the table generates the byte-checked lifecycle diagram embedded in
``docs/robustness.md`` (same discipline as the generated knob table:
regenerate with ``python -m tools.dlilint --write-lifecycle-diagram``).

This module is pure data + string rendering: no imports from the rest
of the runtime, importable by the checker without pulling in sqlite or
jax.
"""

from __future__ import annotations

import os
from typing import NamedTuple, Tuple

# The four request states. ``pending`` and ``processing`` are live;
# the two terminal states are client-visible endpoints a request must
# reach exactly once (the dliverify ``single_terminal`` invariant).
STATES = ("pending", "processing", "completed", "failed")
TERMINAL = ("completed", "failed")

# Markers delimiting the generated diagram in docs/robustness.md.
DOC_BEGIN = ("<!-- BEGIN GENERATED LIFECYCLE DIAGRAM "
             "(python -m tools.dlilint --write-lifecycle-diagram) -->")
DOC_END = "<!-- END GENERATED LIFECYCLE DIAGRAM -->"
DOC_PATH = os.path.join("docs", "robustness.md")


class Transition(NamedTuple):
    name: str            # stable id, used in reports and the diagram
    source: Tuple[str, ...]  # declared source state(s); () = row creation
    target: str
    fn: str              # state.py function owning the write site
    # How the source set is enforced at the SQL site:
    #   "where"         WHERE constrains status to exactly `source`
    #   "not-terminal"  WHERE excludes the terminal states (first
    #                   terminal write wins; a later one no-ops)
    #   "locked-select" the UPDATE flips rows a SELECT picked under the
    #                   same store lock (claim's atomicity)
    #   "none"          unguarded by design (multi-source, id-keyed)
    #   "insert"        row creation, not an UPDATE
    guard: str
    # Durability mechanism the site must use:
    #   "barrier"   routed through Store._submit_write (group-commit
    #               buffer; committed before client visibility)
    #   "sync-txn"  direct execute inside `with self._lock, self._db`
    durability: str
    counts_attempt: bool  # SQL must contain attempts=attempts+1
    note: str             # annotation rendered into the diagram table


TRANSITIONS = (
    Transition(
        "submit", (), "pending", "submit_request", "insert", "sync-txn",
        False,
        "row created with attempts=0; claim-visible immediately"),
    Transition(
        "claim", ("pending",), "processing", "claim_next_pending_many",
        "locked-select", "sync-txn", False,
        "due rows only (next_attempt_at<=now), SLO-class priority "
        "order with deadline-style aging (state.CLAIM_AGING_S) so "
        "batch cannot starve; one locked "
        "SELECT + executemany flip keeps claims disjoint across "
        "dispatchers; claims replicate to HA standbys, so a lease "
        "takeover's recovery sees exactly the dead leader's in-flight "
        "set"),
    Transition(
        "requeue", ("processing", "pending"), "pending", "requeue",
        "none", "barrier", True,
        "failover retry: failed node appended to excluded_nodes "
        "(unless the timeout was sticky), next_attempt_at parks the "
        "backoff; re-parking an already-parked row is legal"),
    Transition(
        "complete", ("processing", "pending"), "completed",
        "mark_completed", "not-terminal", "barrier", False,
        "terminal; result+cost ride the same UPDATE so row and ledger "
        "commit atomically; a request that already reached a terminal "
        "state is never overwritten — replicated applies "
        "(Store.apply_ops) replay this exact guarded SQL, so a "
        "re-delivered frame can never flip a standby's verdict either"),
    Transition(
        "fail", ("processing", "pending"), "failed", "mark_failed",
        "not-terminal", "barrier", False,
        "terminal; covers dispatch failures, MAX_ATTEMPTS exhaustion "
        "and user cancel of a pending row; never overwrites an "
        "existing terminal state"),
    Transition(
        "migrate", ("processing",), "pending", "requeue_migrated",
        "where", "barrier", False,
        "live in-flight migration: the worker's 303 handoff carries a "
        "resume record (tokens emitted, seed, sampler position, "
        "spec-controller state) persisted on the row with a kv_source "
        "hint back at the source arena; the re-dispatch resumes "
        "mid-stream on another node; no attempt burned; the "
        "status='processing' guard means a handoff racing a terminal "
        "write never resurrects a finished row — on a replica too: a "
        "replayed migrate frame lands through this same WHERE"),
    Transition(
        "recover_fail", ("processing",), "failed",
        "recover_stale_processing", "where", "sync-txn", False,
        "crash recovery — master startup AND lease takeover (a "
        "standby promoting at term+1 runs the same site): a poison "
        "request at the attempt budget (attempts+1>=max) fails "
        "instead of re-entering the queue"),
    Transition(
        "recover_requeue", ("processing",), "pending",
        "recover_stale_processing", "where", "sync-txn", True,
        "crash recovery — master startup AND lease takeover: rows the "
        "dead leader held in 'processing' re-enter the queue with the "
        "recovery counted as an attempt; the re-dispatch presents the "
        "replicated cluster tag, so a generation the dead leader left "
        "in flight is joined/replayed, not re-run"),
)


def _check_table() -> None:
    """The table must be self-consistent before anything trusts it."""
    names = [t.name for t in TRANSITIONS]
    assert len(names) == len(set(names)), "duplicate transition names"
    # (fn, target) is the key check_lifecycle resolves SQL sites by —
    # two transitions sharing it would leave one silently unchecked
    sites = [(t.fn, t.target) for t in TRANSITIONS if t.guard != "insert"]
    assert len(sites) == len(set(sites)), \
        "two transitions share (fn, target) — sites would be ambiguous"
    for t in TRANSITIONS:
        assert t.target in STATES, f"{t.name}: unknown target {t.target}"
        for s in t.source:
            assert s in STATES, f"{t.name}: unknown source {s}"
        assert t.guard in ("where", "not-terminal", "locked-select",
                           "none", "insert"), t.name
        assert t.durability in ("barrier", "sync-txn"), t.name
        if t.target in TERMINAL:
            # terminal visibility requires a durability mechanism —
            # either the group-commit barrier or a synchronous locked
            # transaction; declared here, verified at the site by
            # check_lifecycle
            assert t.durability in ("barrier", "sync-txn"), t.name


_check_table()


def by_name(name: str) -> Transition:
    for t in TRANSITIONS:
        if t.name == name:
            return t
    raise KeyError(name)


def mermaid() -> str:
    """Deterministic mermaid state diagram of the declared machine."""
    lines = ["stateDiagram-v2"]
    for t in TRANSITIONS:
        label = t.name
        if t.counts_attempt:
            label += " (attempts+1)"
        if t.durability == "barrier":
            label += " [barrier]"
        if not t.source:
            lines.append(f"    [*] --> {t.target}: {label}")
            continue
        for s in t.source:
            lines.append(f"    {s} --> {t.target}: {label}")
    for s in TERMINAL:
        lines.append(f"    {s} --> [*]")
    return "\n".join(lines)


def transition_table() -> str:
    """Markdown table of every declared transition, rendered under the
    diagram so the guard/durability/attempt semantics are readable
    without opening state.py."""
    rows = ["| Transition | From | To | Site (`state.py`) | Guard | "
            "Durability | Attempt | Notes |",
            "| --- | --- | --- | --- | --- | --- | --- | --- |"]
    for t in TRANSITIONS:
        src = ", ".join(t.source) if t.source else "(new row)"
        rows.append(
            f"| `{t.name}` | {src} | {t.target} | `{t.fn}` | {t.guard} "
            f"| {t.durability} | {'+1' if t.counts_attempt else '—'} "
            f"| {t.note} |")
    return "\n".join(rows)


def generated_block() -> str:
    """Marker-delimited block for docs/robustness.md; the dlilint
    lifecycle checker fails when the committed block != this string."""
    return (f"{DOC_BEGIN}\n\n"
            "This diagram and table are generated from "
            "`runtime/lifecycle.py` — edit the declared\ntransition "
            "table, then run `python -m tools.dlilint "
            "--write-lifecycle-diagram`.\nHand edits here are "
            "overwritten and fail the `lifecycle` checker.\n\n"
            "```mermaid\n"
            f"{mermaid()}\n"
            "```\n\n"
            f"{transition_table()}\n\n{DOC_END}")
