"""Worker agent: the per-TPU-host data-plane process.

Capability-equivalent to the reference worker (worker/app.py:49-413) with
the same lifecycle RPC surface — /health, /load_model, /load_shard,
/unload_model, /inference — plus what the reference lacked: streaming
inference (SSE), Prometheus metrics, race-safe model lifecycle (the
reference mutated module globals from Flask handlers and was safe only
because gunicorn ran one sync worker, SURVEY.md §5.2).

The execution engine behind each loaded model is a jitted, mesh-sharded
JAX program (runtime/engine.py) instead of HF ``generate`` on torch
(reference: worker/app.py:297-305).
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Dict, Optional

import jax

from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec
from distributed_llm_inferencing_tpu.runtime import events, httpd
from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine
from distributed_llm_inferencing_tpu.utils import clock, locks, trace
from distributed_llm_inferencing_tpu.utils.faults import mutation_enabled
from distributed_llm_inferencing_tpu.utils.logging import setup_logging
from distributed_llm_inferencing_tpu.utils.metrics import Metrics
from distributed_llm_inferencing_tpu.utils.tokenizer import load_tokenizer

log = setup_logging("worker")

# Completed-result cache size for idempotent dispatch: the master
# retries with the same request_tag after a timeout, and the cached
# result makes at-least-once delivery execute exactly once.
IDEM_CACHE = int(os.environ.get("DLI_IDEM_CACHE", 256))

# Upper bound on sub-requests per /inference_batch RPC: each sub costs
# a worker thread, so the cap turns an arbitrarily long client list
# from a thread bomb into a 400.
BATCH_RPC_MAX = int(os.environ.get("DLI_BATCH_RPC_MAX", 256))

# Disaggregated serving role (FlowKV, docs/architecture.md): `prefill`
# nodes take long-prompt prefill passes, `decode` nodes take decode
# traffic (pulling prefix KV from prefill peers over /kv_fetch), and
# the default `mixed` keeps the pre-disaggregation behavior — a fleet
# that never sets the knob never changes. Role is MUTABLE worker state
# (POST /role): the master's elastic rebalancer flips workers between
# pools at runtime, re-advertised on /health and charted via the
# numeric dli_worker_role gauge below.
WORKER_ROLES = ("prefill", "decode", "mixed")
ROLE_CODE = {"mixed": 0.0, "prefill": 1.0, "decode": 2.0}

# How long a /migrate_out snapshot may wait on the scheduler before the
# endpoint gives up (the request then just keeps running here).
MIGRATE_TIMEOUT_S = 10.0

# Byte budget for one /kv_fetch response (the size cap on the KV export
# wire): the stream truncates at the cap and reports how many blocks
# were cut, and the fetching peer recomputes the rest.
KV_FETCH_MAX_MB = float(os.environ.get("DLI_KV_FETCH_MAX_MB", 256))

# Lease-fencing headers an HA master stamps on every RPC
# (docs/robustness.md "Replicated control plane"). Workers track the
# newest (term, holder nonce) they have seen and 409 any state-changing
# RPC from an older term — a paused-then-revived old leader can never
# double-dispatch, migrate, drain, or flip roles. Calls WITHOUT the
# headers (solo masters, direct clients, tests) are never fenced.
MASTER_TERM_HEADER = "X-DLI-Master-Term"
MASTER_NONCE_HEADER = "X-DLI-Master-Nonce"
STALE_TERM_HEADER = "X-DLI-Stale-Term"


class LoadedModel:
    def __init__(self, engine, tokenizer, source: str, batcher=None):
        self.engine = engine            # None in batched serving mode
        self.tokenizer = tokenizer
        self.source = source
        self.batcher = batcher          # ContinuousBatcher or None
        self.lock = locks.lock("worker.model")  # engine.generate is not reentrant


class WorkerAgent:
    """Holds loaded models and serves the lifecycle + inference RPC API."""

    def __init__(self, auth_key: Optional[str] = None,
                 role: Optional[str] = None):
        auth_key = auth_key if auth_key is not None else (
            os.environ.get("DLI_AUTH_KEY")
            if os.environ.get("DLI_AUTH_ENABLED", "").lower() in ("1", "true")
            else None)
        role = (role or os.environ.get("DLI_WORKER_ROLE") or "mixed").lower()
        if role not in WORKER_ROLES:
            raise ValueError(f"DLI_WORKER_ROLE must be one of "
                             f"{WORKER_ROLES}, got {role!r}")
        self.role = role
        self.models: Dict[str, LoadedModel] = {}
        self._models_lock = locks.lock("worker.models")
        self._loading: set = set()
        self.metrics = Metrics()
        self.started = clock.now()
        trace.set_service("worker")
        self.service = httpd.JsonHTTPService("worker", auth_key)
        s = self.service
        s.add("GET", "/health", self.health)
        s.add("GET", "/metrics", self.prometheus)
        s.add("GET", "/api/trace", self.api_trace)
        s.add("POST", "/load_model", self.load_model)
        s.add("POST", "/load_shard", self.load_shard)
        s.add("POST", "/unload_model", self.unload_model)
        # multi-LoRA adapter lifecycle (models/lora.py): make an adapter
        # host-resident / drop it; requests then name it per-submit
        s.add("POST", "/load_adapter", self.load_adapter)
        s.add("POST", "/unload_adapter", self.unload_adapter)
        s.add("POST", "/inference", self.inference)
        s.add("POST", "/inference_batch", self.inference_batch)
        # elastic disaggregation (docs/robustness.md "Live migration"):
        # runtime role flips and live in-flight request handoff
        s.add("POST", "/role", self.set_role)
        s.add("POST", "/migrate_out", self.migrate_out)
        # KV export wire (runtime/kvwire.py): stream host-arena blocks
        # to a decode-role peer as length-prefixed binary frames
        s.add("POST", "/kv_fetch", self.kv_fetch)
        s.add("POST", "/inference_stream", self.inference_stream)
        s.add("POST", "/cancel", self.cancel)
        s.add("POST", "/drain", self.drain)
        s.add("POST", "/undrain", self.undrain)
        s.add("POST", "/profile/start", self.profile_start)
        s.add("POST", "/profile/stop", self.profile_stop)
        # decode phase profiler (utils/profiler.py), distinct from the
        # XLA device profiler above: GET reads per-model summaries +
        # flamegraph JSON, POST toggles at runtime
        s.add("GET", "/api/profile", self.api_profile)
        s.add("POST", "/api/profile", self.api_profile_config)
        s.add("GET", "/memory_profile", self.memory_profile)
        s.add("POST", "/ssh_setup", self.ssh_setup)
        self._profile_dir: Optional[str] = None
        self._profile_lock = locks.lock("worker.profile")
        # request_tag -> in-flight batcher request, so a caller (the master
        # on its own timeout, or an operator) can cancel and free the slot
        self._tagged: Dict[str, object] = {}
        self._tagged_lock = locks.lock("worker.tagged")
        # Idempotent dispatch (at-least-once delivery, exactly-once
        # execution): completed results keyed by request_tag in a bounded
        # LRU, plus an in-flight registry so a duplicate dispatch JOINS
        # the running execution instead of re-generating.
        self._idem: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self._idem_lock = locks.lock("worker.idem")
        self._inflight_tags: Dict[str, threading.Event] = {}
        # graceful drain: finish in-flight work, 503 new inference
        self._draining = False
        self._active = 0
        self._active_cv = locks.condition("worker.active")
        # shared peer-fetch client for every batched model on this
        # worker (pooled keep-alive sessions to each prefill peer, the
        # worker's own fault injector for rpc:/kv_fetch chaos, conn
        # accounting in this registry); lazily built — engine-only
        # workers never pay the requests import
        self._peer_client = None
        self._peer_client_lock = locks.lock("worker.peer_client")
        # pre-register the serve-side transfer counters and the
        # headline throughput counter the dashboard's TSDB rate series
        # charts (PR 5 rule — dlilint metric-not-preregistered)
        for name in ("kv_fetch_requests", "kv_fetch_served_blocks",
                     "kv_fetch_served_bytes", "kv_fetch_missing_blocks",
                     # compression accounting: raw = full-precision bytes
                     # the served blocks restore to, sent = the stored
                     # (possibly int8-quantized) bytes that actually
                     # crossed the wire — raw/sent is the wire
                     # compression ratio the planner prices with
                     "kv_wire_raw_bytes", "kv_wire_sent_bytes",
                     "tokens_generated", "role_flips",
                     "requests_migrated_out",
                     "stale_term_rejections"):
            self.metrics.inc(name, 0)
        # worker-side lease validation state: the newest master (term,
        # holder nonce) observed on any fenced RPC; see _term_guard
        self._master_term: tuple = (0, None)
        self._master_term_lock = locks.lock("worker.master_term")
        # numeric role gauge (0 mixed / 1 prefill / 2 decode): the
        # dashboard charts role flips as a TSDB sparkline, so the
        # series must exist from the first scrape. The literal-0 call
        # is the dlilint metric-not-preregistered contract (PR 5 rule
        # — the checker wants the registered-at-0 site); the second
        # call overwrites it with this worker's actual role.
        self.metrics.gauge("worker_role", 0.0)
        self.metrics.gauge("worker_role", ROLE_CODE.get(self.role, 0.0))

    # ---- worker-side lease validation --------------------------------

    def note_master_term(self, nonce: str, term: int) -> bool:
        """One fenced RPC's term check (docs/robustness.md "Replicated
        control plane"): True = current — the caller may proceed and
        the worker's high-water (term, holder) advanced if newer;
        False = stale (an older term, or a competing holder at the
        SAME term — the split-brain guard: whoever presented a term
        first holds it here, anyone else must take a higher one)."""
        with self._master_term_lock:
            cur_term, cur_nonce = self._master_term
            if term > cur_term:
                self._master_term = (int(term), str(nonce))
                return True
            if term == cur_term and (cur_nonce is None
                                     or cur_nonce == nonce):
                if cur_nonce is None:
                    self._master_term = (int(term), str(nonce))
                return True
        if mutation_enabled("stale_term_check"):
            # dliverify mutation gate (docs/static_analysis.md): skip
            # the worker-side fence — the double-dispatch bug the
            # `lease_takeover` scenario must catch. Test-only flag.
            return True
        self.metrics.inc("stale_term_rejections")
        return False

    def master_term(self) -> int:
        """Newest master term this worker has fenced against."""
        with self._master_term_lock:
            return self._master_term[0]

    def _term_guard(self, _request):
        """None when the caller may proceed; else the 409 refusal for a
        stale-term dispatch (the ``X-DLI-Stale-Term`` response header
        tells the old leader which term deposed it, so it steps down
        instead of striking/requeueing state it no longer owns)."""
        if _request is None:
            return None
        raw = _request.headers.get(MASTER_TERM_HEADER)
        if not raw:
            return None       # un-fenced caller (solo master / client)
        try:
            term = int(raw)
        except (TypeError, ValueError):
            return None
        nonce = _request.headers.get(MASTER_NONCE_HEADER) or ""
        if self.note_master_term(nonce, term):
            return None
        cur = self.master_term()
        return 409, {"status": "error", "stale_term": True,
                     "message": f"master term {term} is stale "
                                f"(current lease term: {cur})"}, \
            {STALE_TERM_HEADER: str(cur)}

    # ---- endpoints ---------------------------------------------------

    def health(self, body):
        """Parity with reference /health (worker/app.py:49-92): status +
        resource stats + loaded model inventory; TPU stats replace CUDA."""
        devices = []
        for d in jax.devices():
            entry = {"id": d.id, "platform": d.platform,
                     "kind": getattr(d, "device_kind", "unknown")}
            try:
                ms = d.memory_stats()
                if ms:
                    entry["bytes_in_use"] = ms.get("bytes_in_use")
                    entry["bytes_limit"] = ms.get("bytes_limit")
                    # the planner's memory-feasibility input (node-class
                    # fitting, parallel/planner.py): per-device HBM a
                    # candidate plan's weights + KV must fit under
                    entry["memory_bytes"] = ms.get("bytes_limit")
            except Exception as e:
                # CPU backends raise per scrape — stats stay best-effort
                log.debug("device memory_stats unavailable: %r", e)
            devices.append(entry)
        try:
            import psutil
            cpu = psutil.cpu_percent(interval=None)
            mem = psutil.virtual_memory().percent
        except Exception:
            cpu = mem = None
        with self._models_lock:  # load/unload mutate concurrently
            loaded = []
            for n, m in self.models.items():
                if m.batcher is not None:
                    loaded.append({"name": n, "source": m.source,
                                   "serving": "batched",
                                   "max_seq": m.batcher.max_seq,
                                   "scheduler": m.batcher.stats()})
                else:
                    loaded.append({"name": n, "source": m.source,
                                   "mesh": m.engine.mesh_spec.axis_sizes(),
                                   "max_seq": m.engine.max_seq,
                                   "adapters": m.engine.adapter_stats()})
        # host-arena occupancy fraction (worst across batched models):
        # the master's scheduler keeps prefill traffic off nodes whose
        # arena is about to evict the blocks a decode peer needs
        occ = None
        for lm in loaded:
            kv = (lm.get("scheduler") or {}).get("kvtier")
            if isinstance(kv, dict) and kv.get("occupancy") is not None:
                occ = max(occ or 0.0, float(kv["occupancy"]))
        return {
            "status": "draining" if self._draining else "online",
            "uptime_s": clock.now() - self.started,
            "role": self.role,
            "arena_occupancy": occ,
            "resources": {"cpu": cpu, "memory": mem, "devices": devices,
                          "device": jax.default_backend()},
            "loaded_models": loaded,
            "metrics": self.metrics.snapshot(),
        }

    def prometheus(self, body):
        return (self.metrics.prometheus().encode(), "text/plain; version=0.0.4")

    def api_trace(self, body):
        """This process's span ring buffer as Chrome trace-event JSON
        (utils/trace.py) — load in Perfetto, or let the master's
        /api/trace merge it into the cluster-wide timeline. When the
        decode profiler is armed, its sampled per-phase step spans merge
        onto a dedicated track of the same export."""
        tracer = trace.get_tracer()
        extra = []
        with self._models_lock:
            models = list(self.models.values())
        for m in models:
            if m.batcher is not None and m.batcher.profiler.enabled:
                extra.extend(m.batcher.profiler.chrome_events(
                    tracer.export_pid()))
        return tracer.chrome_trace(extra_events=extra)

    def _batcher_profilers(self):
        with self._models_lock:
            return [(n, m.batcher.profiler)
                    for n, m in self.models.items()
                    if m.batcher is not None]

    def api_profile(self, body):
        """Decode-profiler readout: per-phase wall attribution of the
        batcher step loop (summary + d3-flamegraph JSON) per batched
        model. Zero-cost when the profiler is off — the payload then
        just reports enabled=false."""
        out = {}
        for name, p in self._batcher_profilers():
            out[name] = {"summary": p.summary(), "flame": p.flame()}
        return {"status": "success", "profilers": out}

    def api_profile_config(self, body):
        """Runtime toggle: ``{"enabled": true, "sample_every": 4}``
        arms every batched model's profiler (``reset`` clears the
        ring). Applies to models loaded NOW; a model loaded later
        starts from the DLI_PROFILE env default."""
        cfgs = {}
        for name, p in self._batcher_profilers():
            cfgs[name] = p.configure(
                enabled=body.get("enabled"),
                sample_every=body.get("sample_every"),
                reset=bool(body.get("reset")))
        if not cfgs:
            return 409, {"status": "error",
                         "message": "no batched models loaded"}
        return {"status": "success", "profilers": cfgs}

    def _do_load(self, body) -> tuple:
        name = body.get("model_name")
        if not name:
            return 400, {"status": "error", "message": "model_name required"}
        with self._models_lock:
            if name in self.models:
                # idempotent, like reference worker/app.py:106-110
                return 200, {"status": "success",
                             "message": f"model {name} already loaded"}
            if name in self._loading:
                # the double-load race the reference left open (SURVEY §5.2)
                return 409, {"status": "error",
                             "message": f"model {name} load in progress"}
            self._loading.add(name)
        try:
            return self._do_load_inner(body, name)
        finally:
            with self._models_lock:
                self._loading.discard(name)

    def _do_load_inner(self, body, name) -> tuple:
        ckpt = body.get("checkpoint_path")
        native = body.get("native_checkpoint")
        mesh = MeshSpec.from_dict(body.get("mesh", {}))
        t0 = clock.now()
        if body.get("serving") == "batched" and any(
                getattr(mesh, ax) > 1 for ax in ("dp", "sp")):
            # validate BEFORE any (possibly huge) checkpoint restore; the
            # batcher shards tensors (tp/ep) and pipeline stages (pp) but
            # owns the batch dimension itself (runtime/batcher.py)
            return 400, {"status": "error",
                         "message": "batched serving supports tp/ep/pp "
                                    "mesh axes; drop dp/sp or use "
                                    "default mode"}
        if native:
            # converted-once artifact (models/checkpoint.py): no torch on
            # the serving path, restore is sharded when a mesh is in play
            from distributed_llm_inferencing_tpu.models import checkpoint
            from distributed_llm_inferencing_tpu.parallel.mesh import create_mesh
            cfg, params = checkpoint.load_checkpoint(
                native,
                mesh=create_mesh(mesh) if mesh.num_devices > 1 else None,
                mesh_spec=mesh if mesh.num_devices > 1 else None,
                dtype=body.get("dtype"))
            cfg = cfg.replace(name=name)
            source = native
        elif ckpt:
            from distributed_llm_inferencing_tpu.models.convert import load_hf_model
            cfg, params = load_hf_model(ckpt)
            cfg = cfg.replace(name=name)
            source = ckpt
        else:
            try:
                cfg = get_config(name)
            except KeyError as e:
                return 400, {"status": "error", "message": str(e)}
            params = None  # random init — explicit opt-in
            if not body.get("allow_random_init"):
                return 400, {
                    "status": "error",
                    "message": "no checkpoint_path given; pass "
                               "allow_random_init=true for a demo model"}
            source = "random-init"
        if body.get("dtype"):
            cfg = cfg.replace(dtype=body["dtype"])
        if body.get("kv_quantize"):
            # int8 KV cache (ops/kvcache.py): halves cache traffic and
            # footprint for long contexts, on top of weight int8
            cfg = cfg.replace(kv_quant=body["kv_quantize"])
        if body.get("quantize"):
            cfg = cfg.replace(quant=body["quantize"])
            if params is not None:
                # donate: the float tree is ours and never reused, so each
                # weight frees as its int8 twin lands (peak ≈ float model +
                # one stacked weight, not 1.5x). Pre-baked int8 checkpoints
                # (`convert --quantize int8`) skip even that.
                from distributed_llm_inferencing_tpu.ops.quant import (
                    maybe_quantize)
                params = maybe_quantize(params, cfg, donate=True)
        if body.get("embed_quantize"):
            # per-row int8 token-embedding table (ops/quant.py): the
            # tied-head read and the table footprint both halve
            cfg = cfg.replace(embed_quant=body["embed_quantize"])
            if params is not None:
                from distributed_llm_inferencing_tpu.ops.quant import (
                    maybe_quantize_embed)
                params = maybe_quantize_embed(params, cfg, donate=True)
        from distributed_llm_inferencing_tpu.utils.tokenizer import has_tokenizer
        tok_dir = body.get("tokenizer_path") or next(
            (d for d in (ckpt, native) if has_tokenizer(d)), None)
        tok = load_tokenizer(tok_dir, cfg.vocab_size)
        if body.get("serving") == "batched":
            # Continuous batching over the paged KV cache
            # (runtime/batcher.py) — requests share decode steps instead of
            # serializing behind the per-model lock.
            from distributed_llm_inferencing_tpu.runtime.batcher import (
                ContinuousBatcher)
            batcher = ContinuousBatcher(
                cfg, params,
                num_blocks=int(body.get("kv_blocks", 512)),
                block_size=int(body.get("kv_block_size", 16)),
                slots=int(body.get("slots", 8)),
                max_seq=body.get("max_seq"),
                # chunked prefill cap (blocks); 0/null disables
                prefill_chunk=(int(body["prefill_chunk"])
                               if body.get("prefill_chunk") is not None
                               else None) if "prefill_chunk" in body else 32,
                # on-device prompt-lookup speculative decoding
                # (transformer.paged_speculative_chunk): greedy requests
                # get up to spec_gamma+1 tokens/iteration bit-identically
                speculative=body.get("speculative"),
                spec_gamma=int(body.get("spec_gamma", 4)),
                # host-RAM KV offload arena budget (runtime/kvtier.py);
                # None defers to DLI_KV_HOST_MB, 0 disables the tier
                kv_host_mb=(float(body["kv_host_mb"])
                            if body.get("kv_host_mb") is not None
                            else None),
                kv_digest_chunk=(int(body["kv_digest_chunk"])
                                 if body.get("kv_digest_chunk") else None),
                # latency-tier knob: cap the decode-chunk size so token
                # gaps track real steps instead of K-sized bursts
                decode_chunk_cap=(int(body["decode_chunk_cap"])
                                  if body.get("decode_chunk_cap")
                                  else None),
                # cross-node KV transfer (runtime/kvwire.py): every
                # batched model shares the worker's peer-fetch client
                kv_fetcher=self.peer_client(),
                mesh_spec=mesh, metrics=self.metrics)
            batcher.start()
            lm = LoadedModel(None, tok, source, batcher=batcher)
            stats = batcher.stats()
        else:
            engine = InferenceEngine(
                cfg, params, mesh_spec=mesh, max_seq=body.get("max_seq"),
                metrics=self.metrics)
            lm = LoadedModel(engine, tok, source)
            stats = engine.stats()
        with self._models_lock:
            self.models[name] = lm
        self.metrics.inc("models_loaded")
        log.info("loaded %s from %s in %.1fs", name, source, clock.now() - t0)
        return 200, {"status": "success",
                     "message": f"model {name} loaded",
                     "load_time_s": clock.now() - t0,
                     "stats": stats}

    def load_model(self, body, _request=None):
        # lease-fenced like every state-changing RPC: a revived stale
        # leader must not (re)load models under the current leader
        stale = self._term_guard(_request)
        if stale:
            return stale
        if self._draining:
            return self._refuse_draining()
        with self.metrics.time("load_model"):
            return self._do_load(body)

    def load_shard(self, body, _request=None):
        """Reference parity (worker/app.py:139-206): registering a 'shard'.

        TPU-native meaning: a placement plan (mesh spec + partition specs,
        parallel/plan.py) rather than a weight-file directory — loading a
        'shard' is loading the model with that plan's mesh. Lease-fenced
        like /load_model.
        """
        stale = self._term_guard(_request)
        if stale:
            return stale
        if self._draining:
            return self._refuse_draining()
        plan = body.get("plan")
        if not plan:
            return 400, {"status": "error",
                         "message": "plan required (parallel/plan.py output)"}
        body = dict(body)
        body.setdefault("model_name", plan.get("model"))
        body.setdefault("mesh", plan.get("mesh", {}))
        body.setdefault("max_seq", plan.get("max_seq"))
        return self._do_load(body)

    def unload_model(self, body, _request=None):
        """Parity with worker/app.py:208-250; device buffers are dropped by
        deleting the engine (XLA frees HBM on GC). Lease-fenced: a
        revived stale leader's best-effort unload (remove_node tail)
        must not evict a model the current leader is serving
        mid-generation."""
        stale = self._term_guard(_request)
        if stale:
            return stale
        name = body.get("model_name")
        with self._models_lock:
            m = self.models.pop(name, None)
        if m is None:
            return 404, {"status": "error",
                         "message": f"model {name} not loaded"}
        if m.batcher is not None:
            m.batcher.stop()
        del m
        import gc
        gc.collect()
        self.metrics.inc("models_unloaded")
        return {"status": "success", "message": f"model {name} unloaded"}

    def load_adapter(self, body, _request=None):
        """Make a LoRA adapter host-resident for a loaded model
        (lease-fenced like /load_model; the master's lazy dispatch-time
        load and operator calls both land here). Idempotent for an
        already-resident name. Any refusal is a structured 400 — a
        request naming an unloadable adapter FAILS, it never silently
        serves base weights."""
        stale = self._term_guard(_request)
        if stale:
            return stale
        if self._draining:
            return self._refuse_draining()
        model = body.get("model_name")
        adapter = body.get("adapter")
        source = body.get("source")
        if not (model and adapter and source):
            return 400, {"status": "error",
                         "message": "model_name, adapter and source "
                                    "required"}
        m = self.models.get(model)
        if m is None:
            return 404, {"status": "error",
                         "message": f"model {model} not loaded"}
        with self.metrics.time("load_adapter"):
            try:
                if m.batcher is not None:
                    info = m.batcher.load_adapter(adapter, source)
                else:
                    ad = m.engine.load_adapter(name=adapter, source=source)
                    info = {"name": ad.name, "rank": ad.rank,
                            "nbytes": ad.nbytes, "evicted": []}
            except ValueError as e:
                events.emit("adapter-load-failed", adapter=adapter,
                            model=model, error=str(e))
                return 400, {"status": "error", "adapter": adapter,
                             "message": str(e)}
        for ev in info.get("evicted", []):
            events.emit("adapter-evicted", adapter=ev, model=model,
                        evicted_for=adapter)
        events.emit("adapter-loaded", adapter=adapter, model=model,
                    rank=info.get("rank"), nbytes=info.get("nbytes"),
                    lazy=bool(body.get("lazy")))
        return {"status": "success", **info}

    def unload_adapter(self, body, _request=None):
        """Drop a host-resident adapter (refused while requests still
        reference it). Lease-fenced like /unload_model."""
        stale = self._term_guard(_request)
        if stale:
            return stale
        model = body.get("model_name")
        adapter = body.get("adapter")
        m = self.models.get(model)
        if m is None:
            return 404, {"status": "error",
                         "message": f"model {model} not loaded"}
        try:
            if m.batcher is not None:
                dropped = m.batcher.unload_adapter(adapter)
            else:
                dropped = m.engine.unload_adapter(adapter)
        except ValueError as e:
            return 409, {"status": "error", "message": str(e)}
        if not dropped:
            return 404, {"status": "error",
                         "message": f"adapter {adapter} not resident"}
        return {"status": "success", "adapter": adapter}

    def _prep_inference(self, body):
        name = body.get("model_name")
        m = self.models.get(name)
        if m is None:
            raise KeyError(f"model {name} not loaded")
        resume = body.get("resume")
        resume = resume if isinstance(resume, dict) else None
        if (resume and resume.get("prompt_tokens")
                and "prompt_tokens" not in body):
            # a migrated-in request resumes from the SOURCE's exact
            # token ids — re-tokenizing the text would be identical on
            # a same-tokenizer fleet, but exactness is the contract
            prompt = [int(t) for t in resume["prompt_tokens"]]
        elif "prompt_tokens" in body:
            prompt = [int(t) for t in body["prompt_tokens"]]
        else:
            prompt = m.tokenizer.encode(body.get("prompt", ""))
        if not prompt:
            raise ValueError("empty prompt")
        sp_body = body.get("sampling", {})
        sp = SamplingParams(
            temperature=float(sp_body.get("temperature", 0.8)),
            top_k=int(sp_body.get("top_k", 50)),
            top_p=float(sp_body.get("top_p", 0.95)),
            do_sample=bool(sp_body.get("do_sample", True)))
        # reference parity: max_length counts prompt+new (views.py:351);
        # max_new_tokens preferred.
        if "max_new_tokens" in body:
            max_new = int(body["max_new_tokens"])
        else:
            max_new = max(1, int(body.get("max_length", 100)) - len(prompt))
        spec = body.get("speculative")
        try:
            gamma = int(body.get("spec_gamma", 4))
        except (TypeError, ValueError):
            raise ValueError("spec_gamma must be an integer")
        if spec is not None:
            if spec != "ngram":
                raise ValueError(f"unknown speculative mode {spec!r} "
                                 "(supported: 'ngram')")
            if not 1 <= gamma <= 16:
                raise ValueError("spec_gamma must be in [1, 16]")
            if m.batcher is not None:
                raise ValueError(
                    "speculative decoding is engine-mode only; this model "
                    "serves via the continuous batcher")
        # single source of generate() kwargs: every serving path (blocking,
        # SSE, lockstep co-execution) passes these verbatim, so they can
        # never silently disagree about a request's decode configuration.
        # A resume record's seed wins: an engine-mode node receiving a
        # migrated request regenerates the FULL stream from position 0,
        # and the position-keyed PRNG makes that reproduction exact only
        # under the source's seed.
        if resume is not None and resume.get("seed") is not None:
            seed = int(resume["seed"])
        else:
            seed = int(body.get("seed", time.time_ns() % (1 << 31)))
        # a migrated request must resume under its source ADAPTER too —
        # same exactness contract as the seed above
        if resume is not None and resume.get("adapter"):
            adapter = str(resume["adapter"])
        else:
            adapter = body.get("adapter") or None
        gen_kw = {
            "seed": seed,
            "speculative": spec,
            "spec_gamma": gamma,
            "adapter": adapter,
        }
        return m, prompt, sp, max_new, gen_kw

    # ---- drain / idempotency plumbing --------------------------------

    def _refuse_draining(self):
        return 503, {"status": "error", "draining": True,
                     "message": "worker is draining; retry another node"}, \
               {"Retry-After": "5"}

    def _try_begin_inference(self) -> bool:
        """Atomically either register an in-flight inference or refuse
        because a drain is in progress. The draining check and the
        active-count increment share one lock: without that, a request
        could pass the check before drain set the flag yet not be
        counted when drain samples the in-flight total — and drain
        would report idle with work about to start."""
        with self._active_cv:
            if self._draining:
                return False
            self._active += 1
        return True

    def _end_inference(self):
        with self._active_cv:
            self._active -= 1
            self._active_cv.notify_all()

    def _busy_count(self) -> int:
        """Requests still owed an answer. A batched HTTP request shows
        up in BOTH the handler count and its batcher's inflight() —
        max() de-duplicates that (it is exact for idle detection: zero
        iff both are zero) while still covering batcher requests whose
        handler already gave up (cancelled/abandoned tags)."""
        with self._active_cv:
            n = self._active
        with self._models_lock:
            models = list(self.models.values())
        batched = sum(m.batcher.inflight() for m in models
                      if m.batcher is not None)
        return max(n, batched)

    def _wait_idle(self, timeout: float) -> bool:
        deadline = clock.now() + timeout
        while clock.now() < deadline:
            if self._busy_count() == 0:
                return True
            clock.sleep(0.05)
        return self._busy_count() == 0

    def drain(self, body, _request=None):
        """Graceful drain — no reference counterpart (its only lifecycle
        was kill -9). Marks the worker draining: new inference gets 503
        with Retry-After (the master fails over without recording a
        strike, runtime/master.py), in-flight batcher/engine requests
        run to completion, and this call returns once idle (or when
        ``timeout`` seconds elapse, reporting what is still in flight).
        Lease-fenced: only the current lease holder may drain this
        worker — a revived old leader's drain is a 409."""
        stale = self._term_guard(_request)
        if stale:
            return stale
        with self._active_cv:   # fences against _try_begin_inference
            self._draining = True
        self.metrics.gauge("draining", 1)
        idle = self._wait_idle(float(body.get("timeout", 30)))
        return {"status": "success", "drained": idle,
                "in_flight": self._busy_count()}

    def undrain(self, body, _request=None):
        """Re-open a drained worker for new inference (lease-fenced
        like /drain)."""
        stale = self._term_guard(_request)
        if stale:
            return stale
        with self._active_cv:
            self._draining = False
        self.metrics.gauge("draining", 0)
        return {"status": "success"}

    def inference(self, body, _request=None):
        # semantic span under the HTTP server span; the batcher/engine
        # below parent their own spans to it (contextvar or req.trace_ctx)
        stale = self._term_guard(_request)
        if stale:
            return stale
        if not self._try_begin_inference():
            return self._refuse_draining()
        try:
            with trace.get_tracer().span(
                    "worker.inference",
                    attrs={"model": str(body.get("model_name")),
                           "tag": str(body.get("request_tag") or "")}):
                return self._inference_idempotent(body)
        finally:
            self._end_inference()

    def inference_batch(self, body, _request=None):
        """Multiplexed dispatch: N sub-requests in ONE RPC, per-request
        results streamed back as chunked JSON lines the moment each
        completes (httpd.jsonl_stream keeps the connection reusable).
        Every sub-request keeps the exact /inference semantics — its own
        idempotency tag (replay/join), its own drain refusal, its own
        structured error — so a master can fail/requeue ONE sub-request
        without touching its batch siblings. Batcher-mode models admit
        owned (fresh-tag) sub-requests through ContinuousBatcher
        .submit_many in wire order, so FIFO survives the multiplexing.
        """
        stale = self._term_guard(_request)
        if stale:
            # whole-batch refusal: every sub came from the same stale
            # master, and the current leader re-dispatches them all
            return stale
        subs = body.get("requests")
        if not isinstance(subs, list) or not subs:
            return 400, {"status": "error",
                         "message": "requests: non-empty list required"}
        if len(subs) > BATCH_RPC_MAX:
            # one thread + one queue slot per sub: an uncapped list is
            # a one-connection thread bomb (masters send DISPATCH_BATCH)
            return 400, {"status": "error",
                         "message": f"requests: at most {BATCH_RPC_MAX} "
                                    f"sub-requests per batch RPC"}
        if self._draining:
            # whole-batch refusal BEFORE any work starts: the master
            # fails the batch over without a breaker strike
            return self._refuse_draining()
        model = body.get("model_name")
        with self._models_lock:
            m = self.models.get(model)
        self.metrics.inc("batch_rpcs")
        self.metrics.inc("batch_sub_requests", len(subs))
        import queue as _queue
        out: "_queue.Queue" = _queue.Queue()
        ctx = trace.current()   # sub-request work runs on helper threads

        def emit(tag, status, payload):
            out.put({"request_tag": tag, "status": status, "body": payload})

        def norm(res):
            if isinstance(res, tuple):
                return res[0], res[1]
            return 200, res

        def run_generic(sub_body, tag):
            """One sub-request through the standard idempotent path —
            joins, engine-mode models, untagged requests."""
            try:
                if not self._try_begin_inference():
                    st, pl = norm(self._refuse_draining())
                else:
                    try:
                        # the master injects each sub-request's own trace
                        # context into its body — parent there so this
                        # span lands in the request's trace, not the
                        # batch RPC's
                        with trace.get_tracer().span(
                                "worker.inference",
                                parent=trace.extract(sub_body) or ctx,
                                attrs={"model": str(model),
                                       "tag": tag or ""}):
                            st, pl = norm(self._inference_idempotent(
                                sub_body))
                    finally:
                        self._end_inference()
            except Exception as e:
                st, pl = 500, {"status": "error", "message": str(e)}
            emit(tag, st, pl)

        owned = []   # (sub_body, tag, my_event-or-None) for batcher path
        for sub in subs:
            sub_body = dict(sub)
            sub_body["model_name"] = model
            tag = (str(sub.get("request_tag"))
                   if sub.get("request_tag") else None)
            if m is not None and m.batcher is not None:
                if tag is None:
                    owned.append((sub_body, None, None))
                    continue
                kind, obj = self._idem_claim(tag)
                if kind == "cached":
                    self.metrics.inc("idempotent_hits")
                    emit(tag, 200, dict(obj, idempotent=True))
                    continue
                if kind == "own":
                    owned.append((sub_body, tag, obj))
                    continue
                # kind == "join": the generic path's join loop handles it
            threading.Thread(target=run_generic, args=(sub_body, tag),
                             daemon=True).start()

        self._start_owned_batch(m, owned, emit, ctx)

        def events():
            # every sub-request emits exactly one line, on every path
            for _ in range(len(subs)):
                yield out.get()

        return httpd.jsonl_stream(_request, events())

    def _start_owned_batch(self, m, owned, emit, ctx):
        """Prep + multi-submit the owned (fresh) batcher sub-requests in
        wire order, then wait each out on its own thread. Prep/validation
        failures resolve per sub-request (400 line + ownership release),
        never the batch."""
        specs, metas = [], []
        for sub_body, tag, my_ev in owned:
            t0 = clock.now()
            try:
                _m, prompt, sp, max_new, _gk = self._prep_inference(sub_body)
                if len(prompt) + max_new > m.batcher.max_seq:
                    raise ValueError(
                        f"prompt ({len(prompt)}) + max_new_tokens "
                        f"({max_new}) exceeds max_seq {m.batcher.max_seq}")
            except Exception as e:
                # EVERY prep failure must resolve this sub in place —
                # an exception escaping the loop would leak the earlier
                # subs' _active counts and never-released idempotency
                # events (specs built but submit_many never reached)
                if my_ev is not None:
                    self._idem_release(tag, my_ev, None)
                st = 400 if isinstance(e, (KeyError, ValueError)) else 500
                emit(tag, st, {"status": "error", "message": str(e)})
                continue
            if not self._try_begin_inference():
                if my_ev is not None:
                    self._idem_release(tag, my_ev, None)
                st, pl = self._refuse_draining()[:2]
                emit(tag, st, pl)
                continue
            resume = sub_body.get("resume")
            specs.append({"prompt": prompt, "max_new_tokens": max_new,
                          "sampling": sp,
                          "eos_token_id": m.tokenizer.eos_token_id,
                          "seed": sub_body.get("seed"),
                          "kv_transfer_bytes": 0,
                          "kv_export": bool(sub_body.get("kv_export")),
                          "resume": (resume if isinstance(resume, dict)
                                     else None),
                          "chunk_cap": sub_body.get("decode_chunk_cap"),
                          "adapter": sub_body.get("adapter"),
                          "trace_ctx": trace.extract(sub_body) or ctx})
            self._note_prefix(m, sub_body, prompt)
            metas.append((sub_body, tag, my_ev, t0))
        # peer KV prefetches run CONCURRENTLY across the batch: serial
        # blocking fetches in the loop above would let one dead peer's
        # connect timeout delay every later sibling's submission by the
        # full timeout each — in parallel the batch pays one timeout

        def _fetch_seq(i):
            return self._resume_seq(specs[i]["prompt"],
                                    specs[i].get("resume"))

        fetch_idx = [i for i, (sub_body, *_r) in enumerate(metas)
                     if sub_body.get("kv_source")]
        if fetch_idx:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(
                    max_workers=min(8, len(fetch_idx))) as ex:
                for i, pre in zip(fetch_idx, ex.map(
                        lambda i: self._prefetch_kv(
                            m, metas[i][0], _fetch_seq(i)),
                        fetch_idx)):
                    specs[i]["kv_transfer_bytes"] = pre
        try:
            reqs = m.batcher.submit_many(specs) if specs else []
        except Exception as e:
            # all-or-nothing submit refused the whole group: release
            # every admitted sub (count + idempotency event) in place
            for _sub_body, tag, my_ev, _t0 in metas:
                if my_ev is not None:
                    self._idem_release(tag, my_ev, None)
                self._end_inference()
                emit(tag, 500, {"status": "error", "message": str(e)})
            return
        for breq, meta in zip(reqs, metas):
            threading.Thread(target=self._wait_owned,
                             args=(m, breq, emit) + meta,
                             daemon=True).start()

    def _wait_owned(self, m, breq, emit, sub_body, tag, my_ev, t0):
        """Block on one batch-submitted generation; mirror the single
        /inference result shape, metrics, cancel registration, and
        idempotency-cache population."""
        res = None
        st, pl = 500, {"status": "error", "message": "internal error"}
        if tag is not None:
            with self._tagged_lock:
                self._tagged[tag] = breq
        try:
            with self.metrics.time("inference"):
                toks = breq.wait(
                    timeout=float(sub_body.get("timeout", 300)))
            res = {
                "status": "success",
                "result": m.tokenizer.decode(toks),
                "tokens": toks,
                "execution_time": clock.now() - t0,
                "ttft_ms": breq.ttft_ms,
                "cost": breq.cost,
                "scheduler": m.batcher.stats(),
            }
            self.metrics.inc("requests_completed")
            self.metrics.inc("tokens_generated", len(toks))
            st, pl = 200, res
        except TimeoutError as e:
            breq.cancel()   # free the slot; don't generate for nobody
            st, pl = 408, {"status": "error", "message": str(e)}
        except (ValueError, RuntimeError) as e:
            if breq._migrated:
                # live-migration handoff rides this sub-request's own
                # result line: 303 + resume record, same semantics as
                # the single-dispatch path
                st, pl = 303, {"status": "migrated",
                               "resume": breq.resume_record,
                               "request_tag": tag}
            else:
                st, pl = 400, {"status": "error", "message": str(e)}
        except Exception as e:
            st, pl = 500, {"status": "error", "message": str(e)}
        finally:
            if tag is not None:
                with self._tagged_lock:
                    self._tagged.pop(tag, None)
                self._idem_release(tag, my_ev, res)
            self._end_inference()
            emit(tag, st, pl)

    def set_role(self, body, _request=None):
        """Runtime role flip (the master's elastic rebalancer,
        docs/robustness.md "Live migration"): role becomes mutable
        worker state, re-advertised on the next /health and charted
        via the numeric ``dli_worker_role`` gauge. The routing
        consequences are entirely the master's — this worker serves
        whatever is dispatched to it either way. Lease-fenced: only
        the current lease holder may flip roles."""
        stale = self._term_guard(_request)
        if stale:
            return stale
        role = str(body.get("role") or "").lower()
        if role not in WORKER_ROLES:
            return 400, {"status": "error",
                         "message": f"role must be one of {WORKER_ROLES},"
                                    f" got {role!r}"}
        prev, self.role = self.role, role
        self.metrics.gauge("worker_role", ROLE_CODE.get(role, 0.0))
        if prev != role:
            self.metrics.inc("role_flips")
            log.info("worker role flipped %s -> %s", prev, role)
        return {"status": "success", "role": role, "previous": prev}

    def migrate_out(self, body, _request=None):
        """Live in-flight migration handoff (master rebalancer): ask
        the owning batcher to snapshot the tagged request — export its
        computed KV through the last context position into the host
        arena (where a destination's /kv_fetch finds it) and evict the
        slot. The ORIGINAL dispatch then answers with a 303 + resume
        record — the handoff descriptor rides the already-open RPC, so
        the master's dispatch thread stays the request's only lifecycle
        owner; this endpoint only triggers and confirms. 404: no such
        in-flight tag. 409: the request completed first (the
        migrate-vs-complete race — the normal result stands, the
        request_tag idempotency cache replays it, nothing double-emits)
        or the serving mode cannot migrate (engine mode, lockstep).
        Lease-fenced: a stale master must not migrate a request the
        current leader is streaming."""
        stale = self._term_guard(_request)
        if stale:
            return stale
        tag = body.get("request_tag")
        if not tag:
            return 400, {"status": "error",
                         "message": "request_tag required"}
        with self._tagged_lock:
            req = self._tagged.get(str(tag))
        if req is None:
            return 404, {"status": "error",
                         "message": f"no in-flight request tagged {tag!r}"}
        name = body.get("model_name")
        with self._models_lock:
            models = ([self.models[name]] if name in self.models
                      else list(self.models.values()))
        batcher = next((m.batcher for m in models
                        if m.batcher is not None), None)
        if batcher is None:
            return 409, {"status": "error",
                         "message": "engine-mode requests cannot migrate"}
        rec = batcher.migrate_out(req, timeout=MIGRATE_TIMEOUT_S)
        if rec is None:
            return 409, {"status": "error",
                         "message": f"request {tag!r} completed before "
                                    "the snapshot (or cannot migrate)"}
        self.metrics.inc("requests_migrated_out")
        return {"status": "success", "request_tag": str(tag)}

    def peer_client(self):
        """The worker-wide KVFetchClient (runtime/kvwire.py), built on
        first use and injected into every batched model's batcher."""
        with self._peer_client_lock:
            if self._peer_client is None:
                from distributed_llm_inferencing_tpu.runtime.kvwire import (
                    KVFetchClient)
                self._peer_client = KVFetchClient(
                    auth_key=self.service.auth_key,
                    faults=self.service.faults, metrics=self.metrics)
            return self._peer_client

    def kv_fetch(self, body, _request=None):
        """KV export wire (runtime/kvwire.py): given a model and a list
        of block digests, stream the matching host-arena blocks back as
        length-prefixed binary frames over the chunked httpd response.
        Auth-gated like every route (fleet bearer token); size-capped at
        DLI_KV_FETCH_MAX_MB — past the cap the stream truncates and the
        terminal frame says so, and the peer recomputes the rest. Blocks
        the arena no longer holds are simply reported missing: eviction
        raced the fetch, recompute covers it."""
        from distributed_llm_inferencing_tpu.runtime import kvwire
        name = body.get("model_name")
        with self._models_lock:
            m = self.models.get(name)
        if m is None or m.batcher is None or m.batcher.kvtier is None:
            return 404, {"status": "error",
                         "message": f"model {name} not serving a KV "
                                    "arena on this worker"}
        digests = body.get("digests")
        if (not isinstance(digests, list) or not digests
                or not all(isinstance(d, str) for d in digests)):
            return 400, {"status": "error",
                         "message": "digests: non-empty list of strings "
                                    "required"}
        if len(digests) > kvwire.MAX_DIGESTS:
            return 400, {"status": "error",
                         "message": f"at most {kvwire.MAX_DIGESTS} "
                                    "digests per fetch"}
        arena = m.batcher.kvtier.arena
        cap = int(KV_FETCH_MAX_MB * 1024 * 1024)
        self.metrics.inc("kv_fetch_requests")

        def frames():
            sent = served = truncated = 0
            missing = []
            for i, d in enumerate(digests):
                # ship the STORED representation as-is: an int8 arena's
                # block crosses the wire as its quantized record (kvq8
                # frame), never requantized or inflated on send
                obj = arena.peek_stored(d)
                if obj is None:
                    missing.append(d)
                    self.metrics.inc("kv_fetch_missing_blocks")
                    continue
                frame = kvwire.encode_stored(d, obj)
                if sent + len(frame) > cap:
                    truncated = len(digests) - i
                    break
                sent += len(frame)
                served += 1
                self.metrics.inc("kv_fetch_served_blocks")
                self.metrics.inc("kv_fetch_served_bytes", len(frame))
                self.metrics.inc("kv_wire_sent_bytes",
                                 kvwire.stored_nbytes(obj))
                self.metrics.inc("kv_wire_raw_bytes",
                                 kvwire.logical_nbytes(obj))
                yield frame
            # served_bytes: what actually crossed, so a size-capped
            # partial is distinguishable from a disconnect and the
            # peer's recompute fallback is sized to the true shortfall
            yield kvwire.encode_end(served, missing, truncated,
                                    served_bytes=sent)

        return httpd.binary_stream(_request, frames())

    @staticmethod
    def _resume_seq(prompt, resume):
        """The sequence whose prefix KV a dispatch should prefetch:
        prompt plus any migrated-in resume tokens — a resumed request's
        prefix covers its already-emitted tokens too. The single
        definition both dispatch paths use, so they can never prefetch
        different prefixes for the same resume record."""
        if not isinstance(resume, dict):
            return prompt
        return prompt + [int(t) for t in resume.get("tokens") or []]

    def _prefetch_kv(self, m, body, prompt) -> int:
        """Submit-time KV prefetch for a disaggregated dispatch (the
        ``kv_source`` hint): pull the prompt's prefix blocks from the
        prefill peer into the local arena ON THIS HANDLER THREAD — the
        transfer overlaps the batcher's decode loop instead of stalling
        co-resident streams at admission. Returns bytes transferred for
        the cost ledger; the request is then submitted WITHOUT the
        kv_source (no scheduler-thread fetch fallback: a dead peer must
        cost this request a recompute, not stall the decode loop on a
        connect timeout)."""
        src = body.get("kv_source")
        if not src or m.batcher is None:
            return 0
        try:
            return m.batcher.prefetch_kv(prompt, src)
        except Exception:
            return 0

    def _note_prefix(self, m, body, prompt) -> None:
        """Feed a served prompt into the prefix-digest advertisement
        (runtime/kvtier.py PrefixDigestIndex): called at batcher submit
        time — the prompt's KV is entering the radix cache — with the
        prompt TEXT, because the master routes on text-level digests (it
        never tokenizes). Token-id submissions have no text to chain and
        are simply not advertised."""
        b = m.batcher
        if (b is not None and b.kvtier is not None
                and isinstance(body.get("prompt"), str) and body["prompt"]):
            b.kvtier.note_text(body["prompt"], len(prompt))

    def _idem_claim(self, tag: str):
        """One atomic look at the idempotency state for ``tag``:
        ``("cached", result)`` — a completed result to replay;
        ``("join", event)`` — an execution is in flight, wait on it;
        ``("own", event)`` — the caller now OWNS the execution and must
        _idem_release() when done (the registered event is returned)."""
        with self._idem_lock:
            cached = self._idem.get(tag)
            if cached is not None:
                self._idem.move_to_end(tag)
                return "cached", cached
            ev = self._inflight_tags.get(tag)
            if ev is not None:
                return "join", ev
            my_ev = self._inflight_tags[tag] = threading.Event()
            return "own", my_ev

    def _idem_release(self, tag: str, my_ev: threading.Event, res):
        """End an owned execution: cache a success dict for replays
        (bounded LRU), drop the in-flight registration, and wake joiners
        — they re-check the cache under the lock."""
        with self._idem_lock:
            if isinstance(res, dict):   # 200 success: cache for replays
                self._idem[tag] = res
                self._idem.move_to_end(tag)
                while len(self._idem) > IDEM_CACHE:
                    self._idem.popitem(last=False)
            self._inflight_tags.pop(tag, None)
            my_ev.set()

    def _inference_idempotent(self, body):
        """Exactly-once execution around _inference_execute: a duplicate
        dispatch (master timeout retry — at-least-once delivery) either
        replays the cached result or joins the still-running execution
        and waits for ITS result, so the generation never runs twice for
        one request_tag."""
        tag = str(body["request_tag"]) if body.get("request_tag") else None
        if tag is None:
            return self._inference_execute(body)
        deadline = clock.now() + float(body.get("timeout", 300))
        while True:
            kind, obj = self._idem_claim(tag)
            if kind == "cached":
                self.metrics.inc("idempotent_hits")
                return dict(obj, idempotent=True)
            if kind == "own":
                my_ev = obj
                break
            # join the in-flight execution instead of re-generating
            self.metrics.inc("idempotent_joins")
            if not obj.wait(timeout=max(0.0, deadline - clock.now())):
                # in_flight tells the master the generation is STILL
                # running here — retry this node (join again later), do
                # not fail over and re-generate on a peer
                return 408, {"status": "error", "in_flight": True,
                             "message": f"execution for tag {tag!r} still "
                                        "running past the request budget"}
            # loop: either its result is cached now (replay it), or the
            # original attempt failed — then we take ownership and re-run
        res = None
        try:
            res = self._inference_execute(body)
            return res
        finally:
            self._idem_release(tag, my_ev, res if isinstance(res, dict)
                               else None)

    def _inference_execute(self, body):
        t0 = clock.now()
        try:
            m, prompt, sp, max_new, gen_kw = self._prep_inference(body)
        except (KeyError, ValueError) as e:
            return 400, {"status": "error", "message": str(e)}
        if m.batcher is not None:
            # batched serving: enqueue and wait — no per-model lock, the
            # batcher interleaves this request with others in flight
            tag = body.get("request_tag")
            resume = body.get("resume")
            resume = resume if isinstance(resume, dict) else None
            req = None
            try:
                with self.metrics.time("inference"):
                    pre = self._prefetch_kv(
                        m, body, self._resume_seq(prompt, resume))
                    req = m.batcher.submit(
                        prompt, max_new_tokens=max_new, sampling=sp,
                        eos_token_id=m.tokenizer.eos_token_id,
                        seed=body.get("seed"),
                        kv_transfer_bytes=pre,
                        kv_export=bool(body.get("kv_export")),
                        resume=resume,
                        # master brownout rung 3: per-request decode
                        # chunk ceiling on latency-class dispatches
                        chunk_cap=body.get("decode_chunk_cap"),
                        adapter=body.get("adapter"))
                    self._note_prefix(m, body, prompt)
                    if tag:
                        with self._tagged_lock:
                            self._tagged[str(tag)] = req
                    toks = req.wait(timeout=float(body.get("timeout", 300)))
            except TimeoutError as e:
                req.cancel()   # free the slot; don't generate for nobody
                return 408, {"status": "error", "message": str(e)}
            except (ValueError, RuntimeError) as e:
                if req is not None and req._migrated:
                    # live-migration handoff: 303-style — the master
                    # re-dispatches with the resume record + a
                    # kv_source hint back at this worker's arena
                    return 303, {"status": "migrated",
                                 "resume": req.resume_record,
                                 "request_tag": str(tag) if tag else None}
                return 400, {"status": "error", "message": str(e)}
            finally:
                if tag:
                    with self._tagged_lock:
                        self._tagged.pop(str(tag), None)
            self.metrics.inc("requests_completed")
            self.metrics.inc("tokens_generated", len(toks))
            return {
                "status": "success",
                "result": m.tokenizer.decode(toks),
                "tokens": toks,
                "execution_time": clock.now() - t0,
                "ttft_ms": req.ttft_ms,
                "cost": req.cost,
                "scheduler": m.batcher.stats(),
            }
        try:
            with self.metrics.time("inference"), m.lock:
                res = m.engine.generate(
                    [prompt], max_new_tokens=max_new, sampling=sp,
                    eos_token_id=m.tokenizer.eos_token_id, **gen_kw)
        except ValueError as e:   # request-shape errors (e.g. context
            # window exceeded incl. the speculative gamma margin) are the
            # caller's fault, not a server fault
            return 400, {"status": "error", "message": str(e)}
        text = m.tokenizer.decode(res.tokens[0])
        self.metrics.inc("requests_completed")
        self.metrics.inc("tokens_generated", len(res.tokens[0]))
        self.metrics.gauge("last_decode_tokens_per_s", res.decode_tokens_per_s)
        return {
            "status": "success",
            "result": text,
            "tokens": res.tokens[0],
            "execution_time": clock.now() - t0,  # parity: worker/app.py:317
            "prefill_ms": res.prefill_ms,
            "decode_ms": res.decode_ms,
            "tokens_per_s": res.decode_tokens_per_s,
            "cost": res.cost(),
        }

    def engine_stream_events(self, body, schedule):
        """Engine-mode SSE event stream. ``schedule(fn)`` runs the blocking
        generation (a daemon thread here; the lockstep leader schedules it
        at the op's sequence slot instead — runtime/multihost.py). Prep
        happens INSIDE fn so it observes whatever model state the
        scheduled order establishes (e.g. after an earlier unload)."""
        import queue
        q: "queue.Queue" = queue.Queue()
        done = object()
        ctx = trace.current()   # handler thread's span; run() is scheduled
        # onto another thread, so the link is explicit

        def run():
            try:
                with trace.get_tracer().span("worker.inference_stream",
                                             parent=ctx):
                    return self._run_stream(body, q)
            except Exception as e:
                q.put({"event": "error", "message": str(e)})
            finally:
                q.put(done)

        schedule(run)

        def events():
            while True:
                item = q.get()
                if item is done:
                    break
                yield item
            self.metrics.inc("requests_completed")

        return events()

    def _run_stream(self, body, q):
        m, prompt, sp, max_new, gen_kw = self._prep_inference(body)
        if m.batcher is not None:
            raise ValueError(
                "engine_stream_events is for engine-mode models")

        def cb(step, toks):
            if toks[0] is None:  # sequence finished (post-eos)
                return
            q.put({"event": "token", "step": step, "token": toks[0],
                   "text": m.tokenizer.decode([toks[0]])})

        with m.lock:
            res = m.engine.generate(
                [prompt], max_new_tokens=max_new, sampling=sp,
                eos_token_id=m.tokenizer.eos_token_id,
                stream_cb=cb, **gen_kw)
        q.put({"event": "done",
               "result": m.tokenizer.decode(res.tokens[0]),
               "tokens_per_s": res.decode_tokens_per_s})

    def inference_stream(self, body, _request=None):
        """SSE streaming decode — absent from the reference (SURVEY.md §2.3)."""
        stale = self._term_guard(_request)
        if stale:
            return stale
        if not self._try_begin_inference():
            return self._refuse_draining()
        try:
            return self._inference_stream_inner(body, _request)
        finally:
            self._end_inference()

    def _inference_stream_inner(self, body, _request=None):
        try:
            # validate up front so bad requests get a proper 400, matching
            # /inference; execution still re-preps inside the stream thread
            # (the lockstep leader relies on in-slot prep)
            m, _, _, _, _ = self._prep_inference(body)
        except (KeyError, ValueError) as e:
            return 400, {"status": "error", "message": str(e)}
        if m.batcher is None:
            ev = self.engine_stream_events(
                body, lambda fn: threading.Thread(target=fn,
                                                  daemon=True).start())
            return httpd.sse_stream(_request, ev)
        ctx = trace.current()   # submit happens on a helper thread below

        def events():
            import queue
            q: "queue.Queue" = queue.Queue()
            done = object()

            def run_batched():
                step = [0]

                def cb(token):
                    q.put({"event": "token", "step": step[0], "token": token,
                           "text": m.tokenizer.decode([token])})
                    step[0] += 1

                try:
                    _, prompt, sp, max_new, _gk = self._prep_inference(body)
                    pre = self._prefetch_kv(m, body, prompt)
                    req = m.batcher.submit(
                        prompt, max_new_tokens=max_new, sampling=sp,
                        eos_token_id=m.tokenizer.eos_token_id, stream_cb=cb,
                        seed=body.get("seed"),
                        kv_transfer_bytes=pre, trace_ctx=ctx,
                        adapter=body.get("adapter"))
                    self._note_prefix(m, body, prompt)
                    toks = req.wait(timeout=float(body.get("timeout", 300)))
                    q.put({"event": "done",
                           "result": m.tokenizer.decode(toks),
                           "ttft_ms": req.ttft_ms})
                except Exception as e:
                    q.put({"event": "error", "message": str(e)})
                q.put(done)

            threading.Thread(target=run_batched, daemon=True).start()
            while True:
                item = q.get()
                if item is done:
                    break
                yield item
            self.metrics.inc("requests_completed")

        return httpd.sse_stream(_request, events())

    def cancel(self, body, _request=None):
        """Cancel an in-flight tagged batched request, freeing its slot.

        The reference had no cancellation at all — a master-side timeout
        left the worker generating for nobody (SURVEY.md §2.3 one blocking
        request; the master's 120s timeout vs the worker's open-ended
        generate). Engine-mode requests are not cancellable mid-program
        (one jitted chunk runs to completion); the batcher drops the slot
        at its next step.

        Lease-fenced: a revived old leader's timeout path must not
        cancel a generation the CURRENT leader is waiting on — without
        the fence, its orphan-cancel would kill the live stream.
        """
        stale = self._term_guard(_request)
        if stale:
            return stale
        tag = body.get("request_tag")
        if not tag:
            return 400, {"status": "error", "message": "request_tag required"}
        with self._tagged_lock:
            req = self._tagged.get(str(tag))
        if req is None:
            return 404, {"status": "error",
                         "message": f"no in-flight request tagged {tag!r}"}
        req.cancel()
        self.metrics.inc("requests_cancelled")
        return {"status": "success",
                "message": f"cancel requested for {tag!r}"}

    # ---- profiling ----------------------------------------------------
    # The reference's only timing was wall-clock execution_time per request
    # (reference: worker/app.py:271,317; SURVEY.md §5.1). These endpoints
    # expose real device traces: XLA op timelines viewable in
    # TensorBoard/Perfetto, plus a live HBM profile.

    def profile_start(self, body):
        path = body.get("trace_dir") or "/tmp/dli_trace"
        import jax.profiler
        with self._profile_lock:   # check-then-act vs concurrent handlers
            if self._profile_dir is not None:
                return 409, {"status": "error",
                             "message": f"trace already running -> "
                                        f"{self._profile_dir}"}
            jax.profiler.start_trace(path)
            self._profile_dir = path
        return {"status": "success", "trace_dir": path}

    def profile_stop(self, body):
        import jax.profiler
        with self._profile_lock:
            if self._profile_dir is None:
                return 409, {"status": "error", "message": "no trace running"}
            jax.profiler.stop_trace()
            path, self._profile_dir = self._profile_dir, None
        return {"status": "success", "trace_dir": path,
                "message": "open with tensorboard --logdir or xprof"}

    def memory_profile(self, body):
        """Live device-memory profile (pprof protobuf), HBM ground truth."""
        import jax.profiler
        return (jax.profiler.device_memory_profile(), "application/protobuf")

    def ssh_setup(self, body):
        """Reference parity (worker/app.py:374-413): probe an SSH
        connection with the given credentials, then close it. Like the
        reference this is a connectivity TEST only — no tunnel is kept.
        Unlike the reference (which imported paramiko unconditionally but
        never declared it, SURVEY.md §5.9) the dependency is optional, and
        unlike the reference the endpoint demands worker auth: an open
        /ssh_setup is an SSRF/port-scan primitive and can be pointed at
        the operator's own key files."""
        if self.service.auth_key is None:
            return 403, {"status": "error",
                         "message": "/ssh_setup requires worker auth "
                                    "(set DLI_AUTH_ENABLED + DLI_AUTH_KEY)"}
        try:
            import paramiko
        except ImportError:
            return 501, {"status": "error",
                         "message": "paramiko not installed on this worker"}
        host = body.get("host")
        username = body.get("username")
        if not host or not username:
            return 400, {"status": "error",
                         "message": "host and username required"}
        client = paramiko.SSHClient()
        client.set_missing_host_key_policy(paramiko.AutoAddPolicy())
        try:
            kw = {"hostname": host, "port": int(body.get("port", 22)),
                  "username": username, "timeout": 10}
            if body.get("key_path"):
                kw["key_filename"] = body["key_path"]
            elif body.get("password"):
                kw["password"] = body["password"]
            else:
                return 400, {"status": "error",
                             "message": "password or key_path required"}
            client.connect(**kw)
            return {"status": "success",
                    "message": f"SSH connection to {host} verified"}
        except Exception as e:
            return 502, {"status": "error", "message": f"SSH failed: {e}"}
        finally:
            client.close()

    # ---- lifecycle ---------------------------------------------------

    def serve(self, host="0.0.0.0", port=8100, background=False):
        log.info("worker agent on %s:%d (devices: %s)", host, port,
                 jax.devices())
        return self.service.serve(host, port, background=background)


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(description="TPU worker agent")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8100)
    args = ap.parse_args(argv)
    WorkerAgent().serve(args.host, args.port)


if __name__ == "__main__":
    main()
