"""Cluster prefix-cache tier: host-RAM KV offload + prefix-digest routing.

The worker-local radix cache (native/src/block_pool.cc) was the last cache
tier in the system: a block evicted under pool pressure lost its KV and
the prompt re-prefilled from scratch, and the master's queue-aware
scheduler was prefix-blind — two requests sharing a long system prompt
could land on different workers and each pay full prefill. Following
FlowKV (PAPERS.md, arxiv 2504.03775), the KV cache becomes a
*cluster-level, load-aware* resource with three pieces:

1. **Host-RAM offload arena** (:class:`HostKVArena`): when the radix
   cache evicts a block, the batcher copies its still-resident device KV
   pages into a bounded LRU arena keyed by the block's *token-chain
   digest* (content addressing — the same prompt prefix hashes to the
   same key on any worker). On a later radix miss, admission consults the
   arena and restores matching blocks to device with one scatter
   (``write_block_run`` semantics) instead of re-running prefill. The
   restored bytes are the exact evicted bytes, so outputs are bitwise
   identical to a cold prefill. Bounded by ``DLI_KV_HOST_MB`` (0
   disables the tier).

2. **Prefix-digest advertisement** (:class:`PrefixDigestIndex`): workers
   summarize which prompt prefixes they have served — leading-chunk hash
   chains over the prompt *text*, bounded top-K — in ``batcher.stats()``,
   riding the master's existing health-scrape loop into its per-node
   runtime snapshot. Text-level chaining (not token-level) because the
   master never tokenizes: both sides hash the same UTF-8 byte chunks.

3. **Affinity-aware routing** (runtime/master.py ``_score_pick``): the
   master chains the incoming prompt the same way and scores estimated
   cached-prefix tokens per candidate node — but affinity only wins
   below a load threshold (FlowKV's load-aware rule), so a hot node
   never becomes a convoy, and stale digests (node silent past
   ``SCHED_STALE_S``) drop out exactly like stale queue depths.

The cache hierarchy is now: device radix blocks -> host arena ->
recompute, with routing trying to keep requests where tier 1 already is.
"""

from __future__ import annotations

import hashlib
import os
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from distributed_llm_inferencing_tpu.ops import kvblock_quant as kvq
from distributed_llm_inferencing_tpu.utils import locks

# Host arena budget (MB). 0 disables the offload tier entirely.
DEFAULT_HOST_MB = 256.0
# Arena storage dtype: "native" keeps the exact device bytes (bitwise
# restore guarantee); "int8" stores blocks per-(layer, head) quantized
# (ops/kvblock_quant.py) — ~3.9x more prefix tokens per MB, restores
# are dequantized (lossy) approximations of the evicted KV.
HOST_DTYPES = ("native", "int8")
# Prompt-text chunk size (bytes of the UTF-8 encoding) for prefix-digest
# chains. Master and workers must agree — both read this env.
DIGEST_CHUNK = max(1, int(os.environ.get("DLI_PREFIX_DIGEST_CHUNK", 256)))
# How many distinct prefix chains a worker advertises (bounded top-K by
# recency) and how deep one chain may go (64 chunks x 256 B covers a
# ~16 kB system prompt).
DIGEST_TOP_K = max(1, int(os.environ.get("DLI_PREFIX_DIGEST_TOP_K", 32)))
DIGEST_MAX_CHUNKS = 64

_DIGEST_SIZE = 8   # bytes; 16 hex chars per advertised digest


def _chain(parts) -> List[str]:
    """Hash-chain ``parts`` (byte strings): digest_i covers parts[0..i].
    A chain digest identifies an exact *prefix*, so two prompts sharing
    their first N parts share their first N digests — the property both
    the arena keys and the routing advertisement rely on."""
    out = []
    prev = b""
    for part in parts:
        prev = hashlib.blake2b(prev + part,
                               digest_size=_DIGEST_SIZE).digest()
        out.append(prev.hex())
    return out


def token_chain_digests(tokens: Sequence[int], block_size: int) -> List[str]:
    """One chain digest per FULL block of ``tokens`` — digest i keys the
    KV content of block i given everything before it. Must match for the
    offload (evicted chain) and restore (admission prompt) sides, which
    both call this."""
    arr = np.asarray(list(tokens), dtype=np.int32)
    n_full = len(arr) // block_size
    return _chain(arr[i * block_size:(i + 1) * block_size].tobytes()
                  for i in range(n_full))


def text_chain_digests(text: str, chunk: int = DIGEST_CHUNK,
                       max_chunks: int = DIGEST_MAX_CHUNKS) -> List[str]:
    """Chain digests over the prompt *text* (UTF-8 bytes, ``chunk``-byte
    pieces, full chunks only). The routing-side twin of
    ``token_chain_digests``: the master has no tokenizer, so workers
    advertise — and the master matches — at the text level."""
    data = text.encode("utf-8", errors="replace")
    n_full = min(len(data) // chunk, max_chunks)
    return _chain(data[i * chunk:(i + 1) * chunk] for i in range(n_full))


class HostKVArena:
    """Bounded, LRU-managed host-RAM store of evicted KV blocks.

    Entries are keyed by token-chain digest and hold the block's pages —
    one numpy array per paged-cache leaf (k, v, and the int8 scales when
    quantized), exactly the bytes that were on device. ``get`` touches
    LRU order; inserting past the byte budget drops the LRU entry.
    Thread-safe: the batcher thread offloads/restores while HTTP handler
    threads read ``stats()``.

    ``dtype="int8"`` stores each inserted block as a quantized record
    (ops/kvblock_quant.py) instead of the raw pages: ~3.9x more blocks
    in the same budget, at the cost of the bitwise-restore guarantee
    for arena-served blocks. Whatever the mode, entries are
    self-describing — an already-quantized record (fetched from an int8
    peer) is stored as-is, never requantized — and ``_bytes`` /
    ``occupancy`` count STORED bytes, so the arena-full routing guard
    (DLI_SCHED_ARENA_FULL) sees the honest budget either way.
    """

    def __init__(self, capacity_bytes: int, dtype: str = "native"):
        if dtype not in HOST_DTYPES:
            raise ValueError(
                f"unknown arena dtype {dtype!r}; known: {HOST_DTYPES}")
        self.capacity_bytes = int(capacity_bytes)
        self.dtype = dtype
        self._lock = locks.lock("kvtier.arena")
        self._entries: "OrderedDict[str, Tuple[tuple, int]]" = OrderedDict()
        self._bytes = 0
        self._logical_bytes = 0
        self.hits = 0
        self.misses = 0
        self.offloaded = 0
        self.restored = 0
        self.dropped = 0      # LRU evictions out of the arena

    def __contains__(self, digest: str) -> bool:
        with self._lock:
            return digest in self._entries

    def put(self, digest: str, pages: Sequence[np.ndarray],
            count_offload: bool = True) -> bool:
        """Insert one block's pages; returns False when the block alone
        exceeds the whole budget (never stored). ``count_offload=False``
        keeps inserts from the transfer paths (peer fetch, finish-time
        export) out of the ``offloaded`` counter — that stat means
        device-eviction offloads, and the TSDB series charting it must
        not spike when a decode node merely pulls blocks over the
        wire. ``pages`` may be raw device pages OR an already-quantized
        block record (a peer fetch from an int8 node) — records store
        as-is; raw pages quantize first when this arena is int8."""
        if kvq.is_quantized_block(pages):
            obj = pages
            stored = kvq.stored_nbytes(obj)
            logical = kvq.logical_nbytes(obj)
        elif self.dtype == "int8":
            obj = kvq.quantize_block(pages)
            stored = kvq.stored_nbytes(obj)
            logical = kvq.logical_nbytes(obj)
        else:
            obj = tuple(np.ascontiguousarray(p) for p in pages)
            stored = logical = sum(p.nbytes for p in obj)
        if stored > self.capacity_bytes:
            return False
        with self._lock:
            old = self._entries.pop(digest, None)
            if old is not None:
                self._bytes -= old[1]
                self._logical_bytes -= old[2]
            self._entries[digest] = (obj, stored, logical)
            self._bytes += stored
            self._logical_bytes += logical
            if count_offload:
                self.offloaded += 1
            while self._bytes > self.capacity_bytes and self._entries:
                _, (_, freed, lfreed) = self._entries.popitem(last=False)
                self._bytes -= freed
                self._logical_bytes -= lfreed
                self.dropped += 1
        return True

    def get(self, digest: str) -> Optional[tuple]:
        """Pages for ``digest`` (LRU-touched), or None. The entry STAYS
        in the arena: a restored block may be radix-evicted again later,
        and re-offloading identical content would be wasted copies.
        Quantized entries dequantize here — the caller always sees
        scatter-ready logical pages."""
        with self._lock:
            hit = self._entries.get(digest)
            if hit is None:
                self.misses += 1
                return None
            self._entries.move_to_end(digest)
            self.hits += 1
            self.restored += 1
            obj = hit[0]
        if kvq.is_quantized_block(obj):
            return kvq.dequantize_block(obj)
        return obj

    def peek(self, digest: str) -> bool:
        """Membership without touching hit/miss accounting (used to size
        a consecutive restore run before committing to block allocs)."""
        with self._lock:
            return digest in self._entries

    def peek_pages(self, digest: str) -> Optional[tuple]:
        """Pages for ``digest`` WITHOUT the hit/miss/restored accounting
        ``get`` does — the ``/kv_fetch`` export path reads blocks on a
        peer's behalf, and counting that as a local restore would make
        the arena's own tiering stats lie. LRU order is still touched:
        a block peers keep pulling is a block worth keeping resident."""
        obj = self.peek_stored(digest)
        if obj is None:
            return None
        if kvq.is_quantized_block(obj):
            return kvq.dequantize_block(obj)
        return obj

    def peek_stored(self, digest: str):
        """The STORED object for ``digest`` — raw page tuple or
        quantized record — without hit/miss accounting. The /kv_fetch
        export path ships this representation as-is: a quantized block
        crosses the wire quantized (no requantize, no dequantize on
        send), so the sender's CPU cost is a memcpy either way."""
        with self._lock:
            hit = self._entries.get(digest)
            if hit is None:
                return None
            self._entries.move_to_end(digest)
            return hit[0]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {"blocks": len(self._entries), "bytes": self._bytes,
                    "capacity_bytes": self.capacity_bytes,
                    # occupancy fraction rides /health into the master's
                    # runtime snapshot: the scheduler keeps prefill off
                    # nodes whose arena would evict what a decode peer
                    # is about to fetch (DLI_SCHED_ARENA_FULL). Counts
                    # STORED (possibly quantized) bytes — the honest
                    # budget fraction; logical_bytes carries the
                    # full-precision equivalent so the compression
                    # ratio is derivable fleet-wide.
                    "occupancy": self._bytes / max(1, self.capacity_bytes),
                    "dtype": self.dtype,
                    "logical_bytes": self._logical_bytes,
                    "hits": self.hits, "misses": self.misses,
                    "offloaded": self.offloaded, "restored": self.restored,
                    "dropped": self.dropped}


class PrefixDigestIndex:
    """Worker-side advertisement of served prompt prefixes.

    ``note(text, n_tokens)`` records the prompt's leading-chunk chain
    digests, each mapped to the estimated number of prompt tokens the
    prefix up to that chunk covers (tokens scaled by byte fraction — an
    estimate is enough: routing needs relative magnitudes, and the
    worker-side radix cache is the ground truth once the request lands).
    Chains are tracked whole, keyed by their deepest digest, bounded to
    the ``top_k`` most recent — one shared-prefix *family* costs one
    chain, not one entry per request, and a shorter chain that is a
    prefix of a newly noted one merges into it. ``advertise()`` emits
    each chain at geometric depths (1, 2, 4, ... and the deepest), so a
    64-chunk system prompt costs ~7 advertised digests instead of 64: a
    prompt sharing D chunks still matches the largest advertised depth
    <= D, with a conservative (shallower) token estimate.
    """

    def __init__(self, chunk: int = DIGEST_CHUNK,
                 top_k: int = DIGEST_TOP_K):
        self.chunk = int(chunk)
        self.top_k = int(top_k)
        self._lock = locks.lock("kvtier.digests")
        # chain key (deepest digest) -> [(digest, est_tokens), ...]
        self._chains: "OrderedDict[str, list]" = OrderedDict()

    def note(self, text: str, n_tokens: int) -> None:
        if not text or n_tokens <= 0:
            return
        digs = text_chain_digests(text, self.chunk)
        if not digs:
            return
        n_bytes = len(text.encode("utf-8", errors="replace"))
        ests = [max(1, round(n_tokens * min(
            1.0, (i + 1) * self.chunk / max(1, n_bytes))))
            for i in range(len(digs))]
        key = digs[-1]
        with self._lock:
            # an existing chain that is a PREFIX of this one (same
            # family, shorter prompt) merges: its key is among our
            # shallower digests
            mine = set(digs[:-1])
            for k in [k for k in self._chains if k in mine]:
                del self._chains[k]
            old = self._chains.pop(key, None)
            if old is not None:      # same key == identical chain
                ests = [max(e, oe) for e, (_, oe) in zip(ests, old)]
            self._chains[key] = list(zip(digs, ests))
            while len(self._chains) > self.top_k:
                self._chains.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._chains)

    def advertise(self) -> dict:
        """Bounded summary for ``stats()``: the ``top_k`` most recent
        chains, each downsampled to geometric depths plus the deepest,
        with their token estimates and the chunk size the master must
        chain with."""
        with self._lock:
            chains = list(self._chains.values())
        out: Dict[str, int] = {}
        for chain in chains:
            n = len(chain)
            depths = {n - 1}
            d = 1
            while d < n:
                depths.add(d - 1)
                d *= 2
            for i in depths:
                dig, est = chain[i]
                out[dig] = max(out.get(dig, 0), est)
        return {"chunk": self.chunk,
                "top": [[d, int(v)] for d, v in out.items()]}


def estimate_cached_tokens(prompt: str, advert: Optional[dict],
                           memo: Optional[Dict[int, List[str]]] = None
                           ) -> int:
    """Master-side affinity input: estimated tokens of ``prompt`` whose
    KV a node advertising ``advert`` already holds — the deepest prompt
    chain digest present in the advertisement. ``memo`` caches the
    prompt's digest chains per chunk size across candidate nodes in one
    scheduling pick."""
    if not prompt or not isinstance(advert, dict):
        return 0
    top = advert.get("top")
    chunk = advert.get("chunk")
    if not top or not isinstance(chunk, int) or chunk < 1:
        return 0
    # the advertisement crossed the wire from a worker: malformed shapes
    # must score 0, never raise — this runs inside _pick_node on the
    # master's dispatcher threads, which have no exception net
    try:
        have = {str(d): int(v) for d, v in top}
        chunk = int(chunk)
    except (TypeError, ValueError):
        return 0
    if memo is not None and chunk in memo:
        digs = memo[chunk]
    else:
        digs = text_chain_digests(prompt, chunk)
        if memo is not None:
            memo[chunk] = digs
    for d in reversed(digs):          # deepest match wins
        est = have.get(d)
        if est is not None:
            return est
    return 0


class KVTier:
    """Per-batcher facade tying the arena and the digest index together
    (runtime/batcher.py owns the device side: page gather on offload,
    scatter on restore)."""

    def __init__(self, block_size: int, capacity_mb: float,
                 digest_chunk: int = DIGEST_CHUNK,
                 digest_top_k: int = DIGEST_TOP_K,
                 dtype: str = "native"):
        self.block_size = int(block_size)
        self.arena = HostKVArena(int(capacity_mb * 1024 * 1024),
                                 dtype=dtype)
        self.index = PrefixDigestIndex(digest_chunk, digest_top_k)

    def block_digests(self, tokens: Sequence[int]) -> List[str]:
        return token_chain_digests(tokens, self.block_size)

    def note_text(self, text: str, n_tokens: int) -> None:
        self.index.note(text, n_tokens)

    def stats(self) -> dict:
        s = self.arena.stats()
        # chain count, NOT len(advertise()["top"]): stats() rides every
        # /health scrape and inference response — don't rebuild the
        # advertisement just to count it
        s["chains_advertised"] = len(self.index)
        return s
