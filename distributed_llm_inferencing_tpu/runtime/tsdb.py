"""Master-side time-series store + SLO/goodput evaluator.

``/api/cluster_metrics`` (PR 1) scrapes each worker's exposition on
demand and throws the sample away — there is no history to answer "did
tok/s degrade after the last deploy", no per-node throughput profile
for an auto-parallelism planner to consume, and no rolling SLO signal
to drive load shedding. This module is the retention layer behind the
master's background scrape loop:

- :class:`TSDB` — a bounded in-memory store of per-(node, metric)
  series. Each series is a pair of fixed-interval ring buffers: a
  *fine* ring at ``DLI_TSDB_STEP_S`` covering the recent past and a
  *coarse* ring downsampled 8x covering the full ``DLI_TSDB_WINDOW_S``
  window, so memory is O(buckets), not O(samples), and a 1h query
  doesn't return 720 points per node. Counters are converted to
  per-second *rates* at ingest (a cumulative value would make every
  chart a ramp); a counter reset (worker restart) is detected by the
  value dropping and re-baselines instead of emitting a negative spike.
  Buckets with no sample stay absent — staleness renders as a gap, not
  a frozen line.

- :class:`SLOEvaluator` — declarative latency SLOs
  (``DLI_SLO_TTFT_MS``, ``DLI_SLO_ITL_P95_MS``) evaluated per completed
  request from its cost record (runtime/batcher.py cost ledger),
  aggregated into rolling attainment over a fast and a slow window plus
  the multi-window error-budget *burn rate* that alerting and
  (ROADMAP item 4) load shedding key off.

Everything here is stdlib + lock-guarded; the master owns one TSDB and
feeds it from ``_telemetry_loop`` (pooled keep-alive scrapes through
``_scrape_workers`` + the tolerant ``utils.metrics.parse_prometheus``).
"""

from __future__ import annotations

import collections
import math
import os
from typing import Dict, List, Optional, Tuple

from distributed_llm_inferencing_tpu.utils import clock, locks

# Retention knobs: total window retained per series, and the fine-ring
# bucket width. The fine ring is capped at FINE_BUCKETS_MAX buckets;
# history past that is served from the 8x-downsampled coarse ring.
DEFAULT_WINDOW_S = 3600.0
DEFAULT_STEP_S = 5.0
DOWNSAMPLE_X = 8
FINE_BUCKETS_MAX = 512
# per-node series cap: metric names ultimately come from process
# registries (bounded), but a buggy/hostile worker must not grow the
# master's memory without bound
MAX_SERIES_PER_NODE = int(os.environ.get("DLI_TSDB_MAX_SERIES", 512))


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def slo_targets() -> dict:
    """Declarative SLO targets, read per call so tests/benches can flip
    the env. ``availability`` is the attainment objective the burn rate
    is computed against (burn 1.0 = exactly consuming the error budget;
    >1 = on track to miss the SLO)."""
    return {
        "ttft_ms": _env_float("DLI_SLO_TTFT_MS", 2000.0),
        "itl_p95_ms": _env_float("DLI_SLO_ITL_P95_MS", 250.0),
        "availability": min(0.9999, max(0.5, _env_float(
            "DLI_SLO_TARGET", 0.99))),
    }


def cost_within_slo(cost: Optional[dict], targets: dict) -> Optional[bool]:
    """Evaluate one request's cost record against the targets. None when
    there is nothing to evaluate (no/garbled record). TTFT is
    queue + prefill (the cost ledger's phases sum to the e2e span);
    the ITL target applies to the request's own p95 inter-token gap."""
    if not isinstance(cost, dict):
        return None
    if cost.get("queue_ms") is None and cost.get("prefill_ms") is None:
        return None   # schema drift must read as unevaluable, not as a
        # TTFT of 0 that silently inflates attainment
    try:
        ttft = float(cost.get("queue_ms") or 0.0) \
            + float(cost.get("prefill_ms") or 0.0)
    except (TypeError, ValueError):
        return None
    ok = ttft <= targets["ttft_ms"]
    itl = cost.get("itl_p95_ms")
    if itl is not None:
        try:
            ok = ok and float(itl) <= targets["itl_p95_ms"]
        except (TypeError, ValueError):
            pass
    return ok


class Series:
    """One (node, metric) series: fine + downsampled coarse rings of
    (bucket_epoch, value). Counters store per-second rates."""

    __slots__ = ("kind", "step", "coarse_step", "fine", "coarse",
                 "_prev_raw", "_prev_t", "_acc")

    def __init__(self, kind: str, step: float, window: float):
        self.kind = kind            # "gauge" | "counter" (stored as rate)
        self.step = step
        fine_n = max(2, min(FINE_BUCKETS_MAX, int(math.ceil(window / step))))
        self.fine: collections.deque = collections.deque(maxlen=fine_n)
        self.coarse_step = step * DOWNSAMPLE_X
        coarse_n = max(2, int(math.ceil(window / self.coarse_step)))
        self.coarse: collections.deque = collections.deque(maxlen=coarse_n)
        self._prev_raw: Optional[float] = None   # counter-rate state
        self._prev_t: Optional[float] = None
        self._acc: Optional[list] = None         # [coarse_bucket, sum, n]

    def record(self, value: float, t: float):
        v = float(value)
        if not math.isfinite(v):
            return   # a NaN/Inf sample must not poison the ring
        if self.kind == "counter":
            prev, pt = self._prev_raw, self._prev_t
            self._prev_raw, self._prev_t = v, t
            if prev is None or pt is None or t <= pt:
                return             # first sight: no interval to rate over
            delta = v - prev
            if delta < 0:
                # counter reset (worker restart): the new cumulative IS
                # the growth since the restart — monotone rate, no
                # negative spike
                delta = v
            v = delta / (t - pt)
        bt = t - (t % self.step)
        if self.fine and self.fine[-1][0] == bt:
            self.fine[-1] = (bt, v)      # same bucket: freshest wins
        else:
            self.fine.append((bt, v))
        # downsample into the coarse ring: mean of the fine samples that
        # landed in each coarse bucket, flushed when the bucket rolls
        cb = t - (t % self.coarse_step)
        if self._acc is None or self._acc[0] != cb:
            if self._acc is not None and self._acc[2]:
                self.coarse.append((self._acc[0],
                                    self._acc[1] / self._acc[2]))
            self._acc = [cb, 0.0, 0]
        self._acc[1] += v
        self._acc[2] += 1

    def points(self, window: float, now: float) -> List[Tuple[float, float]]:
        """Samples within ``window`` of ``now``: coarse history up to
        where the fine ring begins, then the fine ring."""
        cutoff = now - window
        fine = [(t, v) for t, v in self.fine if t >= cutoff]
        fine_t0 = fine[0][0] if fine else now
        out = [(t, v) for t, v in self.coarse
               if cutoff <= t < fine_t0]
        if (self._acc is not None and self._acc[2]
                and cutoff <= self._acc[0] < fine_t0):
            out.append((self._acc[0], self._acc[1] / self._acc[2]))
        return out + fine

    def dump(self) -> dict:
        """JSON-serializable snapshot of the COMPLETE series state:
        both rings plus the counter-rate baseline and the in-progress
        coarse accumulator, so a restored series serves byte-identical
        points AND keeps rating the counter from the pre-restart
        baseline (no restart spike, no re-baselining gap)."""
        return {"kind": self.kind,
                "fine": [[t, v] for t, v in self.fine],
                "coarse": [[t, v] for t, v in self.coarse],
                "prev_raw": self._prev_raw, "prev_t": self._prev_t,
                "acc": list(self._acc) if self._acc is not None else None}

    def load(self, data: dict) -> None:
        """Inverse of :meth:`dump` (ring capacities stay this series's
        own — a snapshot from a larger ring keeps its newest points)."""
        self.fine.clear()
        self.fine.extend((float(t), float(v))
                         for t, v in data.get("fine") or [])
        self.coarse.clear()
        self.coarse.extend((float(t), float(v))
                           for t, v in data.get("coarse") or [])
        self._prev_raw = data.get("prev_raw")
        self._prev_t = data.get("prev_t")
        acc = data.get("acc")
        self._acc = list(acc) if acc else None


class TSDB:
    """Bounded per-(node, metric) time-series store."""

    def __init__(self, window_s: Optional[float] = None,
                 step_s: Optional[float] = None,
                 max_series_per_node: int = MAX_SERIES_PER_NODE):
        self.window_s = float(window_s if window_s is not None
                              else _env_float("DLI_TSDB_WINDOW_S",
                                              DEFAULT_WINDOW_S))
        self.step_s = float(step_s if step_s is not None
                            else _env_float("DLI_TSDB_STEP_S",
                                            DEFAULT_STEP_S))
        self.step_s = max(0.1, self.step_s)
        self.window_s = max(self.step_s * 4, self.window_s)
        self._max_series = max(1, int(max_series_per_node))
        self._lock = locks.lock("tsdb.series")
        self._series: Dict[str, Dict[str, Series]] = {}   # node -> metric

    def record(self, node: str, metric: str, value,
               kind: str = "gauge", t: Optional[float] = None):
        t = clock.now() if t is None else t
        with self._lock:
            per_node = self._series.setdefault(str(node), {})
            s = per_node.get(metric)
            if s is None:
                if len(per_node) >= self._max_series:
                    return           # cap: drop new names, keep old series
                s = per_node[metric] = Series(kind, self.step_s,
                                              self.window_s)
            s.record(value, t)

    def ingest_prometheus(self, node: str, samples,
                          t: Optional[float] = None):
        """Feed one scrape's parsed exposition samples
        ((name, labels, value) tuples from ``parse_prometheus``).
        Histogram components are skipped (their cardinality belongs to
        the scrape-time aggregation, not the retention layer); counters
        (``_total``) are ingested for rate conversion, everything else
        as a gauge. The ``dli_`` prefix is stripped so series names
        match the in-process registry names."""
        t = clock.now() if t is None else t
        for name, labels, value in samples:
            if labels or name.endswith(("_bucket", "_sum", "_count")):
                continue
            if name.startswith("dli_"):
                name = name[4:]
            if name.endswith("_total"):
                self.record(node, name[:-6], value, kind="counter", t=t)
            else:
                self.record(node, name, value, kind="gauge", t=t)

    def query(self, metric: str, node: Optional[str] = None,
              window: Optional[float] = None,
              now: Optional[float] = None) -> List[dict]:
        """All nodes' series for ``metric`` (optionally one node), each
        as ``{"node", "metric", "kind", "points": [[t, v], ...]}``.
        Counter series return per-second rates."""
        now = clock.now() if now is None else now
        window = min(self.window_s,
                     window if window else self.window_s)
        out = []
        with self._lock:
            # points() iterates the ring deques, and record() appends to
            # them from the scrape loop — reading under the same lock
            # keeps a dashboard query landing mid-sweep from a "deque
            # mutated during iteration" 500
            for n, d in self._series.items():
                if node is not None and n != str(node):
                    continue
                s = d.get(metric)
                if s is None:
                    continue
                pts = s.points(window, now)
                if pts:
                    out.append({"node": n, "metric": metric,
                                "kind": s.kind,
                                "points": [[round(t, 3), v]
                                           for t, v in pts]})
        return out

    def catalog(self) -> Dict[str, List[str]]:
        with self._lock:
            return {n: sorted(d.keys()) for n, d in self._series.items()}

    def series_count(self) -> int:
        with self._lock:
            return sum(len(d) for d in self._series.values())

    def drop_node(self, node: str):
        with self._lock:
            self._series.pop(str(node), None)

    # ---- durability: snapshot/restore (flight recorder, PR 13) -------

    def dump(self) -> dict:
        """JSON-serializable snapshot of every retained series (fine +
        coarse rings, counter baselines). The master persists this into
        the store's ``meta`` table on a ``DLI_TSDB_SNAPSHOT_S`` cadence
        and restores at startup, so per-node tok/s and prefill-EWMA
        history span restarts — the measured history the ROADMAP item-2
        planner trains on.

        Lock granularity: materializing every ring at once can be tens
        of thousands of points on a fleet near the series caps — held
        under the global lock, that stalls every concurrent record()
        (scrape sweep) and query() (dashboard) for the whole walk. So
        the structure is snapshotted in one brief hold, then each
        series copies under its own short hold; a series mutating
        between holds just contributes its freshest state, which is
        exactly what a periodic snapshot means."""
        with self._lock:
            refs = [(node, metric, s)
                    for node, d in self._series.items()
                    for metric, s in d.items()]
        nodes: Dict[str, dict] = {}
        for node, metric, s in refs:
            with self._lock:
                nodes.setdefault(node, {})[metric] = s.dump()
        return {"v": 1, "step_s": self.step_s, "window_s": self.window_s,
                "nodes": nodes}

    def restore(self, data: dict) -> int:
        """Load a :meth:`dump` snapshot; returns the number of series
        restored. A snapshot taken at a DIFFERENT step width is refused
        whole (its bucket epochs would misalign with every new sample —
        a gap is honest, interpolated history is not). Restored series
        are replaced, not merged; nodes beyond the per-node cap drop
        the excess exactly like live ingest does."""
        if not isinstance(data, dict) or data.get("v") != 1:
            return 0
        if abs(float(data.get("step_s", -1)) - self.step_s) > 1e-9:
            return 0
        restored = 0
        with self._lock:
            for node, metrics in (data.get("nodes") or {}).items():
                per_node = self._series.setdefault(str(node), {})
                for metric, sd in metrics.items():
                    s = per_node.get(metric)
                    if s is None:
                        if len(per_node) >= self._max_series:
                            continue
                        s = per_node[metric] = Series(
                            str(sd.get("kind") or "gauge"), self.step_s,
                            self.window_s)
                    s.load(sd)
                    restored += 1
        return restored


class SLOEvaluator:
    """Rolling SLO attainment + multi-window burn rate over per-request
    outcomes. ``record(ok)`` per terminal request (a failed request is
    an SLO miss); attainment is the within-SLO fraction over a window;
    burn rate is (1 - attainment) / (1 - availability_target), reported
    for the fast window (paging signal) with the slow window as the
    confirmation (classic multi-window burn alerting)."""

    def __init__(self, targets: Optional[dict] = None,
                 fast_window_s: float = 300.0,
                 slow_window_s: float = 3600.0, maxlen: int = 16384):
        self.targets = dict(targets or slo_targets())
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self._events: collections.deque = collections.deque(maxlen=maxlen)
        self._lock = locks.lock("tsdb.slo")
        self.total = 0
        self.violations = 0

    def record(self, ok: bool, t: Optional[float] = None):
        t = clock.now() if t is None else t
        with self._lock:
            self._events.append((t, bool(ok)))
            self.total += 1
            if not ok:
                self.violations += 1

    def attainment(self, window_s: float,
                   now: Optional[float] = None) -> Optional[float]:
        now = clock.now() if now is None else now
        cutoff = now - window_s
        with self._lock:
            evs = [ok for t, ok in self._events if t >= cutoff]
        if not evs:
            return None
        return sum(evs) / len(evs)

    def burn_rate(self, window_s: float,
                  now: Optional[float] = None) -> Optional[float]:
        att = self.attainment(window_s, now)
        if att is None:
            return None
        budget = 1.0 - self.targets["availability"]
        return (1.0 - att) / max(budget, 1e-6)

    def snapshot(self, now: Optional[float] = None) -> dict:
        now = clock.now() if now is None else now
        fast = self.attainment(self.fast_window_s, now)
        slow = self.attainment(self.slow_window_s, now)
        # burn derives from the attainments already in hand — snapshot()
        # runs per scrape step and per dashboard poll, and each
        # attainment() is a lock-held scan of the event deque
        budget = max(1.0 - self.targets["availability"], 1e-6)
        return {
            "targets": dict(self.targets),
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "attainment_fast": round(fast, 4) if fast is not None else None,
            "attainment_slow": round(slow, 4) if slow is not None else None,
            "burn_rate_fast": (round((1.0 - fast) / budget, 3)
                               if fast is not None else None),
            "burn_rate_slow": (round((1.0 - slow) / budget, 3)
                               if slow is not None else None),
            "requests_total": self.total,
            "violations_total": self.violations,
        }
