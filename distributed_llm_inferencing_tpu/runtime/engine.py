"""InferenceEngine: jitted, sharded prefill + decode with streaming.

TPU-native replacement for the reference's hot path — where the worker
called opaque ``model.generate()`` per request (reference:
worker/app.py:297-305), this engine owns the loop:

- **prefill**: one jitted call over a right-padded, bucketed prompt block
  (bucketing bounds XLA recompiles — the problem HF hid from the reference)
- **decode**: one jitted single-token step, compiled once per cache shape,
  with donated cache buffers so decoding is in-place in HBM
- **sampling** is fused into the decode program (ops/sampling.py)
- **sharding**: params/cache placed via parallel/sharding.py over any
  MeshSpec; the same engine runs single-chip or tp×dp×ep meshes unchanged
- **streaming**: tokens surface per step through a callback — the reference
  had no streaming at all (SURVEY.md §2.3)

Engine-level guards reject requests that exceed the context window instead
of silently clipping (models/transformer.py clips only as jit-safety).
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llm_inferencing_tpu.models import transformer
from distributed_llm_inferencing_tpu.models.config import ModelConfig
from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.ops.kvcache import KVCache, init_cache
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams, sample
from distributed_llm_inferencing_tpu.parallel import sharding as shd
from distributed_llm_inferencing_tpu.parallel.mesh import (
    MeshSpec, create_mesh, validate_spec)
from distributed_llm_inferencing_tpu.utils import clock, trace
from distributed_llm_inferencing_tpu.utils.metrics import Metrics

PREFILL_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)


def _bucket(n: int) -> int:
    for b in PREFILL_BUCKETS:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds largest bucket")


@dataclasses.dataclass
class GenerateResult:
    tokens: List[List[int]]          # new tokens per sequence (eos-trimmed)
    prefill_ms: float
    decode_ms: float
    steps: int

    @property
    def decode_tokens_per_s(self) -> float:
        total = sum(len(t) for t in self.tokens)
        return total / (self.decode_ms / 1e3) if self.decode_ms > 0 else 0.0

    def cost(self) -> dict:
        """Engine-mode cost-ledger record, schema-compatible with the
        batcher's (runtime/batcher.py _cost_record). The engine serves
        one blocking generate at a time behind the per-model lock, so
        queue time is the caller's to measure — 0 here; a decode step
        is one weight-streaming pass."""
        total = sum(len(t) for t in self.tokens)
        return {
            "queue_ms": 0.0,
            "prefill_ms": round(self.prefill_ms, 3),
            "decode_ms": round(self.decode_ms, 3),
            "prefill_cached_tokens": 0,
            "prefill_uncached_tokens": 0,
            "decode_tokens": total,
            "weight_passes": self.steps,
            "engine_mode": True,
        }


class InferenceEngine:
    """Owns params on device + compiled step functions for one model."""

    def __init__(self, cfg: ModelConfig, params=None, *,
                 mesh_spec: Optional[MeshSpec] = None,
                 max_seq: Optional[int] = None,
                 seed: int = 0,
                 pipeline_microbatches: Optional[int] = None,
                 metrics: Optional[Metrics] = None):
        # the worker shares its registry so /metrics carries engine
        # timings; standalone engines keep their own
        self.metrics = metrics or Metrics()
        self.mesh_spec = mesh_spec or MeshSpec()
        self._n_micro = pipeline_microbatches
        validate_spec(self.mesh_spec, cfg)
        self.mesh = create_mesh(self.mesh_spec)
        # Pin the attention backend now that the program's device span is
        # known (pallas kernels are single-program; GSPMD partitions the
        # xla formulation on multi-device meshes).
        from distributed_llm_inferencing_tpu.models.transformer import (
            _cfg_backend)
        self.cfg = cfg = cfg.replace(
            attn_backend=_cfg_backend(cfg, self.mesh_spec.num_devices),
            # int4 pallas routing: row-parallel leaves stay on XLA when
            # this GSPMD program shards them over tp (config.py field doc)
            tp_row_sharded=self.mesh_spec.tp > 1,
            # MLA serves from the latent cache (the absorbed
            # formulation, transformer._mla_latent_attn) whenever the
            # mesh is eligible: cuts dense-cache bytes by
            # 2*H*head_dim/(kv_lora_rank+rope) (~19x on deepseek-proxy).
            # DLI_MLA_LATENT=0 opts out (A/B vs materialized).
            mla_latent_cache=(
                cfg.mla and cfg.kv_quant is None
                and cfg.sliding_window is None and cfg.attn_windows is None
                and cfg.attn_softcap is None
                and self.mesh_spec.sp == 1 and self.mesh_spec.pp == 1
                and os.environ.get("DLI_MLA_LATENT") != "0"))
        self.max_seq = min(max_seq or cfg.max_position_embeddings,
                           cfg.max_position_embeddings)
        # sequence parallelism shards the cache S axis: keep it divisible
        # (round DOWN — exceeding the model's position window would admit
        # positions past learned-embedding rows / the trained RoPE range)
        sp = self.mesh_spec.sp
        if sp > 1 and self.max_seq % sp:
            self.max_seq -= self.max_seq % sp

        if params is None:
            params = init_params(cfg, jax.random.PRNGKey(seed))
        else:
            from distributed_llm_inferencing_tpu.ops.quant import (
                maybe_quantize, maybe_quantize_embed)
            params = maybe_quantize_embed(maybe_quantize(params, cfg), cfg)
        with self.mesh:
            self.params = shd.shard_params(params, self.mesh, cfg, self.mesh_spec)
        self._maybe_unroll_layers()

        self._cache_shardings = shd.named(
            self.mesh, shd.cache_specs(cfg, self.mesh_spec))
        self._prefill_fns = {}  # bucket -> compiled
        self._decode_fns = {}   # SamplingParams -> compiled
        # LoRA single-stream hook (models/lora.py): name -> adapter
        # (host numpy) and name -> cached params tree carrying its
        # delta pack. One adapter per generate() call.
        self._adapters = {}
        self._adapter_trees = {}

    # Layer-count cap for the CPU unrolled path: past this, the unrolled
    # program's compile time outweighs the per-step win.
    UNROLL_MAX_LAYERS = 48

    def _maybe_unroll_layers(self):
        """On a single-device CPU backend, split the stacked ``[L, ...]``
        layer params into per-layer trees of separate buffers and let
        transformer.forward run the stack as an unrolled Python loop.

        XLA-CPU compiles an M<=2 dot whose weight operand is a (scan or
        static) slice of a stacked array to a scalar kLoop fusion instead
        of the dot kernel — measured ~7x slower for gpt2 f32 decode. Real
        per-layer buffers restore the dot kernel and let batch-1 decode
        stay batch-1 (engine.generate drops its dummy-row workaround).
        TPU/GPU keep the stacked scan: one traced layer regardless of
        depth, and the layer axis is what pipeline parallelism shards.
        """
        self._layers_unrolled = False
        flag = os.environ.get("DLI_UNROLL_LAYERS")
        if flag in ("0", "false"):
            return
        # cpu + single-device are HARD gates (a list-of-layers tree has
        # no stacked [L,...] axis for pp to shard, and the repacked
        # leaves lower cpu-platform FFI calls); the env flag only lifts
        # the layer-count compile-time heuristic.
        if not (jax.default_backend() == "cpu"
                and self.mesh_spec.num_devices == 1):
            return
        if self.cfg.num_layers > self.UNROLL_MAX_LAYERS and flag is None:
            return
        self.params = dict(self.params)
        k = self.cfg.dense_prefix_layers
        for key, n in (("layers_dense", k),
                       ("layers", self.cfg.num_layers - k)):
            if key not in self.params:
                continue
            stacked = self.params[key]
            self.params[key] = [
                jax.tree.map(lambda a, i=i: a[i], stacked)
                for i in range(n)]
        self._layers_unrolled = True
        self._maybe_repack_cpu()

    def _maybe_repack_cpu(self):
        """Repack linear leaves into the CPU-native transposed layout so
        decode streams the stored bytes via the FFI GEMV
        (ops/cpu_gemv.py): int8 leaves stay int8 (XLA-CPU's lowering
        materializes the f32 dequant first), f32/bf16 leaves get the
        kernel's ~20%-higher streaming bandwidth over XLA's dot."""
        from distributed_llm_inferencing_tpu.ops import cpu_gemv
        if not cpu_gemv.available():
            return
        bf16_storage = os.environ.get(
            "DLI_CPU_WEIGHT_STORAGE") == "bf16"

        def repack(leaf):
            if not isinstance(leaf, dict) or not ("q" in leaf
                                                  or "w" in leaf):
                return leaf
            # eager swapaxes materializes a dense row-major [dout, din]
            # buffer — exactly the contiguous-along-K layout the kernel
            # streams
            if "q" in leaf:
                out = {"qT": jnp.swapaxes(leaf["q"], -2, -1),
                       "scale": leaf["scale"]}
            elif leaf["w"].ndim != 2:   # moe expert stacks etc.
                return leaf
            elif leaf["w"].dtype == jnp.bfloat16 or bf16_storage:
                # bf16-stored weights (f32 accumulate in the kernel):
                # either the model already serves bf16, or the operator
                # opted into storage truncation on an f32 engine
                # (DLI_CPU_WEIGHT_STORAGE=bf16) — half the streamed
                # bytes at near-f32 accuracy
                out = {"wT": jnp.swapaxes(
                    leaf["w"].astype(jnp.bfloat16), -2, -1)}
            elif leaf["w"].dtype == jnp.float32:
                # f32 via the FFI kernel measures at parity with XLA's
                # own dot — keep XLA (no repack) for plain f32 leaves
                return leaf
            else:
                out = {"wT": jnp.swapaxes(leaf["w"], -2, -1)}
            if "b" in leaf:
                out["b"] = leaf["b"]
            return out

        # only the big matmul leaves (ops/quant.py's set): the router is
        # read raw by _moe_gates and norms carry no "w"
        from distributed_llm_inferencing_tpu.ops.quant import _LINEAR_LEAVES
        # the latent path consumes kv_b_k/kv_b_v through absorbed
        # einsums (_wfull), not _linear — keep their stored layout
        skip = ({"kv_b_k", "kv_b_v"} if self.cfg.mla_latent_cache
                else set())
        for key in ("layers", "layers_dense"):
            for lp in self.params.get(key, ()):
                for name in _LINEAR_LEAVES:
                    if name in lp and name not in skip:
                        lp[name] = repack(lp[name])
        if "lm_head" in self.params:
            self.params["lm_head"] = repack(self.params["lm_head"])
        # the tied-head table is the single largest per-token read for
        # the gpt2 family; under bf16 storage it halves too (embed is a
        # gather — dequant is a per-row astype; the unembed FFI branch
        # streams the bf16 rows directly, models/transformer.py unembed)
        tok = self.params.get("embed", {}).get("tokens")
        if (bf16_storage and tok is not None and not isinstance(tok, dict)
                and tok.dtype == jnp.float32):
            self.params["embed"] = dict(self.params["embed"])
            self.params["embed"]["tokens"] = tok.astype(jnp.bfloat16)

    # ---- compiled step builders -------------------------------------

    def _timed_first_call(self, fn):
        """Wrap a freshly-built jitted fn: jit compiles synchronously
        inside the first call (execution dispatches async), so timing
        that call observes ``engine_jit_compile`` to within one dispatch.
        Lives here — not at the call sites — so every compile-cache
        accessor reports compile time without re-deriving its key shape."""
        state = {"first": True}

        def wrapper(*args):
            if state.pop("first", None):
                t0 = time.perf_counter()
                out = fn(*args)
                self.metrics.observe("engine_jit_compile",
                                     time.perf_counter() - t0)
                return out
            return fn(*args)

        return wrapper

    def _build_prefill(self, s0: int):
        cfg = self.cfg
        # sp>1 routes prefill attention through the ring (parallel/ring.py);
        # pp>1 routes the whole stack through the pipelined executor
        mesh = self.mesh if self.mesh_spec.sp > 1 else None
        pp = self.mesh_spec.pp

        def fn(params, tokens, lengths, cache):
            if pp > 1:
                from distributed_llm_inferencing_tpu.parallel import pipeline
                logits, cache = pipeline.pipelined_prefill(
                    params, cfg, tokens, lengths, cache, mesh=self.mesh,
                    n_micro=pipeline.pick_n_micro(tokens.shape[0], pp,
                                                  self._n_micro))
            else:
                logits, cache = transformer.prefill(
                    params, cfg, tokens, lengths, cache, mesh=mesh)
            # gather last valid logit per sequence: [B,V]
            idx = jnp.maximum(lengths - 1, 0)
            last = jnp.take_along_axis(
                logits, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
            return last, cache

        return self._timed_first_call(jax.jit(fn, donate_argnums=(3,)))

    # Chunk sizes for the scanned decode loop. Any max_new_tokens is a
    # greedy sum of these, so at most len(DECODE_CHUNKS) programs compile
    # per sampling config and the host syncs once per chunk, not per token
    # (the per-token dispatch+transfer pattern is what made the reference's
    # serving loop unshippable on an accelerator behind a network hop).
    # Powers of two keep the greedy cover tight: 63 remaining = 6 chunks,
    # which is what bounds per-chunk syncs on the streaming/eos path (the
    # non-streaming path queues every chunk and syncs once regardless).
    # The continuous batcher reuses this schedule (runtime/batcher.py).
    DECODE_CHUNKS = (64, 32, 16, 8, 4, 2, 1)
    # The incremental (streaming / eos-early-exit) path syncs and emits
    # only at chunk boundaries, and a chunk that straddles eos is wasted
    # compute — cap its chunk size so burst latency and eos overshoot
    # stay bounded while the fire-and-forget path uses the full 64.
    STREAM_CHUNK_MAX = 32

    def _decode_jitted(self, sp: SamplingParams, T: int):
        # per-instance cache (an lru_cache on the method would pin the
        # engine — and its HBM-resident params — in a class-global cache,
        # defeating /unload_model)
        fn = self._decode_fns.get((sp, T))
        if fn is None:
            cfg = self.cfg

            pp = self.mesh_spec.pp
            mesh, n_micro_req = self.mesh, self._n_micro

            def raw(params, tokens, cache, key):
                def step(carry, _):
                    cur, cache, key = carry
                    key, sub = jax.random.split(key)
                    if pp > 1:
                        from distributed_llm_inferencing_tpu.parallel import (
                            pipeline)
                        logits, cache = pipeline.pipelined_decode_step(
                            params, cfg, cur[:, None], cache, mesh=mesh,
                            n_micro=pipeline.pick_n_micro(
                                cur.shape[0], pp, n_micro_req))
                    else:
                        logits, cache = transformer.decode_step(
                            params, cfg, cur[:, None], cache,
                            mesh=(mesh if self.mesh_spec.sp > 1 else None))
                    nxt = sample(logits[:, 0], sub, sp)
                    return (nxt, cache, key), nxt

                (cur, cache, key), toks = jax.lax.scan(
                    step, (tokens, cache, key), length=T)
                return toks, cur, cache, key   # toks: [T, B]

            fn = self._timed_first_call(jax.jit(raw, donate_argnums=(2,)))
            # cap scaled to the chunk schedule: ~8 sampling configs' worth
            # of compiled programs before FIFO eviction
            if len(self._decode_fns) >= 8 * len(self.DECODE_CHUNKS):
                self._decode_fns.pop(next(iter(self._decode_fns)))
            self._decode_fns[(sp, T)] = fn
        return fn

    # ---- LoRA adapters (single-stream delta hook) ---------------------

    def load_adapter(self, adapter=None, *, name=None, source=None):
        """Make a LoRA adapter available to ``generate(adapter=...)``.

        Pass a ``models.lora.LoRAAdapter`` directly, or ``name`` +
        ``source`` (checkpoint dir, or a ``synth:`` URI for tests).
        The engine serves one adapter per request by swapping in a
        params tree whose layers carry the delta pack — the SAME
        ``_lora_apply`` hook the batcher's gathered path runs, so the
        single-stream and batched paths agree bitwise per request.
        """
        from distributed_llm_inferencing_tpu.models import lora as lora_mod
        if self.mesh_spec.pp > 1:
            raise ValueError("LoRA serving does not support pp > 1 "
                             "(the pipelined executor re-stages the "
                             "stacked layer tree without the delta pack)")
        if adapter is None:
            adapter = lora_mod.resolve(self.cfg, name, source)
        else:
            lora_mod._check_adapter(self.cfg, adapter)
        self._adapters[adapter.name] = adapter
        self._adapter_trees.pop(adapter.name, None)
        return adapter

    def unload_adapter(self, name: str) -> bool:
        self._adapter_trees.pop(name, None)
        return self._adapters.pop(name, None) is not None

    def adapter_stats(self) -> dict:
        """Resident-adapter advertisement for the worker's /health (the
        master's affinity scorer reads it from the node snapshot)."""
        return {"resident": sorted(self._adapters),
                "bytes": sum(a.nbytes for a in self._adapters.values())}

    def _params_for(self, adapter: Optional[str]):
        """Base params, or a shallow-copied tree whose layers carry the
        adapter's delta pack at slot 0. The dense forward passes no
        per-row ids, so ``_lora_apply`` gathers row 0 for every row —
        exactly this adapter. jit retraces once per adapter rank (the
        tree structure gains a "lora" subtree); the tree is cached so
        repeat requests reuse the committed device buffers."""
        if adapter is None:
            return self.params
        ad = self._adapters.get(adapter)
        if ad is None:
            raise ValueError(
                f"unknown adapter {adapter!r} (load_adapter first)")
        tree = self._adapter_trees.get(adapter)
        if tree is None:
            # per-layer {target: {"a": [1, din, r], "b": [1, r, dout]}}
            # with the alpha/rank scale folded into B (ops/lora.py doc)
            packs = [
                {t: {"a": a[None], "b": (b * ad.scale)[None]}
                 for t, (a, b) in lp.items()}
                for lp in ad.layers]
            tree = dict(self.params)
            if self._layers_unrolled:
                tree["layers"] = [
                    dict(lp, lora=jax.tree.map(jnp.asarray, packs[i]))
                    for i, lp in enumerate(tree["layers"])]
            else:
                stacked = {
                    t: {k: jnp.asarray(np.stack([p[t][k] for p in packs]))
                        for k in ("a", "b")}
                    for t in packs[0]}
                tree["layers"] = dict(tree["layers"], lora=stacked)
            self._adapter_trees[adapter] = tree
        return tree

    # ---- public API --------------------------------------------------

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: int = 100,   # reference default, views.py:351
        sampling: Optional[SamplingParams] = None,
        seed: int = 0,
        eos_token_id: Optional[int] = None,
        stream_cb: Optional[Callable[[int, List[int]], None]] = None,
        speculative: Optional[str] = None,   # "ngram" (ops/speculative.py)
        spec_gamma: int = 4,
        adapter: Optional[str] = None,       # LoRA adapter name (load_adapter)
    ) -> GenerateResult:
        """Generate continuations for a batch of token-id prompts.

        stream_cb(step, tokens_this_step) fires after every decode step —
        the streaming surface the server layer exposes as SSE.

        ``speculative="ngram"`` turns on prompt-lookup speculative decoding
        (single sequence only): each dispatched program verifies
        ``spec_gamma`` self-drafted tokens, emitting 1..gamma+1 tokens per
        step — output distribution identical to plain decode (exact for
        greedy; leave-one-out rejection for sampling).
        """
        if speculative is not None:
            if adapter is not None:
                raise ValueError(
                    "LoRA adapters do not combine with speculative "
                    "decoding (the verify program has no delta hook)")
            return self._generate_speculative(
                prompts, max_new_tokens, sampling, seed, eos_token_id,
                stream_cb, speculative, spec_gamma)
        # raises on unknown adapter — a request NEVER silently serves
        # base weights (models/lora.py doc)
        params = self._params_for(adapter)
        cfg = self.cfg
        sp = sampling or SamplingParams()
        n_real = len(prompts)
        lens = [len(p) for p in prompts]
        if not lens or min(lens) < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            return GenerateResult(tokens=[[] for _ in range(n_real)],
                                  prefill_ms=0.0, decode_ms=0.0, steps=0)
        max_len = max(lens)
        if max_len + max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt ({max_len}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds engine max_seq {self.max_seq} "
                f"(context window {cfg.max_position_embeddings})")

        # pad batch to a dp-divisible size with dummy rows (trimmed below)
        dp = self.mesh_spec.dp
        B = -(-n_real // dp) * dp
        if B == 1 and jax.default_backend() == "cpu" \
                and not self._layers_unrolled:
            # XLA-CPU strength-reduces M=1 dots whose weight operand is a
            # scan slice into naive kLoop fusions (~10-20x slower than the
            # dot kernel); a dummy second batch row keeps the real dot.
            # The unrolled-layer path has real per-layer buffers, so it
            # decodes at true batch 1. TPU/GPU never take this branch.
            B = 2
        prompts = list(prompts) + [[0]] * (B - n_real)
        lens = lens + [1] * (B - n_real)

        # bucket capped at cache capacity (max_len <= max_seq is guaranteed
        # by the guard above, so s0 >= max_len always holds)
        s0 = min(_bucket(max_len), self.max_seq)
        sp_deg = self.mesh_spec.sp
        if sp_deg > 1 and s0 % sp_deg:  # ring needs sp-divisible blocks
            s0 = min(s0 + sp_deg - s0 % sp_deg, self.max_seq)
        tokens = np.zeros((B, s0), np.int32)
        for i, p in enumerate(prompts):
            tokens[i, :len(p)] = p
        lengths = jnp.asarray(lens, jnp.int32)

        with self.mesh:
            cache = init_cache(cfg, B, self.max_seq)
            cache = jax.device_put(cache, self._cache_shardings)

            prefill_fresh = s0 not in self._prefill_fns
            if prefill_fresh:
                self._prefill_fns[s0] = self._build_prefill(s0)
            t0 = time.perf_counter()
            wt0 = clock.now()
            last_logits, cache = self._prefill_fns[s0](
                params, jnp.asarray(tokens), lengths, cache)
            key = jax.random.PRNGKey(seed)
            key, sub = jax.random.split(key)
            cur = sample(last_logits, sub, sp)

            # Host syncs are the enemy: on a remote-attached chip one
            # device->host round trip costs tens of ms. Sync per chunk only
            # when the host must see tokens mid-flight (eos early-exit /
            # streaming); otherwise queue every chunk dispatch and sync ONCE.
            incremental = (eos_token_id is not None) or (stream_cb is not None)

            if incremental:
                cur.block_until_ready()
            t1 = time.perf_counter()
            wt1 = clock.now()

            steps = 1
            remaining = max_new_tokens - 1
            if not incremental:
                first_dev = cur          # prefill's sample (never donated)
                chunks_dev = []
                while remaining > 0:
                    T = next(c for c in self.DECODE_CHUNKS if c <= remaining)
                    decode = self._decode_jitted(sp, T)
                    toks_dev, cur, cache, key = decode(
                        params, cur, cache, key)
                    chunks_dev.append(toks_dev)
                    steps += T
                    remaining -= T
                # ONE sync for the whole request
                first, host_chunks = jax.device_get((first_dev, chunks_dev))
                toks_all = (np.concatenate(host_chunks, axis=0)
                            if host_chunks else np.zeros((0, B), np.int32))
                out = [[int(first[i])] + [int(t) for t in toks_all[:, i]]
                       for i in range(B)]
            else:
                out = [[int(cur[i])] for i in range(B)]
                done = [(i >= n_real) or
                        (eos_token_id is not None and out[i][0] == eos_token_id)
                        for i in range(B)]
                if stream_cb:
                    stream_cb(0, [int(cur[i]) for i in range(n_real)])

                # Without an eos stop-check the chunk schedule is data-
                # independent: keep a BOUNDED lookahead of dispatched
                # chunks (depth 2 — chunk N+1 launches before chunk N's
                # tokens transfer back, which is all the dispatch/
                # transfer overlap there is to win) rather than queueing
                # the whole generation: a stream_cb that dies mid-stream
                # (client disconnect) then wastes at most the in-flight
                # pair, not every remaining chunk. With eos the host
                # must see each chunk's tokens before dispatching more.
                pipelined: list = []
                rem_dispatch = remaining if eos_token_id is None else 0

                def dispatch_next():
                    nonlocal rem_dispatch, cur, cache, key
                    T = next(c for c in self.DECODE_CHUNKS
                             if c <= min(rem_dispatch,
                                         self.STREAM_CHUNK_MAX))
                    decode = self._decode_jitted(sp, T)
                    toks_dev, cur, cache, key = decode(
                        params, cur, cache, key)
                    pipelined.append((toks_dev, T))
                    rem_dispatch -= T

                while rem_dispatch > 0 and len(pipelined) < 2:
                    dispatch_next()

                while remaining > 0 and not all(done):
                    if pipelined:
                        toks_dev, T = pipelined.pop(0)
                        if rem_dispatch > 0:   # refill BEFORE blocking
                            dispatch_next()
                    else:
                        T = next(c for c in self.DECODE_CHUNKS
                                 if c <= min(remaining,
                                             self.STREAM_CHUNK_MAX))
                        decode = self._decode_jitted(sp, T)
                        toks_dev, cur, cache, key = decode(
                            params, cur, cache, key)
                    toks = np.asarray(toks_dev)    # [T, B] — one sync per chunk
                    for t in range(T):
                        # stream exactly what lands in `out` this step;
                        # finished sequences surface as None
                        emit = [None if done[i] else int(toks[t, i])
                                for i in range(n_real)]
                        for i in range(B):
                            if not done[i]:
                                out[i].append(int(toks[t, i]))
                                if (eos_token_id is not None
                                        and toks[t, i] == eos_token_id):
                                    done[i] = True
                        if stream_cb and any(e is not None for e in emit):
                            stream_cb(steps + t, emit)
                    steps += T
                    remaining -= T
            t2 = time.perf_counter()
            wt2 = clock.now()

        out = out[:n_real]  # drop dp-padding rows
        # trim trailing eos
        if eos_token_id is not None:
            out = [t[:-1] if t and t[-1] == eos_token_id else t for t in out]
        self._observe_generate(
            wt0, wt1, wt2, t1 - t0, t2 - t1, steps,
            {"model": cfg.name, "batch": n_real, "steps": steps},
            {"bucket": s0, "compiled": prefill_fresh},
            {"steps": steps, "incremental": incremental})
        return GenerateResult(
            tokens=out, prefill_ms=(t1 - t0) * 1e3,
            decode_ms=(t2 - t1) * 1e3, steps=steps)

    def _observe_generate(self, wt0, wt1, wt2, prefill_s, decode_s, steps,
                          gen_attrs, prefill_attrs, decode_attrs):
        """Shared metrics+trace epilogue for every generate path. Spans
        are retroactive (utils/trace.py record) and nest under the
        caller's span — the worker's /inference handler — via the
        contextvar; wall stamps keep master/worker timelines aligned
        while the perf_counter deltas feed the histograms."""
        self.metrics.observe("engine_prefill", prefill_s)
        self.metrics.observe("engine_decode", decode_s)
        self.metrics.inc("engine_decode_steps", steps)
        tracer = trace.get_tracer()
        g = tracer.record("engine.generate", wt0, wt2,
                          parent=trace.current(), attrs=gen_attrs)
        tracer.record("engine.prefill", wt0, wt1, parent=g,
                      attrs=prefill_attrs)
        tracer.record("engine.decode", wt1, wt2, parent=g,
                      attrs=decode_attrs)

    # ---- speculative decoding (ops/speculative.py) --------------------

    def _verify_jitted(self, sp: SamplingParams, g: int):
        fn = self._decode_fns.get(("spec", sp, g))
        if fn is None:
            cfg = self.cfg
            from distributed_llm_inferencing_tpu.ops import speculative

            def raw(params, cache, cur, drafts, key):
                return speculative.verify_step(params, cfg, cache, cur,
                                               drafts, key, sp)

            fn = self._timed_first_call(jax.jit(raw, donate_argnums=(1,)))
            if len(self._decode_fns) >= 8 * len(self.DECODE_CHUNKS):
                self._decode_fns.pop(next(iter(self._decode_fns)))
            self._decode_fns[("spec", sp, g)] = fn
        return fn

    def _generate_speculative(self, prompts, max_new_tokens, sampling, seed,
                              eos_token_id, stream_cb, mode, gamma):
        """Prompt-lookup speculative loop: one verify program per step,
        1..gamma+1 tokens per host sync. Single-sequence (speculation is a
        latency lever for individual streams; batched throughput comes
        from the continuous batcher)."""
        from distributed_llm_inferencing_tpu.ops import speculative
        if mode != "ngram":
            raise ValueError(f"unknown speculative mode {mode!r}")
        if len(prompts) != 1:
            raise ValueError("speculative decoding serves one sequence")
        if any(getattr(self.mesh_spec, ax) > 1 for ax in ("sp", "pp", "dp")):
            raise ValueError("speculative decoding supports tp/ep meshes")
        cfg = self.cfg
        sp = sampling or SamplingParams()
        gamma = max(1, int(gamma))
        prompt = list(map(int, prompts[0]))
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            return GenerateResult(tokens=[[]], prefill_ms=0.0, decode_ms=0.0,
                                  steps=0)
        if len(prompt) + max_new_tokens + gamma + 1 > self.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens})"
                f" + gamma ({gamma}) exceeds engine max_seq {self.max_seq}")

        s0 = min(_bucket(len(prompt)), self.max_seq)
        tokens = np.zeros((1, s0), np.int32)
        tokens[0, :len(prompt)] = prompt
        with self.mesh:
            cache = init_cache(cfg, 1, self.max_seq)
            cache = jax.device_put(cache, self._cache_shardings)
            prefill_fresh = s0 not in self._prefill_fns
            if prefill_fresh:
                self._prefill_fns[s0] = self._build_prefill(s0)
            t0 = time.perf_counter()
            wt0 = clock.now()
            last_logits, cache = self._prefill_fns[s0](
                self.params, jnp.asarray(tokens),
                jnp.asarray([len(prompt)], jnp.int32), cache)
            key = jax.random.PRNGKey(seed)
            key, sub = jax.random.split(key)
            cur = int(sample(last_logits, sub, sp)[0])
            t1 = time.perf_counter()
            wt1 = clock.now()

            hit_eos = eos_token_id is not None and cur == eos_token_id
            out: List[int] = [] if hit_eos else [cur]
            if stream_cb and not hit_eos:
                stream_cb(0, [cur])   # same contract as the plain path
            history = prompt + out
            steps = 1
            # Adaptive drafting (ops/speculative.py): a verify dispatch
            # costs one host sync per <= gamma+1 tokens, while a plain
            # chunk syncs once per <= STREAM_CHUNK_MAX — on a host where
            # dispatch dominates, drafting loses even at full acceptance
            # (BENCH_r05: 5.54 vs 17.04 tok/s). The controller measures
            # both arms and hands the loop to whichever is faster, so
            # ``speculative="ngram"`` can never stay slower than off.
            # Fresh per call — a request's output must stay a function
            # of (params, prompt, seed), never of neighbor requests —
            # with a SHORT probe cadence so even a few-dozen-token
            # generation measures the plain arm and can fall back
            # mid-request (probe schedules count chunks, so same-seed
            # reruns make identical decisions until both arms are
            # measured). DLI_SPEC_ADAPTIVE=0 pins always-draft
            # (parity tests / A/B).
            ctl = (speculative.AdaptiveSpecController(gamma, probe_every=8)
                   if os.environ.get("DLI_SPEC_ADAPTIVE", "1")
                   not in ("0", "false") else None)
            while len(out) < max_new_tokens and not hit_eos:
                g_now = ctl.choose() if ctl is not None else gamma
                p0 = time.perf_counter()
                if g_now == 0:
                    # plain fallback: same chunk trade as the streaming
                    # decode path (eos checked host-side per chunk)
                    rem = max_new_tokens - len(out)
                    T = next(c for c in self.DECODE_CHUNKS
                             if c <= min(rem, self.STREAM_CHUNK_MAX))
                    compiled = (sp, T) not in self._decode_fns
                    decode = self._decode_jitted(sp, T)
                    toks_dev, _, cache, key = decode(
                        self.params, jnp.asarray([out[-1]], jnp.int32),
                        cache, key)
                    emitted = [int(t) for t in np.asarray(toks_dev)[:, 0]]
                    steps += T
                else:
                    drafts = speculative.propose_ngram(history, g_now)
                    if drafts is None:
                        # no n-gram hit: verify a dummy draft — still
                        # emits >= 1 correct token for one dispatch
                        drafts = [history[-1]] * g_now
                    compiled = ("spec", sp, g_now) not in self._decode_fns
                    verify = self._verify_jitted(sp, g_now)
                    toks_dev, n_emit, cache, key = verify(
                        self.params, cache,
                        jnp.asarray([out[-1]], jnp.int32),
                        jnp.asarray([drafts], jnp.int32), key)
                    steps += 1
                    n = int(n_emit[0])
                    emitted = [int(t) for t in np.asarray(toks_dev)[0, :n]]
                # keep (and stream) only what the result will contain:
                # nothing past max_new_tokens, nothing at/after eos
                kept = []
                for t in emitted:
                    if eos_token_id is not None and t == eos_token_id:
                        hit_eos = True
                        break
                    kept.append(t)
                    if len(out) + len(kept) >= max_new_tokens:
                        break
                if ctl is not None:
                    dt = time.perf_counter() - p0
                    if g_now == 0:
                        ctl.record("plain", emitted=len(emitted),
                                   elapsed_s=dt, compiled=compiled)
                    else:
                        ctl.record("spec", emitted=len(emitted),
                                   elapsed_s=dt, drafted=g_now,
                                   accepted=len(emitted) - 1,
                                   compiled=compiled)
                out.extend(kept)
                history.extend(kept)
                if stream_cb:
                    # same contract as the plain path: one call per token,
                    # payload = that step's tokens per sequence ([t] here)
                    for j, t in enumerate(kept):
                        stream_cb(len(out) - len(kept) + j, [t])
            t2 = time.perf_counter()
            wt2 = clock.now()

        self._observe_generate(
            wt0, wt1, wt2, t1 - t0, t2 - t1, steps,
            {"model": cfg.name, "batch": 1, "steps": steps,
             "speculative": mode},
            {"bucket": s0, "compiled": prefill_fresh},
            {"steps": steps, "incremental": True})
        return GenerateResult(tokens=[out], prefill_ms=(t1 - t0) * 1e3,
                              decode_ms=(t2 - t1) * 1e3, steps=steps)

    # ---- introspection ----------------------------------------------

    def stats(self):
        from distributed_llm_inferencing_tpu.models.params import (
            param_bytes, param_count)
        return {
            "model": self.cfg.name,
            "mesh": self.mesh_spec.axis_sizes(),
            "params": param_count(self.params),
            "param_bytes": param_bytes(self.params),
            "max_seq": self.max_seq,
            "compiled_prefill_buckets": sorted(self._prefill_fns),
            "adapters": sorted(self._adapters),
        }
