"""Replicated control plane: leader-leased master pair over op-log
replication (docs/robustness.md "Replicated control plane").

One master process fronting one SQLite file was the fleet's last single
point of failure (ROADMAP item 4; FailSafe, arxiv 2511.14116, applied
to the control plane itself): its death orphaned every request row,
health probe, breaker transition and rebalance decision. This module
removes it with three pieces:

1. **Op-log replication through the Store waist.** The leader's
   :class:`~runtime.state.Store` hands every committed write batch to
   :meth:`HAController.on_ops`; the shipper assigns monotonically
   increasing sequence numbers and POSTs sequenced frames to every peer
   over pooled keep-alive HTTP (``POST /replicate``). A standby applies
   frames strictly in order into its own store (``Store.apply_ops`` —
   the leader's original WHERE-guarded SQL, so a replayed frame can
   never resurrect a terminal row) and acks its high-water mark; a
   fresh or diverged peer gets a full table snapshot first
   (``Store.dump_tables``), AUTOINCREMENT counters included, so the
   stream that follows replays onto identical rowids.

2. **Leader lease + automatic failover.** The lease — (term, holder
   nonce, expiry) — is heartbeated through the same ``/replicate``
   channel (empty frames when there is nothing to ship). Only the
   lease holder schedules/dispatches; when a standby's lease deadline
   expires it takes over at term+1, runs the crash-recovery requeue,
   and resumes dispatch. Standby takeover order is rank-deterministic
   (sorted identity) so N>2 fleets don't race the lease.

3. **Fencing.** Workers validate the dispatching master's (nonce,
   term) on every state-changing RPC and 409 stale terms
   (runtime/worker.py), and peers reject replication frames from a
   stale or competing term — a paused-then-revived old leader can
   neither double-dispatch nor write into the authoritative store.
   Split-brain guard: at equal terms the first holder a node saw wins;
   everyone else must take a HIGHER term to act.

Durability barrier: with ``DLI_HA_REPL_BARRIER=1`` client-visible
terminal statuses (and submit acks) additionally wait for a standby
ack — bounded by two lease intervals, after which the write degrades
to leader-only durability with a journaled ``replication-lag`` event
instead of ever hanging a dispatch thread.

Knobs (utils/knobs.py, generated table in docs/serving.md):
``DLI_HA_PEERS``, ``DLI_HA_LEASE_MS``, ``DLI_HA_REPL_BARRIER``,
``DLI_HA_REPL_LAG_WARN_MS``.
"""

from __future__ import annotations

import collections
import itertools
import json
import logging
import os
import threading
import uuid
from typing import Dict, List, Optional, Tuple

from distributed_llm_inferencing_tpu.runtime import events
from distributed_llm_inferencing_tpu.utils import clock, locks

log = logging.getLogger("dli_tpu.replication")

# Comma list of the OTHER masters' base URLs (http://host:port). Unset
# = solo master, HA entirely off (byte-for-byte the old behavior).
HA_PEERS = [u.strip() for u in
            os.environ.get("DLI_HA_PEERS", "").split(",") if u.strip()]
# Lease duration: the leader heartbeats every LEASE/3; a standby whose
# lease deadline (last heartbeat + LEASE) expires takes over.
HA_LEASE_MS = float(os.environ.get("DLI_HA_LEASE_MS", 3000))
# Durability barrier: terminal statuses / submit acks wait for a
# standby ack (bounded at 2 lease intervals, degrading loudly).
HA_REPL_BARRIER = os.environ.get("DLI_HA_REPL_BARRIER", "0") not in (
    "0", "false", "")
# Sustained replication lag above this (ms behind the op-log head)
# journals a replication-lag warning even without a barrier wait.
HA_REPL_LAG_WARN_MS = float(
    os.environ.get("DLI_HA_REPL_LAG_WARN_MS", 1000))
# The base URL peers/clients should reach THIS master at — distinct
# from the bind address: a master bound to 0.0.0.0 must not advertise
# "http://0.0.0.0:8000" as the redirect/heartbeat holder URL.
HA_ADVERTISE = os.environ.get("DLI_HA_ADVERTISE", "").rstrip("/")

# Ops per /replicate frame: bounds one POST's body; the shipper loops
# until the peer is caught up.
_FRAME_OPS = 512
# Op-log retention: a peer further behind than this gets a snapshot.
_OPLOG_RETAIN = 1 << 16


class OpLog:
    """Bounded, sequence-numbered log of committed store writes."""

    def __init__(self, retain: int = _OPLOG_RETAIN):
        self._lock = locks.lock("repl.oplog")
        self._ops: collections.deque = collections.deque()  # (seq, sql, args)
        self._seq = 0
        self._retain = max(1, int(retain))

    def append_new(self, ops) -> int:
        """Leader side: assign the next sequence numbers. Returns the
        new high-water mark."""
        with self._lock:
            for sql, args in ops:
                self._seq += 1
                self._ops.append((self._seq, sql, list(args)))
            while len(self._ops) > self._retain:
                self._ops.popleft()
            return self._seq

    def append_at(self, entries) -> int:
        """Standby side: advance the sequence counter past applied
        entries. Only the NUMBERING survives a promotion — `_takeover`
        resyncs every peer from a snapshot regardless, so storing the
        mirrored ops would be pure per-frame memory/CPU cost that is
        never served."""
        with self._lock:
            for seq, _sql, _args in entries:
                if seq > self._seq:
                    self._seq = seq
            return self._seq

    def since(self, seq: int, limit: int = _FRAME_OPS
              ) -> Optional[List[Tuple[int, str, list]]]:
        """Entries with sequence > ``seq`` (oldest first, capped), or
        None when ``seq`` predates retention — the caller must snapshot
        instead."""
        with self._lock:
            if seq < 0:
                return None
            if seq >= self._seq or not self._ops:
                # caught up (the steady-state hot path — every shipper
                # wake while ANY peer lags lands here for the others)
                return []
            if seq < self._ops[0][0] - 1:
                return None
            # sequence numbers are consecutive: slice by offset rather
            # than scanning the whole retention window per frame
            start = seq - self._ops[0][0] + 1
            return list(itertools.islice(self._ops, start,
                                         start + limit))

    def seq(self) -> int:
        with self._lock:
            return self._seq

    def reset_to(self, seq: int):
        """After loading a snapshot taken at ``seq``: the log restarts
        there (older entries are inside the snapshot)."""
        with self._lock:
            self._ops.clear()
            self._seq = int(seq)


class _Peer:
    __slots__ = ("url", "session", "cursor", "acked", "synced",
                 "last_ack_at", "last_error")

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.session = None          # requests.Session, built lazily
        self.cursor = 0              # last seq shipped
        self.acked = 0               # last seq the peer confirmed applied
        self.synced = False          # has this peer received a snapshot?
        self.last_ack_at = 0.0
        self.last_error: Optional[str] = None


class HAController:
    """One master's half of the replicated control plane: lease state,
    the op-log shipper/heartbeat thread, the ``/replicate`` apply path,
    and the standby takeover monitor. With no peers configured it
    degenerates to a permanently-leading no-op."""

    def __init__(self, master, *, peers: Optional[list] = None,
                 lease_ms: Optional[float] = None,
                 repl_barrier: Optional[bool] = None,
                 lag_warn_ms: Optional[float] = None,
                 leader: Optional[bool] = None,
                 self_url: Optional[str] = None):
        self.master = master
        self.store = master.store
        if peers is None:
            peers = HA_PEERS
        elif isinstance(peers, str):
            peers = [u.strip() for u in peers.split(",") if u.strip()]
        self.enabled = bool(peers)
        self.lease_s = (HA_LEASE_MS if lease_ms is None
                        else float(lease_ms)) / 1e3
        self.barrier_enabled = (HA_REPL_BARRIER if repl_barrier is None
                                else bool(repl_barrier))
        self.lag_warn_s = (HA_REPL_LAG_WARN_MS if lag_warn_ms is None
                           else float(lag_warn_ms)) / 1e3
        self.node_nonce = uuid.uuid4().hex[:8]
        self.self_url = ((self_url or "").rstrip("/") or HA_ADVERTISE
                         or None)
        self.oplog = OpLog()
        self._peers: Dict[str, _Peer] = {
            u.rstrip("/"): _Peer(u) for u in peers}
        # lease + apply state share one lock; the ack condition wakes
        # barrier waiters when the shipper records a peer ack
        self._state_lock = locks.lock("repl.state")
        # one frame applies at a time: the leader's POST timeout can
        # re-deliver a frame while the first apply is still running —
        # the watermark check and the apply must be one critical
        # section or non-idempotent ops (attempts+1, INSERTs) land
        # twice and the replica silently diverges
        self._apply_lock = locks.lock("repl.apply")
        self._ack_cv = locks.condition("repl.ack")
        # standby: last applied leader seq. Boots at -1 — DIVERGED —
        # not 0: a restarted standby holds none of the pre-op-log
        # state, and if its first resync ack said 0 the leader (whose
        # peer.synced is still True from the previous incarnation)
        # would happily rewind and replay from seq 1 onto the fresh
        # store instead of re-snapshotting it. -1 is the "snapshot me
        # first" sentinel the shipper already understands.
        self._applied = -1
        self._holder: Optional[str] = None
        self._leader_url: Optional[str] = None
        self._lease_deadline = 0.0
        self._lagging = False        # replication-lag event hysteresis
        self._behind_since = 0.0     # first sweep the best peer lagged
        # barrier circuit: a timed-out barrier wait disables further
        # waits until this deadline (or until a peer catches back up
        # to the op-log head) so one dead peer costs one bounded wait,
        # not one per write
        self._barrier_down_until = 0.0
        self._ship_wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        try:
            self.term = int(self.store.get_meta("ha_term") or 0)
        except Exception:
            self.term = 0
        if not self.enabled:
            # solo master: permanently the leader, zero overhead
            self.leader = True
            return
        self.leader = bool(leader)
        if self.leader:
            # bootstrap leader asserts a fresh term — and PERSISTS it,
            # so a restart on the same store comes back ABOVE any term
            # it held before the crash and a standby that meanwhile
            # took over is never usurped at an equal term (the
            # split-brain guard rejects equal-term competitors; higher
            # terms win cleanly)
            self.term += 1
            self._holder = self.node_nonce
            self._leader_url = self.self_url
            try:
                self.store.set_meta("ha_term", str(self.term))
            except Exception as e:
                log.warning("could not persist bootstrap term: %r", e)
        else:
            # standby boot grace: give an existing leader rank+2 lease
            # intervals to reach us before the takeover monitor fires
            self._lease_deadline = clock.now() + self.lease_s * (
                2 + self._rank())

    # ---- identity -----------------------------------------------------

    def _rank(self) -> int:
        """Deterministic takeover order across standbys: position of
        our identity in the sorted peer set. Rank 0 takes over first;
        each higher rank waits one extra lease interval, so N>2 fleets
        do not race the lease."""
        me = self.self_url or self.node_nonce
        return sorted(self._peers.keys() | {me}).index(me)

    def set_self_url(self, url: str):
        if url and self.self_url is None:
            self.self_url = url.rstrip("/")
            if self.leader:
                self._leader_url = self.self_url

    def is_leader(self) -> bool:
        return self.leader

    def leader_url(self) -> Optional[str]:
        return self._leader_url if not self.leader else self.self_url

    # ---- op-log hook (Store -> shipper) -------------------------------

    def on_ops(self, ops) -> None:
        """Store op hook: committed writes enter the op-log and wake
        the shipper. Runs under the store lock — cheap append only."""
        if not self.enabled or not self.leader:
            return
        self.oplog.append_new(ops)
        self._ship_wake.set()

    # ---- durability barrier -------------------------------------------

    def repl_barrier(self) -> bool:
        """Store barrier hook (leader side): wait until at least one
        standby acked the current op-log head. Bounded at TWO lease
        intervals — a wedged peer degrades this write to leader-only
        durability with a journaled ``replication-lag`` event, it never
        hangs the dispatch thread (the satellite fix for the unbounded
        barrier wait)."""
        if not (self.enabled and self.barrier_enabled):
            return True
        if not self.leader:
            # deposed between the commit and the barrier: the write
            # exists only in a diverged store the new leader's snapshot
            # will overwrite. Report the barrier FAILED — acking it as
            # durable would be silent loss (the caller decides: a
            # submit 503s so the client retries against the current
            # leader; a dispatch-tail write is already fenced).
            return False
        if clock.now() < self._barrier_down_until:
            # degraded mode (journaled when the wait that armed it
            # timed out): the peer is effectively dead — paying the
            # two-lease timeout on EVERY write would wedge throughput
            # on exactly the failover the barrier exists for. Writes
            # degrade to leader-only durability immediately; the
            # barrier re-probes after a cool-down, and a peer ack that
            # catches back up to the op-log head re-arms it at once.
            return False
        target = self.oplog.seq()
        if target == 0:
            return True
        self._ship_wake.set()
        deadline = clock.now() + 2 * self.lease_s
        with self._ack_cv:
            while True:
                if any(p.acked >= target for p in self._peers.values()):
                    return True
                if not self.leader:
                    # deposed while waiting: the ack will never come
                    # from the new regime — fail NOW (the known-at-
                    # step_down condition), don't burn the full window
                    # per blocked thread or arm the degrade circuit
                    # for a lag that isn't one
                    return False
                remaining = deadline - clock.now()
                if remaining <= 0:
                    break
                self._ack_cv.wait(timeout=min(remaining, 0.05))
        now = clock.now()
        self._barrier_down_until = now + 2 * self.lease_s
        self.master.metrics.inc("repl_barrier_timeouts")
        self._note_lag(now, forced=True)
        return False

    def _note_lag(self, now: float, forced: bool = False) -> None:
        """replication-lag journaling with hysteresis: one event per
        entering-lag edge (or per barrier timeout), one per recovery.
        Lag = how long the best peer has CONTINUOUSLY been behind the
        op-log head — not the staleness of its last ack: a standby that
        acks every frame promptly while applying at half the write rate
        is falling ever further behind and must still warn."""
        head = self.oplog.seq()
        best = max((p.acked for p in self._peers.values()), default=0)
        behind = head - best
        if behind > 0:
            if not self._behind_since:
                self._behind_since = now
        else:
            self._behind_since = 0.0
        lag_s = (now - self._behind_since) if self._behind_since else 0.0
        lagging = forced or (behind > 0 and lag_s > self.lag_warn_s)
        if lagging and not self._lagging:
            self._lagging = True
            events.emit("replication-lag", ops_behind=behind,
                        lag_ms=round(lag_s * 1e3, 1),
                        acked_seq=best, log_seq=head,
                        barrier_timeout=forced or None)
        elif not lagging and self._lagging and behind == 0:
            self._lagging = False
            events.emit("replication-lag", ops_behind=0, acked_seq=best,
                        log_seq=head, severity="info")

    # ---- shipper / lease loop -----------------------------------------

    def start(self):
        if not self.enabled or self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ha-repl")
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._ship_wake.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2 * self.lease_s + 1)
        for p in self._peers.values():
            if p.session is not None:
                try:
                    p.session.close()
                except Exception as e:
                    # the pool being closed is usually already dead
                    log.debug("peer session close failed: %r", e)

    def _loop(self):
        """Leader: ship op frames / heartbeats every lease/3 (sooner
        when writes land). Standby: watch the lease deadline and take
        over when it expires. The loop survives anything — a failed
        sweep costs one interval."""
        interval = max(0.02, self.lease_s / 3.0)
        while not self._stop.is_set():
            try:
                if self.leader:
                    self._ship_all()
            except Exception as e:
                log.debug("replication sweep failed: %r", e)
            try:
                if not self.leader and clock.now() > self._lease_deadline:
                    self._takeover()
            except Exception as e:
                log.warning("lease takeover attempt failed: %r", e)
            self._ship_wake.wait(timeout=interval)
            self._ship_wake.clear()

    def _session(self, peer: _Peer):
        if peer.session is None:
            import requests as http
            s = http.Session()
            adapter = http.adapters.HTTPAdapter(pool_connections=1,
                                                pool_maxsize=2)
            s.mount("http://", adapter)
            s.mount("https://", adapter)
            peer.session = s
        return peer.session

    def _headers(self) -> dict:
        key = os.environ.get("DLI_MASTER_AUTH_KEY")
        return {"Authorization": f"Bearer {key}"} if key else {}

    def _post(self, peer: _Peer, body: dict):
        # snapshot frames carry the whole store and the standby applies
        # them in one transaction: a lease-scale read timeout would
        # abort the resync every sweep and livelock the peer at
        # synced=False — give snapshots their own generous budget
        read = (max(10 * self.lease_s, 30.0) if "snapshot" in body
                else max(self.lease_s, 2.0))
        to = (min(2.0, self.lease_s), read)
        return self._session(peer).post(
            f"{peer.url}/replicate", json=body, headers=self._headers(),
            timeout=to)

    def _frame(self, peer: _Peer) -> dict:
        """The next frame for ``peer``: a snapshot on first contact or
        after divergence, else the ops past its cursor (empty = pure
        heartbeat). The cursor advances from the peer's ACK, not from
        what was shipped."""
        base = {"term": self.term, "holder": self.node_nonce,
                "holder_url": self.self_url,
                "lease_ms": self.lease_s * 1e3}
        if not peer.synced:
            # snapshot and op-log head read atomically under the store
            # lock (the op hook appends there): a write committing
            # between the two would be labeled into the gap and never
            # reach the standby. Known cost: the dump holds the store
            # lock for the walk, stalling writes for its duration —
            # acceptable because snapshots happen only at first
            # contact / divergence, never in the steady state.
            snap, seq = self.store.snapshot_with(self.oplog.seq)
            return dict(base, snapshot=snap, seq_start=seq + 1, ops=[])
        entries = self.oplog.since(peer.cursor)
        if entries is None:
            # fell behind retention: back to a snapshot
            peer.synced = False
            return self._frame(peer)
        seq_start = entries[0][0] if entries else peer.cursor + 1
        return dict(base, seq_start=seq_start,
                    ops=[[sql, args] for _s, sql, args in entries])

    def _ship_all(self):
        """One replication sweep: every peer gets its frame (ops or
        heartbeat) CONCURRENTLY — from one sequential loop, a dead
        peer's connect timeout (up to 2s) would starve the live peers'
        lease renewals and promote a healthy standby in N>=3 fleets."""
        now = clock.now()
        peers = list(self._peers.values())
        if len(peers) <= 1:
            for peer in peers:
                self._ship_peer(peer)
        else:
            def ship(p):
                try:
                    self._ship_peer(p)
                except Exception as e:
                    # inline shipping is covered by _loop's handler;
                    # a thread must not die silently
                    log.debug("ship to %s failed: %r", p.url, e)
            ts = [threading.Thread(target=ship, args=(p,),
                                   daemon=True, name=f"ha-ship-{i}")
                  for i, p in enumerate(peers)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        self.master.metrics.gauge(
            "repl_lag_ops",
            self.oplog.seq() - max((p.acked
                                    for p in self._peers.values()),
                                   default=0))
        self._note_lag(now)

    def _ship_peer(self, peer: _Peer):
        while self.leader and not self._stop.is_set():
            frame = self._frame(peer)
            try:
                r = self._post(peer, frame)
            except Exception as e:
                peer.last_error = repr(e)[:200]
                break
            if r.status_code == 409:
                # the peer is at a HIGHER (or competing equal) term:
                # we lost the lease while partitioned — stop acting.
                # But a peer 409ing at a LOWER term is not a lease
                # conflict (HA unconfigured on it, or a stale
                # persisted term): deposing ourselves on its word
                # would flap leadership forever — every takeover
                # bumps in-flight attempts until requests are
                # spuriously failed as poison
                try:
                    new_term = int(r.json().get("term") or 0)
                except ValueError:
                    # unparseable body: assume a real conflict
                    new_term = self.term + 1
                if new_term >= self.term:
                    self.step_down(new_term, reason="peer-term")
                    return
                peer.last_error = f"peer 409 at stale term {new_term}"
                break
            if r.status_code != 200:
                peer.last_error = f"HTTP {r.status_code}"
                break
            try:
                ack = r.json()
            except ValueError:
                peer.last_error = "unparseable ack"
                break
            peer.last_error = None
            applied = int(ack.get("applied") or 0)
            if applied < 0:
                # the peer declared divergence (a demoted leader's
                # dirty store): resync it from a snapshot
                peer.synced = False
                continue
            if "snapshot" in frame:
                peer.synced = True
            # the peer's ack is the ground truth of what it holds:
            # ship strictly past it next frame (a "resync" ack
            # rewinds the cursor; a clean ack advances it)
            peer.cursor = applied
            with self._ack_cv:
                peer.acked = max(peer.acked, applied)
                peer.last_ack_at = clock.now()
                if peer.acked >= self.oplog.seq():
                    # caught back up: re-arm the durability barrier
                    self._barrier_down_until = 0.0
                self._ack_cv.notify_all()
            self.master.metrics.inc("repl_frames_shipped")
            if frame["ops"]:
                self.master.metrics.inc("repl_ops_shipped",
                                        len(frame["ops"]))
            if applied >= self.oplog.seq():
                break               # caught up; next wake ships more
            # else: loop immediately with the next frame

    # ---- standby apply path (POST /replicate) -------------------------

    def handle_replicate(self, body: dict):
        """Apply one leader frame: lease bookkeeping + in-order op
        application. Returns the (status, payload) the HTTP handler
        relays. 409 carries OUR term so a stale leader steps down."""
        if not self.enabled:
            return 409, {"status": "error", "term": self.term,
                         "message": "HA not configured on this master"}
        try:
            term = int(body.get("term") or 0)
        except (TypeError, ValueError):
            return 400, {"status": "error", "message": "bad term"}
        holder = str(body.get("holder") or "")
        with self._state_lock:
            if term < self.term or (
                    term == self.term and self._holder
                    and holder != self._holder):
                # stale or competing claimant: the split-brain guard —
                # at equal terms the first holder we saw wins; anyone
                # else must take a HIGHER term to act
                return 409, {"status": "stale", "term": self.term,
                             "applied": self._applied}
            if self.leader and (term > self.term or holder
                                != self.node_nonce):
                # a higher-term leader exists: we were deposed while
                # running (pause/partition) — stop acting immediately
                self.step_down(term, reason="replicate-frame",
                               locked=True)
            self.term = max(self.term, term)
            self._holder = holder
            url = body.get("holder_url")
            if url:
                self._leader_url = str(url).rstrip("/")
            try:
                lease_ms = float(body.get("lease_ms") or 0)
            except (TypeError, ValueError):
                lease_ms = 0.0
            lease_s = lease_ms / 1e3 if lease_ms > 0 else self.lease_s
            self._lease_deadline = clock.now() + lease_s
        snap = body.get("snapshot")
        if isinstance(snap, dict):
            with self._apply_lock:
                stale = self._stale_for_apply(term, holder)
                if stale is not None:
                    return stale
                try:
                    seq = int(body.get("seq_start") or 1) - 1
                    self.store.load_tables(snap)
                    self.oplog.reset_to(seq)
                    with self._state_lock:
                        self._applied = seq
                    self.master.metrics.inc("repl_snapshots_loaded")
                    log.info("replication snapshot loaded at seq %d "
                             "(term %d)", seq, term)
                except Exception as e:
                    log.warning("replication snapshot load failed: %r",
                                e)
                    return 500, {"status": "error", "applied": -1,
                                 "term": self.term,
                                 "message": f"snapshot load failed: {e}"}
        ops = body.get("ops") or []
        try:
            seq_start = int(body.get("seq_start") or 0)
        except (TypeError, ValueError):
            return 400, {"status": "error", "message": "bad seq_start"}
        if ops:
            # one frame applies at a time: the watermark check and the
            # apply are one critical section, so a leader-retry
            # re-delivery racing the still-running first apply cannot
            # double-apply non-idempotent ops (attempts+1, INSERTs)
            with self._apply_lock:
                # re-validate under the apply lock: the lease may have
                # moved while this frame was in flight (our own
                # takeover, or a higher term) — admitting the old
                # leader's ops AFTER takeover recovery ran would flip
                # recovered rows back to unowned 'processing' and
                # silently strand them
                stale = self._stale_for_apply(term, holder)
                if stale is not None:
                    return stale
                with self._state_lock:
                    applied = self._applied
                if seq_start > applied + 1:
                    # gap (we missed frames): ask the leader to rewind
                    return {"status": "resync", "applied": applied,
                            "term": self.term}
                # drop the already-applied prefix (at-least-once
                # delivery after a leader retry must not double-apply
                # attempts+1)
                skip = applied + 1 - seq_start
                todo = ops[skip:] if skip > 0 else ops
                if todo:
                    try:
                        self.store.apply_ops(todo)
                    except Exception as e:
                        log.warning("replicated op apply failed: %r", e)
                        return 500, {"status": "error",
                                     "applied": self._applied,
                                     "term": self.term,
                                     "message": f"apply failed: {e}"}
                    last = seq_start + len(ops) - 1
                    self.oplog.append_at(
                        [(seq_start + skip + i, sql, args)
                         for i, (sql, args) in enumerate(todo)])
                    with self._state_lock:
                        self._applied = max(self._applied, last)
                    self.master.metrics.inc("repl_ops_applied",
                                            len(todo))
        with self._state_lock:
            if not self.leader and term == self.term and \
                    holder == self._holder:
                # refresh the lease AFTER the apply too: a snapshot
                # load can legitimately outlast the lease (its read
                # timeout is deliberately generous), and the leader's
                # single shipper thread was blocked on this very POST
                # the whole time — expiring the deadline at the
                # admission-time stamp would promote this standby the
                # instant the apply commits, deposing a healthy leader
                # (and then flapping forever on every resync)
                self._lease_deadline = clock.now() + lease_s
            return {"status": "success", "applied": self._applied,
                    "term": self.term}

    def _stale_for_apply(self, term: int, holder: str):
        """Re-check, under the apply lock, that the frame's (term,
        holder) is still the lease this node recognizes. The admission
        check at the top of :meth:`handle_replicate` ran under the
        state lock and then RELEASED it — by the time the frame holds
        the apply lock, this node may have taken over itself or
        observed a higher-term leader. Returns the 409 response to
        relay when stale, else None."""
        with self._state_lock:
            if (self.leader or term < self.term
                    or (term == self.term and self._holder
                        and holder != self._holder)):
                return 409, {"status": "stale", "term": self.term,
                             "applied": self._applied}
        return None

    # ---- takeover / step-down -----------------------------------------

    def _takeover(self):
        """Standby -> leader at term+1: assert the lease, persist the
        term (a replicated write — the new op-log's first entry is the
        leadership record itself), adopt the cluster tag nonce, requeue
        everything the dead leader held in flight, and wake dispatch."""
        # the apply lock first: an in-flight frame that already passed
        # _stale_for_apply (a snapshot load can outlive a lease — its
        # read timeout is deliberately generous) must COMMIT before the
        # promotion flips `leader`, or the old leader's bytes would land
        # on top of this takeover's recovery and strand recovered rows
        # back in ownerless 'processing'. Frames arriving after the
        # flip re-check _stale_for_apply under this same lock and 409.
        with self._apply_lock, self._state_lock:
            if self.leader:
                return
            if clock.now() <= self._lease_deadline:
                # a heartbeat frame renewed the lease while the monitor
                # thread was waiting on this lock: the leader is alive
                # after all — do NOT depose it
                return
            self.term += 1
            self.leader = True
            self._holder = self.node_nonce
            self._leader_url = self.self_url
            for p in self._peers.values():
                p.synced = False
                p.cursor = p.acked = 0
            # our mirrored op-log numbering continues where the dead
            # leader's stream stopped
            self.oplog.reset_to(max(self.oplog.seq(), self._applied))
        m = self.master
        m.on_promote()
        events.emit("lease-acquired", term=self.term,
                    holder=self.node_nonce, prev_applied=self._applied)
        self.store.set_meta("ha_term", str(self.term))
        try:
            n = self.store.recover_stale_processing(
                max_attempts=m.max_attempts())
        except Exception as e:
            log.warning("takeover recovery failed: %r", e)
            n = -1
        events.emit("takeover-recovery", term=self.term, recovered=n)
        m.metrics.inc("ha_takeovers")
        log.warning("lease TAKEOVER: this master now leads at term %d "
                    "(%s requests recovered)", self.term, n)
        self._ship_wake.set()

    def step_down(self, new_term: int, reason: str = "",
                  locked: bool = False):
        """Leader -> standby on observing a higher (or competing
        winning) term: stop scheduling immediately, mark our store
        diverged (the next leader resyncs us with a snapshot), and
        journal the demotion to the in-memory ring — our durable
        journal is no longer authoritative."""
        if not locked:
            with self._state_lock:
                return self.step_down(new_term, reason, locked=True)
        was = self.leader
        self.leader = False
        self.term = max(self.term, int(new_term))
        try:
            # a restart (even with --ha-leader) must assert ABOVE the
            # term that deposed us, not re-contest it
            self.store.set_meta("ha_term", str(self.term),
                                replicate=False)
        except Exception as e:
            log.warning("could not persist observed term: %r", e)
        # acked-but-unreplicated tail writes may exist: declare
        # divergence so the new leader's first frame snapshots us
        self._applied = -1
        self._lease_deadline = clock.now() + self.lease_s * (
            2 + self._rank())
        with self._ack_cv:
            # wake barrier waiters so they observe the demotion at
            # once instead of sleeping out their full timeout window
            self._ack_cv.notify_all()
        if was:
            self.master.on_demote()
            events.emit("lease-lost", term=self.term, reason=reason,
                        holder=self._holder)
            self.master.metrics.inc("ha_lease_lost")
            log.warning("lease LOST (%s): stepping down at term %d",
                        reason, self.term)

    def observe_stale(self, worker_term: int, node_id=None):
        """A worker 409ed our dispatch with a newer term: we lost the
        lease while acting. Journal the rejection (to the ring — the
        new leader's journal is the durable one) and step down."""
        events.emit("stale-term-rejected", term=self.term,
                    observed_term=int(worker_term), node_id=node_id)
        self.master.metrics.inc("repl_stale_term_rejections")
        self.step_down(int(worker_term), reason="worker-fence")

    # ---- introspection (GET /api/ha) ----------------------------------

    def status(self) -> dict:
        with self._state_lock:
            peers = [{
                "url": p.url, "acked_seq": p.acked,
                "synced": p.synced, "last_error": p.last_error,
                "last_ack_age_s": (round(clock.now() - p.last_ack_at, 3)
                                   if p.last_ack_at else None),
            } for p in self._peers.values()]
            return {
                "enabled": self.enabled, "is_leader": self.leader,
                "term": self.term, "nonce": self.node_nonce,
                "holder": self._holder,
                "leader_url": self.leader_url(),
                "lease_ms": self.lease_s * 1e3,
                "barrier": self.barrier_enabled,
                "log_seq": self.oplog.seq(),
                "applied_seq": self._applied,
                "peers": peers,
            }
