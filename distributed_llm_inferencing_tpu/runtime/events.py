"""Cluster flight recorder: a typed, durable journal of control-plane
decisions.

PRs 8 and 12 made the cluster *decide* things — transfer-vs-recompute
plans, breaker trips, role flips, live migrations — but each decision
survived only as a transient ``log.warning`` line or a bare counter.
After an incident there was no way to prove what the recovery actually
did (FailSafe, arxiv 2511.14116, is only trustworthy with a
post-incident record), and the ROADMAP item-2 planner needs decision
history that outlives the master process.

This module is the declared half plus the journal:

- :data:`EVENT_TYPES` — every event type the cluster may emit, declared
  as data (name, severity, doc, fields) in the ``runtime/lifecycle.py``
  style. ``tools/dlilint/check_events.py`` enforces three-way parity:
  every ``events.emit("<type>", ...)`` site names a declared type, every
  declared type has an emit site, and the generated appendix in
  ``docs/observability.md`` matches this registry byte-for-byte
  (regenerate with ``python -m tools.dlilint --write-event-table``).
- :class:`EventJournal` — a bounded in-memory ring of recent events plus
  durable persistence through the ``Store`` group-commit path (the new
  ``events`` table, retention-capped), served at ``GET /api/events`` and
  merged into ``GET /api/requests/<id>/journey``.
- module-level :func:`emit` — the fire-and-forget helper decision sites
  call. It routes to the installed journal (the master installs its own
  at construction) and NEVER raises: a journaling hiccup must not turn
  a servable request into a failure.

Like ``lifecycle.py``, the registry part is pure data + string
rendering, importable by the dlilint checker without pulling in sqlite
or jax (the journal half leans only on ``utils.locks`` + stdlib).
"""

from __future__ import annotations

import collections
import json
import logging
import os
from typing import Dict, NamedTuple, Optional, Tuple

from distributed_llm_inferencing_tpu.utils import clock, locks

log = logging.getLogger("dli_tpu.events")

SEVERITIES = ("info", "warning", "error")

# Markers delimiting the generated appendix in docs/observability.md.
DOC_BEGIN = ("<!-- BEGIN GENERATED EVENT TABLE "
             "(python -m tools.dlilint --write-event-table) -->")
DOC_END = "<!-- END GENERATED EVENT TABLE -->"
DOC_PATH = os.path.join("docs", "observability.md")


class EventType(NamedTuple):
    name: str                 # stable kebab-case id, the wire `type`
    severity: str             # default severity: info | warning | error
    doc: str                  # one-line meaning, rendered into the docs
    fields: Tuple[str, ...]   # declared `data` keys (documented; a site
    #                           may emit a subset when inputs are absent)


EVENT_TYPES = (
    # ---- fleet membership / health -----------------------------------
    EventType(
        "node-added", "info",
        "A worker registered (or re-registered) with the master.",
        ("name", "host", "port", "readded")),
    EventType(
        "node-removed", "info",
        "A worker was removed from the registry (operator action).",
        ("name",)),
    EventType(
        "node-drain", "info",
        "A worker's self-declared draining flag changed — planned "
        "shutdown starting or finishing.",
        ("draining",)),
    EventType(
        "breaker-open", "warning",
        "A node's circuit breaker tripped OPEN (strike threshold "
        "reached, or a half-open probe failed): the node is "
        "unschedulable until a health probe half-opens it.",
        ("strikes", "prev_state")),
    EventType(
        "breaker-half-open", "info",
        "An open node answered a health probe: schedulable again as a "
        "single-probe candidate until a real request closes the "
        "breaker.", ()),
    EventType(
        "breaker-closed", "info",
        "A half-open probe request succeeded (or strikes cleared): the "
        "node is fully schedulable again.", ()),
    EventType(
        "node-refresh-failed", "warning",
        "A post-load node snapshot refresh failed — dispatch proceeded "
        "on the stale snapshot (was a log.warning-only path before the "
        "flight recorder).", ("error",)),
    # ---- scheduling / dispatch ---------------------------------------
    EventType(
        "request-submitted", "info",
        "A request entered the queue. The event's own ts is the "
        "arrival timestamp and the data carries the workload shape "
        "(prompt length, token budget), so the journal doubles as a "
        "replayable arrival trace: tools/dlisim reconstructs a real "
        "run's workload from exactly these rows (a debug bundle is "
        "sim-replayable because collect_debug_bundle.sh exports them).",
        ("model", "prompt_chars", "max_new_tokens", "max_length",
         "slo_class", "tenant", "adapter")),
    EventType(
        "admission-rejected", "warning",
        "The overload front door refused a submit — degradation-ladder "
        "class shed, pending-queue cap, or the tenant's token bucket — "
        "with an honest 429 + Retry-After. One event per refusal: a "
        "shed is never a silent drop (docs/robustness.md \"Overload "
        "control\").",
        ("tenant", "slo_class", "reason", "retry_after_s", "level")),
    EventType(
        "overload-level", "warning",
        "The overload ladder moved one rung (up under pressure, down "
        "on recovery), with the gauge values that justified the "
        "transition — the postmortem reconstructs the whole brownout "
        "walk from these rows alone.",
        ("level", "prev_level", "direction", "burn_rate",
         "queue_depth")),
    EventType(
        "request-park", "warning",
        "No schedulable node for a claimed request: parked behind a "
        "backoff delay, or terminally failed when the attempt budget "
        "was already burned.",
        ("attempts", "terminal", "delay_s")),
    EventType(
        "request-requeued", "warning",
        "A dispatch attempt failed and the request re-entered the "
        "queue: the failed node is excluded (or the retry stays pinned "
        "on a sticky timeout) and the next attempt parks behind "
        "backoff.",
        ("error", "attempts", "sticky", "excluded", "delay_s")),
    EventType(
        "disagg-plan", "info",
        "A transfer-vs-recompute verdict for a disaggregation-eligible "
        "request, carrying the actual inputs that decided it "
        "(estimated prompt tokens, warmest advertised prefix, learned "
        "prefill EWMA, pool sizes).",
        ("verdict", "est_tokens", "warm_tokens",
         "prefill_ewma_ms_per_tok", "prefill_pool", "decode_pool",
         "prefill_node", "decode_node")),
    EventType(
        "disagg-prefill-failed", "warning",
        "Phase 1 of a disaggregated dispatch failed on the prefill "
        "node: the request degraded to plain recompute dispatch on the "
        "decode node (was a log.warning-only path).",
        ("error", "status")),
    # ---- live migration / elasticity ---------------------------------
    EventType(
        "migrate-out", "info",
        "A worker answered an in-flight dispatch with a 303 handoff: "
        "the resume record (stream cursor) was persisted and the "
        "request re-queued with a kv_source hint back at the source "
        "arena.", ("resume_tokens",)),
    EventType(
        "migrate-resume", "info",
        "A dispatch attempt carried a migrated request's resume record "
        "to the chosen node (one event per attempt — a failed-over "
        "resume emits again on the next node; the terminal lifecycle "
        "entry names where the stream actually finished).",
        ("resume_tokens", "attempt")),
    EventType(
        "migrate-anomaly", "warning",
        "A /migrate_out RPC did not hand off cleanly: transport "
        "failure (retried next sweep) or a 409 completion race "
        "(settled, nothing to migrate) — was a log-only path.",
        ("status", "error")),
    EventType(
        "role-flip", "info",
        "The elastic rebalancer flipped a worker between the "
        "prefill/decode pools (or re-created an emptied prefill pool "
        "on disagg demand).",
        ("role", "prev_role", "reason")),
    EventType(
        "plan-chosen", "info",
        "The auto-parallelism planner (parallel/planner.py) chose a "
        "deployment plan: mesh shape + prefill/decode role split, "
        "ranked over the enumerated candidates by the profile-fed "
        "cost model. The data carries the full decision inputs — "
        "fitted node classes, workload shape, learned rates — so the "
        "choice is reconstructable from the journal alone.",
        ("model", "plan_id", "mesh", "role_split", "prefill_nodes",
         "candidates", "scored", "score", "classes",
         "est_prompt_tokens", "est_decode_tokens",
         "prefill_ewma_ms_per_tok", "decode_tokens_per_weight_pass",
         "slo_e2e_ms", "reason")),
    EventType(
        "rebalance-divergence", "info",
        "A rebalancer sweep found sustained pool-utilization "
        "divergence past the configured ratio, with the pool means "
        "that justified the (attempted) flip.",
        ("prefill_mean", "decode_mean", "ratio", "action")),
    # ---- SLO / telemetry / store -------------------------------------
    EventType(
        "slo-burn", "warning",
        "The fast-window error-budget burn rate crossed the alerting "
        "threshold (1.0 = consuming exactly the budget) — in either "
        "direction.", ("burn_rate", "direction")),
    EventType(
        "store-flush-failed", "error",
        "A group-commit store flush failed (disk full / I/O error): "
        "the batch was re-buffered in order and the flusher retries; "
        "barrier waiters stay blocked until a flush succeeds.",
        ("error", "ops")),
    EventType(
        "fault-armed", "warning",
        "A fault-injection schedule was armed on a service (env or "
        "runtime admin API) — chaos experiments are part of the "
        "post-incident record too.",
        ("service", "count", "points")),
    # ---- replicated control plane (runtime/replication.py) -----------
    EventType(
        "lease-acquired", "warning",
        "A standby's lease deadline expired and it took the leader "
        "lease at term+1: this master now schedules/dispatches (the "
        "takeover-recovery event that follows carries the requeue "
        "count).", ("term", "holder", "prev_applied")),
    EventType(
        "lease-lost", "warning",
        "A leading master observed a higher (or winning) term — via a "
        "peer frame, a peer ack, or a worker's stale-term fence — and "
        "stepped down: it stops scheduling immediately and its store "
        "is resynced from the new leader's snapshot.",
        ("term", "reason", "holder")),
    EventType(
        "takeover-recovery", "warning",
        "The crash-recovery requeue run at lease takeover: every "
        "request the dead leader held in 'processing' re-entered the "
        "queue (attempt counted; poison requests at the budget fail "
        "instead).", ("term", "recovered")),
    EventType(
        "replication-lag", "warning",
        "Standby acks fell behind the op-log head past the warn "
        "threshold — or a durability-barrier wait timed out and the "
        "write degraded to leader-only durability. The info-severity "
        "twin marks recovery (acks caught back up).",
        ("ops_behind", "lag_ms", "acked_seq", "log_seq",
         "barrier_timeout")),
    EventType(
        "stale-term-rejected", "warning",
        "A worker fenced this master's dispatch with 409 + "
        "X-DLI-Stale-Term: a newer term holds the lease. Emitted by "
        "the deposed master (to its in-memory ring) as it steps down "
        "— the paused-then-revived-leader trail a postmortem needs.",
        ("term", "observed_term")),
    # ---- multi-LoRA adapter serving (models/lora.py) ------------------
    EventType(
        "adapter-loaded", "info",
        "A LoRA adapter became host-resident on a worker — an explicit "
        "operator /load_adapter, or the master's lazy dispatch-time "
        "load for a request naming an adapter the chosen node lacked.",
        ("adapter", "model", "rank", "nbytes", "lazy")),
    EventType(
        "adapter-evicted", "info",
        "The bounded host adapter store evicted an idle adapter (LRU "
        "by bytes) to make room for a newly loaded one — the evicted "
        "name reloads lazily on its next request.",
        ("adapter", "model", "evicted_for")),
    EventType(
        "adapter-load-failed", "error",
        "An adapter load was refused (bad source, shape mismatch "
        "against the base model, store full of pinned adapters): the "
        "request path fails rather than silently serving base "
        "weights.", ("adapter", "model", "error")),
)

_BY_NAME: Dict[str, EventType] = {t.name: t for t in EVENT_TYPES}


def _check_registry() -> None:
    """The registry must be self-consistent before anything trusts it."""
    assert len(_BY_NAME) == len(EVENT_TYPES), "duplicate event type names"
    for t in EVENT_TYPES:
        assert t.name == t.name.lower() and " " not in t.name, t.name
        assert t.severity in SEVERITIES, t.name
        assert t.doc.strip(), f"{t.name}: undocumented event type"
        assert isinstance(t.fields, tuple), t.name
        assert len(t.fields) == len(set(t.fields)), t.name


_check_registry()


def registry() -> Dict[str, EventType]:
    """Name -> EventType for the whole declared set."""
    return dict(_BY_NAME)


def names() -> frozenset:
    return frozenset(_BY_NAME)


def get(name: str) -> EventType:
    return _BY_NAME[name]


class EventJournal:
    """Bounded ring of recent events + durable persistence through the
    master's :class:`~runtime.state.Store` group-commit path.

    Every emit lands in the in-memory ring immediately and (when a
    store is attached) queues one INSERT into the ``events`` table
    through the same write-behind buffer the request-status writes use
    — journaling rides the group commit, it never adds its own
    transaction to the hot path. Retention: the table is pruned back to
    ``retain`` rows every ``_PRUNE_EVERY`` persisted events, so a
    long-lived master's journal is a sliding window, not an unbounded
    log."""

    _PRUNE_EVERY = 512

    def __init__(self, store=None, ring: Optional[int] = None,
                 retain: Optional[int] = None):
        if ring is None:
            ring = int(os.environ.get("DLI_EVENTS_RING", 2048))
        if retain is None:
            retain = int(os.environ.get("DLI_EVENTS_RETAIN", 20000))
        self._store = store
        # Replicated control plane (runtime/replication.py): a STANDBY
        # master journals to its in-memory ring only — the durable
        # journal rows arrive from the leader through op-log
        # replication, and a replica writing its own would fork the
        # replicated autoincrement stream. Flipped at promote/demote.
        self.durable = True
        self._retain = max(1, int(retain))
        self._lock = locks.lock("events.ring")
        self._ring: collections.deque = collections.deque(
            maxlen=max(1, int(ring)))
        self._emitted = 0
        self._since_prune = 0

    def emit(self, etype: str, *, node_id=None, request_id=None,
             trace_id: Optional[str] = None, severity: Optional[str] = None,
             t: Optional[float] = None, **data) -> dict:
        """Record one event. ``etype`` MUST be declared in
        :data:`EVENT_TYPES` (an undeclared type raises — the static
        checker keeps call sites honest, this keeps dynamic ones);
        ``severity`` overrides the declared default (a site may escalate,
        e.g. a routine verdict observed during an incident)."""
        decl = _BY_NAME.get(etype)
        if decl is None:
            raise ValueError(f"undeclared event type {etype!r} "
                             "(declare it in runtime/events.py)")
        sev = severity or decl.severity
        if sev not in SEVERITIES:
            raise ValueError(f"unknown severity {sev!r}")
        ev = {
            "ts": clock.now() if t is None else float(t),
            "type": etype,
            "severity": sev,
            "node_id": int(node_id) if node_id is not None else None,
            "request_id": (int(request_id) if request_id is not None
                           else None),
            "trace_id": trace_id,
            "data": {k: v for k, v in data.items() if v is not None},
        }
        with self._lock:
            self._ring.append(ev)
            self._emitted += 1
            self._since_prune += 1
            prune = self._since_prune >= self._PRUNE_EVERY
            if prune:
                self._since_prune = 0
        if self._store is not None and self.durable:
            # one buffered INSERT through the group-commit write-behind
            # path (barrier=False: durability within a flush cycle, no
            # hot-path commit wait); the periodic prune rides the same
            # buffer, so the retention cap costs no extra transaction
            self._store.append_event(
                ev["ts"], etype, sev, ev["node_id"], ev["request_id"],
                trace_id, json.dumps(ev["data"]))
            if prune:
                self._store.prune_events(self._retain)
        return ev

    def tail(self, n: int = 100) -> list:
        """Most recent events from the in-memory ring (newest last)."""
        with self._lock:
            evs = list(self._ring)
        return evs[-max(0, int(n)):]

    def counts(self) -> dict:
        with self._lock:
            return {"emitted": self._emitted, "ring": len(self._ring),
                    "ring_cap": self._ring.maxlen,
                    "retain": self._retain}


# ---- module-level emit: the decision sites' entry point ---------------
#
# The master installs its journal here at construction; decision sites
# anywhere in the process (master loops, state.py's flusher, the fault
# injector) call ``events.emit(...)`` without plumbing a journal handle
# through every layer. Installed journal wins; with none installed
# (worker-only processes, unit tests) the helper is a no-op.

_GLOBAL: Optional[EventJournal] = None


def set_journal(journal: Optional[EventJournal]) -> None:
    global _GLOBAL
    _GLOBAL = journal


def clear_journal(journal: EventJournal) -> None:
    """Uninstall ``journal`` if it is the installed one (a stopped
    master must not unhook a newer master's journal — benches run
    several in one process)."""
    global _GLOBAL
    if _GLOBAL is journal:
        _GLOBAL = None


def get_journal() -> Optional[EventJournal]:
    return _GLOBAL


def emit(etype: str, **kw) -> Optional[dict]:
    """Fire-and-forget emit to the installed journal. Never raises:
    the flight recorder observes the control plane, it must not be able
    to fail it."""
    j = _GLOBAL
    if j is None:
        return None
    try:
        return j.emit(etype, **kw)
    except Exception as e:
        log.warning("event emit %r failed: %r", etype, e)
        return None


# ---- generated docs appendix ------------------------------------------

def markdown_table() -> str:
    """One row per declared event type, as embedded in
    docs/observability.md."""
    rows = ["| Event type | Severity | Data fields | Meaning |",
            "| --- | --- | --- | --- |"]
    for t in EVENT_TYPES:
        fields = ", ".join(f"`{f}`" for f in t.fields) or "—"
        rows.append(f"| `{t.name}` | {t.severity} | {fields} | {t.doc} |")
    return "\n".join(rows)


def generated_block() -> str:
    """Marker-delimited block for docs/observability.md; the dlilint
    events checker fails when the committed block != this string."""
    return (f"{DOC_BEGIN}\n\n"
            "This table is generated from `runtime/events.py` — edit "
            "the declared registry,\nthen run `python -m tools.dlilint "
            "--write-event-table`. Hand edits here are\noverwritten "
            "and fail the `events` checker.\n\n"
            f"{markdown_table()}\n\n{DOC_END}")
