"""Continuous batching over the paged KV cache.

The reference served one prompt per blocking HTTP request, fully serialized
per worker (1 gunicorn sync worker, reference: worker/Dockerfile:47,
worker/app.py:252-330). The engine (runtime/engine.py) batches only within
one ``generate`` call. This scheduler is the serving-native upgrade: a
fixed pool of decode *slots* advances every active request one token per
jitted step, admitting queued requests into freed slots mid-flight —
in-flight batching, so short and long generations share the chip without
head-of-line blocking.

Memory is paged (ops/paged_kvcache.py): which HBM blocks each sequence
owns is decided host-side by the native C++ allocator
(native/src/block_pool.cc), whose radix tree lets requests with a shared
prompt prefix reuse already-prefilled blocks — admission then prefills
only the tail (models/transformer.py paged_prefill_tail). Under memory
pressure the youngest slot is preempted back to the queue (its prefix
stays warm in the radix cache, so the re-run is mostly a cache hit).

Per-request sampling params ride the jitted decode step as data
(ops/sampling.py sample_batch), so one compiled program serves any mix of
greedy/temperature/top-k/top-p requests.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llm_inferencing_tpu.models import transformer
from distributed_llm_inferencing_tpu.models.config import ModelConfig
from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.native import BlockPool
from distributed_llm_inferencing_tpu.ops.paged_kvcache import init_paged_cache
from distributed_llm_inferencing_tpu.ops.sampling import (
    SamplingParams, sample_batch)
from distributed_llm_inferencing_tpu.parallel import sharding as shd
from distributed_llm_inferencing_tpu.parallel.mesh import (
    MeshSpec, create_mesh, validate_spec)

TAIL_BUCKETS_X_BS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)  # × block_size
PREFIX_BUCKETS = (0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512)  # blocks


@dataclasses.dataclass
class BatchRequest:
    """One queued/active generation. The handle the caller waits on."""
    prompt: List[int]
    max_new_tokens: int
    sampling: SamplingParams
    eos_token_id: Optional[int] = None
    stream_cb: Optional[Callable[[int], None]] = None
    seed: int = 0    # output is a pure fn of (params, prompt, seed)
    # results
    tokens: List[int] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    # timing
    submitted_at: float = dataclasses.field(default_factory=time.time)
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    # internal scheduling state
    _blocks: List[int] = dataclasses.field(default_factory=list)
    _preemptions: int = 0
    _cancelled: bool = False

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError("generation still running")
        if self.error:
            raise RuntimeError(self.error)
        return self.tokens

    def cancel(self):
        """Ask the scheduler to drop this request (frees its slot/blocks at
        the next step; already-generated tokens are kept)."""
        self._cancelled = True

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return (self.first_token_at - self.submitted_at) * 1e3


class ContinuousBatcher:
    """Slot-based continuous batching scheduler.

    One jitted program per step; the model may be mesh-sharded (tensor /
    expert parallel) — params and the paged cache carry NamedShardings and
    GSPMD partitions the step's matmuls/attention over ICI. Batch-dim
    parallelism (dp), pipeline stages (pp), and sequence sharding (sp) are
    rejected: the slot scheduler owns the batch dimension, and its
    per-step host round trip is incompatible with stage/sequence pipelining.

    Drive it either with an owned background thread (``start()``/``stop()``)
    or synchronously via ``step()`` (tests, custom loops).
    """

    def __init__(self, cfg: ModelConfig, params=None, *,
                 num_blocks: int = 512, block_size: int = 16,
                 slots: int = 8, max_seq: Optional[int] = None,
                 seed: int = 0, force_python_pool: bool = False,
                 mesh_spec: Optional[MeshSpec] = None):
        self.mesh_spec = mesh_spec or MeshSpec()
        for ax in ("dp", "pp", "sp"):
            if getattr(self.mesh_spec, ax) > 1:
                raise ValueError(
                    f"batched serving shards tensors only (tp/ep); "
                    f"{ax}={getattr(self.mesh_spec, ax)} unsupported")
        self.cfg = cfg = cfg.replace(
            attn_backend=_backend(cfg, self.mesh_spec.num_devices))
        validate_spec(self.mesh_spec, cfg)
        self.mesh = create_mesh(self.mesh_spec)
        self.block_size = block_size
        self.slots = slots
        self.max_seq = min(max_seq or cfg.max_position_embeddings,
                           cfg.max_position_embeddings)
        self.max_blocks = -(-self.max_seq // block_size)
        if params is None:
            params = init_params(cfg, jax.random.PRNGKey(seed))
        else:
            from distributed_llm_inferencing_tpu.ops.quant import maybe_quantize
            params = maybe_quantize(params, cfg)
        with self.mesh:
            self.params = shd.shard_params(params, self.mesh, cfg,
                                           self.mesh_spec)

        # +1: block 0 is the reserved dummy every inactive table entry
        # points at, so it never carries real KV
        self.pool = BlockPool(num_blocks + 1, block_size,
                              force_python=force_python_pool)
        [self._dummy] = self.pool.alloc(1)
        self.paged = jax.device_put(
            init_paged_cache(cfg, num_blocks + 1, block_size),
            shd.named(self.mesh, shd.paged_cache_specs(cfg, self.mesh_spec)))
        self.block_tables = np.full((slots, self.max_blocks), self._dummy,
                                    np.int32)
        self.context_lens = np.zeros((slots,), np.int32)
        self.active: List[Optional[BatchRequest]] = [None] * slots
        self._admit_order: collections.deque = collections.deque()  # slot ids

        self.queue: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._step_count = 0
        self._tokens_out = 0

        self._prefill_fns = {}
        self._decode_fn = None
        self._sample1 = None

        # Multi-host seam (runtime/multihost.py): when set, every device
        # program this scheduler launches is routed through
        # ``program_hook(kind, payload, run)`` — the lockstep leader
        # broadcasts (kind, payload) to follower hosts, which ``replay()``
        # the identical program, then calls ``run()`` in sequence order.
        # The *scheduling decisions* stay leader-local; only their compiled
        # consequences are replicated, so followers need no pool/queue.
        self.program_hook = None

    # ---- public API ---------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 100,
               sampling: Optional[SamplingParams] = None,
               eos_token_id: Optional[int] = None,
               stream_cb: Optional[Callable[[int], None]] = None,
               seed: Optional[int] = None) -> BatchRequest:
        if not prompt:
            raise ValueError("empty prompt")
        if seed is None:
            seed = time.time_ns() % (1 << 31)
        req = BatchRequest(prompt=list(map(int, prompt)),
                           max_new_tokens=int(max_new_tokens),
                           sampling=sampling or SamplingParams(),
                           eos_token_id=eos_token_id, stream_cb=stream_cb,
                           seed=int(seed))
        if len(req.prompt) + req.max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt ({len(req.prompt)}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds max_seq {self.max_seq}")
        with self._lock:
            self.queue.append(req)
        self._work.set()
        return req

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="batcher")
            self._thread.start()

    def stop(self):
        """Stop the loop and fail every in-flight/queued request, so no
        client blocks until its timeout on an unloading worker."""
        self._stop.set()
        self._work.set()
        if self._thread:
            self._thread.join(timeout=30)
            self._thread = None
        for slot in range(self.slots):
            req = self.active[slot]
            if req is not None:
                req.error = req.error or "scheduler stopped"
                self._finish_slot(slot)
        with self._lock:
            drained = list(self.queue)
            self.queue.clear()
        for req in drained:
            req.error = "scheduler stopped"
            req.done.set()

    def stats(self) -> dict:
        return {
            "slots": self.slots,
            "mesh": self.mesh_spec.axis_sizes(),
            "active": sum(a is not None for a in self.active),
            "queued": len(self.queue),
            "steps": self._step_count,
            "tokens_out": self._tokens_out,
            "block_size": self.block_size,
            "blocks_free": self.pool.free_count(),
            "pool": self.pool.stats(),
        }

    # ---- compiled steps ----------------------------------------------

    def _prefill_jit(self, t: int, pb: int):
        fn = self._prefill_fns.get((t, pb))
        if fn is None:
            cfg = self.cfg
            fn = jax.jit(
                lambda p, toks, tl, tb, pfb, pfl, paged:
                transformer.paged_prefill_tail(p, cfg, toks, tl, tb, pfb,
                                               pfl, paged),
                donate_argnums=(6,))
            self._prefill_fns[(t, pb)] = fn
        return fn

    def _decode_jit(self):
        if self._decode_fn is None:
            cfg = self.cfg

            def step(params, tokens, paged, bt, cl, seeds, steps, temps, tks,
                     tps, ds):
                logits, paged = transformer.paged_decode_step(
                    params, cfg, tokens, paged, bt, cl)
                nxt = sample_batch(logits, seeds, steps, temps, tks, tps, ds)
                return nxt, paged

            self._decode_fn = jax.jit(step, donate_argnums=(2,))
        return self._decode_fn

    # ---- program launch (shared by the scheduler and lockstep replay) --

    def _run_admit(self, a: dict) -> int:
        """Launch the admission programs (tail prefill + first-token
        sample) from a JSON-safe arg dict. Pure device-program execution:
        no scheduler state is read, so a follower replaying the leader's
        args evolves its cache shard bit-identically."""
        toks = np.asarray([a["toks"]], np.int32)
        pfb = np.asarray([a["pfb"]], np.int32)
        fn = self._prefill_jit(toks.shape[1], pfb.shape[1])
        with self.mesh:
            last, self.paged = fn(
                self.params, jnp.asarray(toks),
                jnp.asarray([a["tail_len"]], jnp.int32),
                jnp.asarray(a["tail_alloc"], jnp.int32),
                jnp.asarray(pfb), jnp.asarray([a["cached"]], jnp.int32),
                self.paged)
            if self._sample1 is None:
                self._sample1 = jax.jit(sample_batch)
            return int(self._sample1(
                last,
                jnp.asarray([a["seed"]], jnp.int32),
                jnp.asarray([a["step"]], jnp.int32),
                jnp.asarray([a["temperature"]], jnp.float32),
                jnp.asarray([a["top_k"]], jnp.int32),
                jnp.asarray([a["top_p"]], jnp.float32),
                jnp.asarray([a["do_sample"]]))[0])

    def _run_decode(self, a: dict) -> np.ndarray:
        """Launch one decode step's program from a JSON-safe arg dict."""
        fn = self._decode_jit()
        with self.mesh:
            nxt, self.paged = fn(
                self.params, jnp.asarray(a["tokens"], jnp.int32), self.paged,
                jnp.asarray(a["bt"], jnp.int32),
                jnp.asarray(a["cl"], jnp.int32),
                jnp.asarray(a["seeds"], jnp.int32),
                jnp.asarray(a["steps"], jnp.int32),
                jnp.asarray(a["temps"], jnp.float32),
                jnp.asarray(a["tks"], jnp.int32),
                jnp.asarray(a["tps"], jnp.float32),
                jnp.asarray(a["ds"], bool))
            return np.asarray(nxt)   # ONE host sync per step for all slots

    def replay(self, kind: str, args: dict):
        """Re-execute a program the lockstep leader broadcast. SPMD
        correctness requires every host to launch identical programs in
        identical order — the caller (LockstepFollower) provides the
        ordering; identical args provide the identity."""
        if kind == "admit":
            self._run_admit(args)
        elif kind == "decode":
            self._run_decode(args)
        else:
            raise ValueError(f"unknown batcher program kind {kind!r}")

    # ---- scheduling ---------------------------------------------------

    def _bucket_tail(self, n: int) -> int:
        for m in TAIL_BUCKETS_X_BS:
            if n <= m * self.block_size:
                return min(m * self.block_size,
                           self.max_blocks * self.block_size)
        raise ValueError(f"tail of {n} tokens exceeds buckets")

    def _bucket_prefix(self, nb: int) -> int:
        for m in PREFIX_BUCKETS:
            if nb <= m:
                return min(m, self.max_blocks) if m else 0
        raise ValueError(f"prefix of {nb} blocks exceeds buckets")

    def _admit_one(self, req: BatchRequest, slot: int) -> bool:
        """Prefill req into `slot`. False if blocks are unavailable.

        For a preempted request the already-generated tokens are part of
        the prefill (generation resumes where it left off — streamed
        tokens are never re-emitted).
        """
        bs = self.block_size
        prompt = req.prompt + req.tokens
        n = len(prompt)
        # Leave >=1 token for the tail: prefill must produce the last
        # token's logits (a fully-cached prompt would have nothing to run).
        prefix_blocks, cached = self.pool.match_prefix(prompt[:n - 1])
        tail_len = n - cached
        t = self._bucket_tail(tail_len)
        tail_alloc = self.pool.alloc(t // bs)
        if tail_alloc is None:
            self.pool.release(prefix_blocks)
            return False
        tail_real = tail_alloc[: -(-tail_len // bs)]
        tail_extra = tail_alloc[len(tail_real):]

        pb = self._bucket_prefix(len(prefix_blocks))
        pfb = np.full((1, max(pb, 1)), self._dummy, np.int32)
        pfb[0, :len(prefix_blocks)] = prefix_blocks
        toks = np.zeros((1, t), np.int32)
        toks[0, :tail_len] = prompt[cached:]

        sp = req.sampling
        admit_args = {
            "toks": toks[0].tolist(), "tail_len": int(tail_len),
            "tail_alloc": [int(b) for b in tail_alloc],
            "pfb": pfb[0].tolist(), "cached": int(cached),
            "seed": int(req.seed), "step": len(req.tokens),
            "temperature": float(sp.temperature), "top_k": int(sp.top_k),
            "top_p": float(sp.top_p), "do_sample": bool(sp.do_sample),
        }
        t0 = time.perf_counter()
        if self.program_hook is not None:
            first = self.program_hook("admit", admit_args,
                                      lambda: self._run_admit(admit_args))
        else:
            first = self._run_admit(admit_args)
        self.pool.release(tail_extra)   # padding blocks beyond the real tail

        # register the prompt's full blocks in the radix cache
        n_full = n // bs
        skip = cached // bs
        if n_full > skip:
            self.pool.insert_prefix(prompt[:n_full * bs],
                                    tail_real[:n_full - skip], skip)

        req._blocks = prefix_blocks + tail_real
        self.block_tables[slot, :] = self._dummy
        owned = prefix_blocks + tail_real
        self.block_tables[slot, :len(owned)] = owned
        self.context_lens[slot] = n
        self.active[slot] = req
        self._admit_order.append(slot)
        if req.first_token_at is None:
            req.first_token_at = time.time()
        self._emit(req, first)
        if req.done.is_set() or len(req.tokens) >= req.max_new_tokens:
            self._finish_slot(slot)
        return True

    def _emit(self, req: BatchRequest, token: int):
        """Append a sampled token; mark done on eos (eos not kept)."""
        if req.eos_token_id is not None and token == req.eos_token_id:
            self._finish_req(req)
            return
        req.tokens.append(token)
        self._tokens_out += 1
        if req.stream_cb:
            try:
                req.stream_cb(token)
            except Exception:
                pass

    def _finish_req(self, req: BatchRequest):
        self.pool.release(req._blocks)
        req._blocks = []
        req.finished_at = time.time()
        req.done.set()

    def _finish_slot(self, slot: int):
        req = self.active[slot]
        self.active[slot] = None
        self.block_tables[slot, :] = self._dummy
        self.context_lens[slot] = 0
        if slot in self._admit_order:
            self._admit_order.remove(slot)
        if req is not None and not req.done.is_set():
            self._finish_req(req)

    def _preempt_youngest(self) -> bool:
        """Free the most recently admitted slot, requeueing its request."""
        if not self._admit_order:
            return False
        slot = self._admit_order.pop()
        req = self.active[slot]
        self.active[slot] = None
        self.block_tables[slot, :] = self._dummy
        self.context_lens[slot] = 0
        if req is not None:
            self.pool.release(req._blocks)
            req._blocks = []
            req._preemptions += 1
            if req._preemptions > 5:
                req.error = "preempted repeatedly: KV pool too small"
                req.done.set()
            else:
                # generated tokens are kept; re-admission prefills
                # prompt+tokens and resumes (see _admit_one)
                with self._lock:
                    self.queue.appendleft(req)
        return True

    def _ensure_growth(self, slot: int) -> bool:
        """Make sure the slot owns the block its next token writes into."""
        pos = int(self.context_lens[slot])
        bi = pos // self.block_size
        if bi >= self.max_blocks:
            return False
        if self.block_tables[slot, bi] != self._dummy:
            return True
        got = self.pool.alloc(1)
        if got is None:
            return False
        self.block_tables[slot, bi] = got[0]
        self.active[slot]._blocks.extend(got)
        return True

    # ---- the step -----------------------------------------------------

    def step(self) -> int:
        """Admit + one decode step. Returns number of active slots."""
        # drop cancelled slots first — frees their blocks for admission
        for slot in range(self.slots):
            req = self.active[slot]
            if req is not None and req._cancelled:
                req.error = req.error or "cancelled"
                self._finish_slot(slot)
        # admission into free slots
        while True:
            free = [i for i, a in enumerate(self.active) if a is None]
            if not free:
                break
            with self._lock:
                req = self.queue.popleft() if self.queue else None
            if req is None:
                break
            if req._cancelled:
                req.error = req.error or "cancelled"
                req.done.set()
                continue
            try:
                admitted = self._admit_one(req, free[0])
            except ValueError as e:
                req.error = str(e)
                req.done.set()
                continue
            if not admitted:
                # Free memory by preempting the youngest slot, then retry
                # this request FIRST next step (it goes in front of the
                # preempted one, or ping-pong would starve it).
                preempted = self._preempt_youngest()
                if not preempted and not self._admit_order:
                    # no active slots to free: this prompt can never fit
                    req.error = "KV block pool exhausted"
                    req.done.set()
                else:
                    with self._lock:
                        self.queue.appendleft(req)
                break

        active = [i for i, a in enumerate(self.active) if a is not None]
        if not active:
            return 0

        # growth blocks for sequences crossing a block boundary
        for slot in range(self.slots):
            while (self.active[slot] is not None
                   and not self._ensure_growth(slot)):
                # _preempt_youngest may free `slot` itself — the loop
                # condition re-checks before retrying
                if not self._preempt_youngest():
                    self.active[slot].error = "cannot grow KV allocation"
                    self._finish_slot(slot)
                    break
        active = [i for i, a in enumerate(self.active) if a is not None]
        if not active:
            return 0

        r = self.slots
        tokens = np.zeros((r,), np.int32)
        seeds = np.zeros((r,), np.int32)
        steps = np.zeros((r,), np.int32)
        temps = np.full((r,), 1.0, np.float32)
        tks = np.zeros((r,), np.int32)
        tps = np.ones((r,), np.float32)
        ds = np.zeros((r,), bool)
        for i in active:
            req = self.active[i]
            tokens[i] = req.tokens[-1]
            seeds[i] = req.seed
            steps[i] = len(req.tokens)
            temps[i] = req.sampling.temperature
            tks[i] = req.sampling.top_k
            tps[i] = req.sampling.top_p
            ds[i] = req.sampling.do_sample

        decode_args = {
            "tokens": tokens.tolist(), "bt": self.block_tables.tolist(),
            "cl": self.context_lens.tolist(), "seeds": seeds.tolist(),
            "steps": steps.tolist(), "temps": temps.tolist(),
            "tks": tks.tolist(), "tps": tps.tolist(), "ds": ds.tolist(),
        }
        if self.program_hook is not None:
            nxt = self.program_hook("decode", decode_args,
                                    lambda: self._run_decode(decode_args))
        else:
            nxt = self._run_decode(decode_args)
        self._step_count += 1

        for i in active:
            req = self.active[i]
            self.context_lens[i] += 1
            self._emit(req, int(nxt[i]))
            if req.done.is_set() or len(req.tokens) >= req.max_new_tokens:
                self._finish_slot(i)
        return len([a for a in self.active if a is not None])

    # ---- background loop ----------------------------------------------

    def _loop(self):
        while not self._stop.is_set():
            try:
                busy = self.step()
            except Exception as e:
                # e.g. the lockstep hook reporting a degraded slice: fail
                # every waiter fast instead of letting them block to their
                # timeouts against a dead scheduler
                for slot in range(self.slots):
                    if self.active[slot] is not None:
                        self.active[slot].error = f"scheduler error: {e}"
                        self._finish_slot(slot)
                with self._lock:
                    drained = list(self.queue)
                    self.queue.clear()
                for req in drained:
                    req.error = f"scheduler error: {e}"
                    req.done.set()
                self._stop.set()
                return
            if not busy and not self.queue:
                self._work.wait(timeout=0.05)
                self._work.clear()


def _backend(cfg: ModelConfig, num_devices: int = 1) -> str:
    from distributed_llm_inferencing_tpu.ops.attention import resolve_backend
    return resolve_backend(cfg.attn_backend, num_devices)
