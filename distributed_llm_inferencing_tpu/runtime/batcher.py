"""Continuous batching over the paged KV cache.

The reference served one prompt per blocking HTTP request, fully serialized
per worker (1 gunicorn sync worker, reference: worker/Dockerfile:47,
worker/app.py:252-330). The engine (runtime/engine.py) batches only within
one ``generate`` call. This scheduler is the serving-native upgrade: a
fixed pool of decode *slots* advances every active request together,
admitting queued requests into freed slots mid-flight — in-flight
batching, so short and long generations share the chip without
head-of-line blocking.

Two dispatch-amortization levers keep the host off the critical path (a
host round trip to a tunnel-attached chip costs tens of ms):

- **Chunked decode**: each scheduler step launches ONE program that runs
  up to K decode iterations on device (models/transformer.py
  paged_decode_chunk) with per-slot budget/eos lifecycle as data. The
  host syncs once per K tokens, and admission/growth/preemption decisions
  happen at chunk boundaries (growth blocks for the whole chunk are
  pre-allocated before dispatch).
- **Wave admission**: queued requests are admitted in waves — one batched
  tail-prefill program per (tail, prefix) bucket with first-token
  sampling fused in, so a burst of N requests costs 1-2 dispatches of
  TTFT, not 2N.

Memory is paged (ops/paged_kvcache.py): which HBM blocks each sequence
owns is decided host-side by the native C++ allocator
(native/src/block_pool.cc), whose radix tree lets requests with a shared
prompt prefix reuse already-prefilled blocks — admission then prefills
only the tail. Under memory pressure the youngest slot is preempted back
to the queue (its prefix stays warm in the radix cache, so the re-run is
mostly a cache hit).

Per-request sampling params ride the jitted programs as data
(ops/sampling.py sample_batch), so one compiled program serves any mix of
greedy/temperature/top-k/top-p requests.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llm_inferencing_tpu.models import lora as lora_mod
from distributed_llm_inferencing_tpu.models import transformer
from distributed_llm_inferencing_tpu.models.config import ModelConfig
from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.native import BlockPool
from distributed_llm_inferencing_tpu.ops import kvblock_quant as kvq
from distributed_llm_inferencing_tpu.ops.paged_kvcache import init_paged_cache
from distributed_llm_inferencing_tpu.ops.sampling import (
    SamplingParams, sample_batch)
from distributed_llm_inferencing_tpu.parallel import sharding as shd
from distributed_llm_inferencing_tpu.parallel.mesh import (
    MeshSpec, create_mesh, validate_spec)
from distributed_llm_inferencing_tpu.runtime import kvtier as kvtier_mod
from distributed_llm_inferencing_tpu.runtime import kvwire as kvwire_mod
from distributed_llm_inferencing_tpu.runtime import tsdb as tsdb_mod
from distributed_llm_inferencing_tpu.utils import clock, locks, trace
from distributed_llm_inferencing_tpu.utils.metrics import Metrics
from distributed_llm_inferencing_tpu.utils.profiler import PhaseProfiler

log = logging.getLogger("dli.batcher")

TAIL_BUCKETS_X_BS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)  # × block_size
PREFIX_BUCKETS = (0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512)  # blocks


@dataclasses.dataclass
class BatchRequest:
    """One queued/active generation. The handle the caller waits on."""
    prompt: List[int]
    max_new_tokens: int
    sampling: SamplingParams
    eos_token_id: Optional[int] = None
    stream_cb: Optional[Callable[[int], None]] = None
    seed: int = 0    # output is a pure fn of (params, prompt, seed)
    # results
    tokens: List[int] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    # timing
    submitted_at: float = dataclasses.field(default_factory=clock.now)
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    # cost ledger: when the FIRST admission wave carrying this request
    # started dispatching — queue_ms = admitted_at - submitted_at, and
    # queue + prefill + decode sum exactly to the e2e span
    admitted_at: Optional[float] = None
    # the finished record (phase ms + resource counts), built once in
    # _observe_finished; the worker attaches it to the response payload
    cost: Optional[dict] = None
    # submitter's trace context (utils/trace.py SpanCtx): the scheduler
    # runs in its own thread, so the link to the originating HTTP request
    # rides the request object instead of a contextvar
    trace_ctx: Optional[object] = None
    _last_emit_at: Optional[float] = None
    # internal scheduling state
    _blocks: List[int] = dataclasses.field(default_factory=list)
    _preemptions: int = 0
    _cancelled: bool = False
    # chunked-prefill progress: high-water of cached+chunk across partial
    # passes, and how many passes failed to advance it (radix eviction
    # between chunks can undo progress — bounded, or two pool-sized
    # prompts could re-prefill each other's evictions forever)
    _chunk_high: int = 0
    _chunk_stalls: int = 0
    # prompt extent already counted into the prefill cached/uncached
    # metrics: a resumed chunk pass (or a preemption re-admission)
    # re-matches this request's OWN earlier blocks, which must not be
    # reported as cross-request cache wins
    _prefill_counted: int = 0
    # set when a no-free-slot pop found the request non-partial (its long
    # prompt is mostly radix-cached): skip re-popping it — and the
    # match_prefix + alloc churn that costs — until a slot frees
    _noslot_bounce: bool = False
    # Disaggregated prefill/decode (runtime/kvwire.py): where to pull
    # missing prefix KV from ({"url": peer base URL, "model": name} — the
    # master's kv_source dispatch hint), and whether to export this
    # request's prompt KV into the host arena at finish so a decode peer
    # can fetch it. One peer RPC per request, success or not.
    kv_source: Optional[dict] = None
    kv_export: bool = False
    _peer_fetch_done: bool = False
    _kv_transfer_bytes: int = 0
    # Multi-LoRA serving (models/lora.py): the adapter this request's
    # tokens run through (None = base weights) and the device-pack slot
    # its wave rows gather (0 = base; assigned at admission prep and
    # stable while the adapter's refcount pins the slot). The refcount
    # is taken at submit and released exactly once at the terminal
    # accounting point (_observe_finished).
    adapter: Optional[str] = None
    _lora_slot: int = 0
    _lora_released: bool = False
    # Per-request decode-chunk ceiling (master brownout rung 3 sends
    # body["decode_chunk_cap"] on latency-class dispatches — see
    # runtime/master.py _infer_body and docs/robustness.md "Overload
    # control"). 0 = uncapped. While a capped request is active it
    # clamps the WHOLE wave's chunk choice in _step_inner: shorter
    # slices reach scheduling boundaries sooner, which is the point.
    chunk_cap: int = 0
    # Live in-flight migration (docs/robustness.md "Live migration"):
    # _migrate_requested asks the scheduler to snapshot+evict this
    # request at the next chunk boundary (migrate_out blocks on done);
    # resume_record is the JSON-safe handoff — emitted tokens, seed,
    # sampler position, spec-controller state — a destination batcher
    # resumes from bitwise-exactly; _migrated marks the terminal
    # "handed off" outcome (distinct from failed in every account).
    _migrate_requested: bool = False
    _migrated: bool = False
    resume_record: Optional[dict] = None
    # cost-ledger accumulators (freed with the request)
    _gaps: List[float] = dataclasses.field(default_factory=list)
    _cost_cached: int = 0       # prompt tokens served from cache tiers
    _cost_uncached: int = 0     # prompt tokens actually prefilled
    _weight_passes: int = 0     # decode iterations this request rode
    _kv_peak: int = 0           # peak device KV blocks owned at once
    _arena_restored_bytes: int = 0
    _arena_offloaded_bytes: int = 0
    _spec_acc: int = 0          # draft tokens accepted beyond 1/iteration
    _spec_rej: int = 0          # draft tokens rejected by verification
    _spec_drafted: int = 0      # draft tokens proposed for this request
    # wave-level speculation (DLI_SPEC_WAVE): this request's OWN
    # drafting controller (ops/speculative.py AdaptiveSpecController) —
    # created lazily at its first speculative chunk, surviving
    # preemption/re-admission so a request's acceptance history follows
    # it across slots
    _spec_ctl: Optional[object] = None

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError("generation still running")
        if self.error:
            raise RuntimeError(self.error)
        return self.tokens

    def cancel(self):
        """Ask the scheduler to drop this request (frees its slot/blocks at
        the next chunk boundary; already-generated tokens are kept)."""
        self._cancelled = True

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return (self.first_token_at - self.submitted_at) * 1e3

    @property
    def latency_ms(self) -> Optional[float]:
        if self.finished_at is None:
            return None
        return (self.finished_at - self.submitted_at) * 1e3


class ContinuousBatcher:
    """Slot-based continuous batching scheduler.

    One jitted program per step; the model may be mesh-sharded. Tensor /
    expert parallelism (tp/ep) ride GSPMD — params and the paged cache
    carry NamedShardings and XLA partitions the step's matmuls/attention
    over ICI. Pipeline parallelism (pp > 1) swaps the decode-chunk and
    admission programs for GPipe-scheduled shard_map versions
    (parallel/paged_pipeline.py) with slots as the microbatch dimension
    and the paged pool's layer axis sharded per stage — the serving path
    for models too big for one slice's tp×ep. Batch-dim parallelism (dp)
    and sequence sharding (sp) are rejected: the slot scheduler owns the
    batch dimension, and decode chunks never span one sequence.

    Drive it either with an owned background thread (``start()``/``stop()``)
    or synchronously via ``step()`` (tests, custom loops).
    """

    # Decode-chunk sizes (tokens per dispatched program), tried in order.
    # Each step picks the largest chunk some active slot can fill; per-slot
    # budget/eos masks handle slots that finish mid-chunk. Mirrors the
    # engine's DECODE_CHUNKS trade (one shared schedule — a tuning there
    # is a tuning here): bigger chunks amortize dispatch RTT, at the cost
    # of chunk-granularity admission/cancellation latency.
    from distributed_llm_inferencing_tpu.runtime.engine import (
        InferenceEngine as _Eng)
    DECODE_CHUNKS = _Eng.DECODE_CHUNKS
    del _Eng
    # A dispatch round trip costs ~10-15 decode steps of compute on a
    # tunnel-attached chip, so rounding the chunk UP past the largest
    # remaining budget (budget masks make overshoot steps dead compute)
    # is a win as long as the overshoot stays small.
    CHUNK_OVERSHOOT_MAX = 8

    def __init__(self, cfg: ModelConfig, params=None, *,
                 num_blocks: int = 512, block_size: int = 16,
                 slots: int = 8, max_seq: Optional[int] = None,
                 seed: int = 0, force_python_pool: bool = False,
                 mesh_spec: Optional[MeshSpec] = None,
                 prefill_chunk: Optional[int] = 32,
                 decode_chunk_cap: Optional[int] = None,
                 speculative: Optional[str] = None, spec_gamma: int = 4,
                 spec_adaptive: Optional[bool] = None,
                 spec_wave: Optional[bool] = None,
                 decode_overlap: Optional[bool] = None,
                 kv_host_mb: Optional[float] = None,
                 kv_digest_chunk: Optional[int] = None,
                 kv_fetcher=None,
                 metrics: Optional[Metrics] = None):
        # shared with the worker's registry when serving (so /metrics
        # carries the scheduler's gauges/histograms); owned otherwise
        self.metrics = metrics or Metrics()
        self.mesh_spec = mesh_spec or MeshSpec()
        for ax in ("dp", "sp"):
            if getattr(self.mesh_spec, ax) > 1:
                raise ValueError(
                    f"batched serving shards tensors (tp/ep) and pipeline "
                    f"stages (pp); {ax}={getattr(self.mesh_spec, ax)} "
                    "unsupported (the slot scheduler owns the batch dim)")
        if self.mesh_spec.pp > 1:
            # pipeline-parallel serving (parallel/paged_pipeline.py):
            # slots microbatch over pp inside one GPipe-scheduled program
            # (speculative chunks included — the draft/acceptance state
            # rides the ppermute ring, paged_speculative_chunk_pp)
            slots = -(-slots // self.mesh_spec.pp) * self.mesh_spec.pp
        self.cfg = cfg = cfg.replace(
            attn_backend=_backend(cfg, self.mesh_spec.num_devices),
            # int4 pallas routing hint (models/config.py): this GSPMD
            # program din-shards o/down over tp, and the kernel's
            # partition rule would all-gather those shards every step
            tp_row_sharded=self.mesh_spec.tp > 1,
            # the paged pool keeps the materialized per-head K/V layout;
            # the latent formulation is the dense-cache engine's
            # (config.py mla_latent_cache)
            mla_latent_cache=False)
        validate_spec(self.mesh_spec, cfg)
        self.mesh = create_mesh(self.mesh_spec)
        self.block_size = block_size
        self.slots = slots
        self.max_seq = min(max_seq or cfg.max_position_embeddings,
                           cfg.max_position_embeddings)
        self.max_blocks = -(-self.max_seq // block_size)
        # Chunked prefill (vLLM-style): prompts whose un-cached tail
        # exceeds this many blocks admit one chunk per step — KV lands in
        # the radix cache, the request requeues, and the next wave's
        # prefix match resumes exactly where the chunk ended. Bounds how
        # long one huge prompt can stall co-running decode. None/0
        # disables; snapped to a tail bucket so chunk programs hit the
        # same compile cache as ordinary admissions.
        if prefill_chunk:
            self.prefill_chunk = next(
                (m for m in TAIL_BUCKETS_X_BS if m >= prefill_chunk),
                TAIL_BUCKETS_X_BS[-1])
        else:
            self.prefill_chunk = None
        self._chunked_admissions = 0
        # Decode-chunk cap (latency-tier knob): bigger chunks amortize
        # dispatch RTT, but a K-token chunk also delivers its tokens as
        # one K-sized burst — a latency-tier model (or an ITL-measuring
        # bench) caps the chunk so inter-token gaps track real steps.
        self._decode_chunk_cap = (int(decode_chunk_cap)
                                  if decode_chunk_cap else None)
        # Double-buffered decode dispatch: when the next chunk pair is
        # provably stop-check-free (no eos, no streaming callback, every
        # active budget covers BOTH chunks, nothing queued), dispatch
        # chunk N+1 fed by chunk N's device-resident last tokens and sync
        # the pair once — chunk N's token transfer overlaps chunk N+1's
        # compute, halving host round trips on the steady-state decode
        # path. Single-host only (the lockstep broadcast ships JSON args;
        # a device-array token feed cannot ride it). DLI_DECODE_OVERLAP=0
        # opts out for A/B.
        if decode_overlap is None:
            decode_overlap = os.environ.get(
                "DLI_DECODE_OVERLAP", "1") not in ("0", "false")
        self.decode_overlap = bool(decode_overlap)
        self._overlapped_dispatches = 0
        # Speculative decoding (models/transformer.py
        # paged_speculative_chunk): on-device prompt-lookup drafts, up to
        # spec_gamma+1 tokens per slot per iteration. Greedy requests get
        # the speedup with bit-identical output; sampling requests run
        # one exact token per iteration (no speedup, no distribution
        # drift).
        if speculative not in (None, "ngram"):
            raise ValueError(f"unknown speculative mode {speculative!r}")
        self.speculative = speculative
        self.spec_gamma = int(spec_gamma)
        self._spec_accepted = 0
        # Adaptive drafting (ops/speculative.py AdaptiveSpecController):
        # gamma shrinks / drafting auto-falls-back to plain chunks when
        # measured acceptance or tok/s says drafting loses, with periodic
        # re-probes — "speculative=ngram" must never be slower than off.
        # Default on; DLI_SPEC_ADAPTIVE=0 pins the always-draft behavior
        # (A/B and the fixed-gamma parity tests).
        if spec_adaptive is None:
            spec_adaptive = os.environ.get(
                "DLI_SPEC_ADAPTIVE", "1") not in ("0", "false")
        self._spec_adaptive = bool(spec_adaptive)
        # Wave-level speculation (DLI_SPEC_WAVE, default on): ONE shared
        # verify pass serves the whole active wave with PER-SLOT draft
        # widths as data — each request carries its own
        # AdaptiveSpecController (BatchRequest._spec_ctl), so a
        # draft-hostile request converges to width 0 and rides the wave's
        # verify pass as plain decode while its draft-friendly chunk-mates
        # keep their speedup (no wave-wide fallback cliff). Off: the
        # pre-wave global controller arbitrates one gamma for the wave.
        if spec_wave is None:
            spec_wave = os.environ.get(
                "DLI_SPEC_WAVE", "1") not in ("0", "false")
        self.spec_wave = bool(spec_wave) and bool(speculative)
        self._spec_wave_dispatches = 0
        # Cross-request arbitration state for wave mode: measured spec /
        # plain tok/s and the probe clocks are HOST+WORKLOAD properties,
        # not per-request ones — a fresh request's controller seeds from
        # them (and starts in plain mode when the fleet measurements say
        # drafting loses), so short generations inherit the fleet's
        # verdict instead of each re-paying the discovery cost.
        # Acceptance windows, gamma and MODE transitions stay
        # per-request: one draft-hostile request still can't drag its
        # chunk-mates off the speculative path.
        self._wave_shared = {"spec_tps": None, "plain_tps": None,
                             "since_plain_probe": 0, "since_probe": 0}
        # register the headline gauge + wave counters at 0 up front so a
        # scrape (and the TSDB catalog behind it) can't confuse "no
        # decode yet" with "metric not exported" — PR 5's radix-counter
        # rule applied to the amortization plane
        self.metrics.gauge("decode_tokens_per_weight_pass", 0.0)
        # the dashboard's TSDB panel charts these from the first scrape;
        # without pre-registration the series is invisible until the
        # first submit/step (dlilint metric-not-preregistered)
        self.metrics.gauge("batcher_queue_depth", 0.0)
        self.metrics.gauge("batcher_free_kv_blocks", 0.0)
        # live-migration handoffs (distinct from failed in every
        # account); registered at 0 so a scrape can't confuse "no
        # migrations yet" with "metric not exported"
        self.metrics.inc("batcher_requests_migrated", 0)
        if self.spec_wave:
            for name in ("spec_wave_dispatches", "spec_wave_drafted_tokens",
                         "spec_wave_accepted_tokens",
                         "spec_wave_plain_rides"):
                self.metrics.inc(name, 0)
            self.metrics.gauge("spec_wave_drafting_slots", 0.0)
            self.metrics.gauge("spec_wave_gamma_mean", 0.0)
        self._spec_ctl = None
        # spec_gamma < 1 is an explicit zero-draft request: no controller
        # (it would clamp gamma up to 1 and start drafting), the step's
        # gamma==0 branch runs plain chunks
        if (speculative and spec_adaptive and self.spec_gamma >= 1
                and not self.spec_wave):
            from distributed_llm_inferencing_tpu.ops.speculative import (
                AdaptiveSpecController)
            self._spec_ctl = AdaptiveSpecController(self.spec_gamma)
        # device-drafting token history, maintained incrementally (a
        # per-step rebuild would be O(slots * max_seq) host work on the
        # hot path): row i holds slot i's prompt + emitted tokens
        self._hist = (np.zeros((slots, self.max_seq + 1), np.int32)
                      if speculative else None)
        # lockstep-mirror watermark: how many leading entries of each hist
        # row the followers hold (spec dispatches broadcast only the
        # per-slot delta past it — the appends themselves are derived from
        # the replayed program's outputs on both sides)
        self._hist_synced = (np.zeros((slots,), np.int64)
                             if speculative else None)
        if params is None:
            params = init_params(cfg, jax.random.PRNGKey(seed))
        else:
            from distributed_llm_inferencing_tpu.ops.quant import (
                maybe_quantize, maybe_quantize_embed)
            params = maybe_quantize_embed(maybe_quantize(params, cfg), cfg)
        with self.mesh:
            self.params = shd.shard_params(params, self.mesh, cfg,
                                           self.mesh_spec)

        # +1: block 0 is the reserved dummy every inactive table entry
        # points at, so it never carries real KV
        self.pool = BlockPool(num_blocks + 1, block_size,
                              force_python=force_python_pool)
        [self._dummy] = self.pool.alloc(1)
        # overwrite the 0 pre-registration with the truth now the pool
        # exists — a scrape between construction and the first step must
        # not read "0 free blocks" as exhaustion
        self.metrics.gauge("batcher_free_kv_blocks", self.pool.free_count())
        self.paged = jax.device_put(
            init_paged_cache(cfg, num_blocks + 1, block_size),
            shd.named(self.mesh, shd.paged_cache_specs(cfg, self.mesh_spec)))
        self.block_tables = np.full((slots, self.max_blocks), self._dummy,
                                    np.int32)
        # Host-RAM KV offload tier (runtime/kvtier.py): radix-evicted
        # blocks copy their device KV pages into a bounded, content-keyed
        # host arena; admission restores matching blocks with one scatter
        # instead of re-prefilling. DLI_KV_HOST_MB (or the kv_host_mb
        # kwarg) sizes the arena; 0 disables the tier — advertisement
        # included (docs/serving.md "Prefix-cache tier").
        if kv_host_mb is None:
            try:
                kv_host_mb = float(os.environ.get(
                    "DLI_KV_HOST_MB", kvtier_mod.DEFAULT_HOST_MB))
            except ValueError:
                kv_host_mb = kvtier_mod.DEFAULT_HOST_MB
        # Arena storage dtype (ops/kvblock_quant.py): "native" keeps the
        # exact device bytes (bitwise restore), "int8" packs ~3.9x more
        # prefix tokens per MB and ships ~3.9x fewer wire bytes, at a
        # bounded dequant error per restored block.
        kv_dtype = os.environ.get("DLI_KV_HOST_DTYPE", "native")
        if kv_dtype not in kvtier_mod.HOST_DTYPES:
            kv_dtype = "native"
        self.kvtier = (kvtier_mod.KVTier(
            block_size, kv_host_mb,
            digest_chunk=kv_digest_chunk or kvtier_mod.DIGEST_CHUNK,
            dtype=kv_dtype)
            if kv_host_mb and kv_host_mb > 0 else None)
        if self.kvtier is not None:
            self.pool.set_evict_hook(self._offload_evicted)
        # Cross-node KV transfer (runtime/kvwire.py): the worker injects
        # its shared KVFetchClient (pooled peer sessions, fault point,
        # conn accounting in the worker registry); a standalone batcher
        # builds its own lazily at the first kv_source admission.
        self.kv_fetcher = kv_fetcher
        # Receive-overlapped restore (DLI_KV_WIRE_OVERLAP, default on):
        # peer fetches stream through kvwire.FetchStream so the device
        # scatter of block N overlaps the receive of block N+1; 0 falls
        # back to the serial fetch-then-scatter path.
        self._wire_overlap = os.environ.get(
            "DLI_KV_WIRE_OVERLAP", "1") not in ("0", "false", "no", "")
        # Single-flight prefetch registry: concurrent fetches to the
        # same (peer, model) — shared-prefix fan-in, a dying node's mass
        # drain — coalesce onto one leader transfer with the digest
        # union deduped; waiters block on the leader's round and find
        # the blocks arena-resident.
        self._kvf_lock = locks.lock("batcher.kvfetch")
        self._kvf_inflight: Dict[tuple, dict] = {}
        if self.kvtier is not None:
            # pre-register the transfer plane at 0 (PR 5 rule): the TSDB
            # catalog and a first scrape must see the counters exist
            for name in ("kv_transfer_blocks", "kv_transfer_bytes",
                         "kv_transfer_ms", "kv_transfer_failures",
                         "kvtier_exported_blocks",
                         "kv_prefetch_coalesced"):
                self.metrics.inc(name, 0)
            self.metrics.gauge("kv_restore_overlap_ratio", 0.0)
        self._restore_fns = {}        # restore-scatter jits per row bucket
        self._last_pool_stats = {}    # radix counter -> metrics delta base
        # cost-ledger attribution: the request whose admission prep is
        # currently allocating (scheduler-thread-local by construction) —
        # arena offloads triggered by ITS alloc bill to it
        self._admitting: Optional[BatchRequest] = None
        # declarative SLO targets (runtime/tsdb.py): used worker-side
        # only to flag SLO-violating requests for trace tail-retention
        self._slo_targets = tsdb_mod.slo_targets()
        # Multi-LoRA serving (models/lora.py): a bounded host adapter
        # tier (LRU by bytes, DLI_LORA_HOST_MB) feeding DLI_LORA_SLOTS
        # device pack slots (+ reserved slot 0 = base). Loading or
        # evicting an adapter rebuilds the stacked device pack DATA —
        # shapes are static in (slots, max_rank), so adapter mixes
        # never recompile. Refcounts pin a slotted adapter while any
        # submitted request still references it.
        self._lora_lock = locks.lock("batcher.lora")
        self._lora_store = lora_mod.LoRAHostStore()
        self._lora_max_rank = lora_mod.max_rank_from_env()
        self._lora_slot_names: List[Optional[str]] = \
            [None] * (lora_mod.slots_from_env() + 1)
        self._lora_refs: Dict[str, int] = {}
        self._lora_last_use: Dict[str, int] = {}
        self._lora_seq = 0
        self._params_lora = None   # params tree + layers["lora"] pack
        # pre-register the adapter plane at 0 (PR 5 rule): the TSDB
        # catalog and a first scrape must see the series exist before
        # the first load/submit
        self.metrics.gauge("lora_host_bytes", 0.0)
        self.metrics.gauge("lora_host_adapters", 0.0)
        for name in ("lora_loads", "lora_evictions", "lora_load_failures",
                     "lora_requests"):
            self.metrics.inc(name, 0)
        # opt-in sampling phase profiler for this step loop
        # (utils/profiler.py; DLI_PROFILE=1 or worker POST /api/profile)
        self.profiler = PhaseProfiler.from_env()
        self.context_lens = np.zeros((slots,), np.int32)
        self.active: List[Optional[BatchRequest]] = [None] * slots
        self._admit_order: collections.deque = collections.deque()  # slot ids

        self.queue: collections.deque = collections.deque()
        self._lock = locks.lock("batcher.state")
        self._work = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._step_count = 0
        self._tokens_out = 0

        self._prefill_fns = {}   # (tail, prefix, wave) -> compiled admit
        self._decode_fns = {}    # chunk k -> compiled decode chunk

        # Multi-host seam (runtime/multihost.py): when set, every device
        # program this scheduler launches is routed through
        # ``program_hook(kind, payload, run)`` — the lockstep leader
        # broadcasts (kind, payload) to follower hosts, which ``replay()``
        # the identical program, then calls ``run()`` in sequence order.
        # The *scheduling decisions* stay leader-local; only their compiled
        # consequences are replicated, so followers need no pool/queue.
        # Chunked decode + wave admission make this one broadcast per K
        # tokens / per admission wave, not per token (round-2's per-token
        # mirror was the multi-host throughput ceiling).
        self.program_hook = None

    @property
    def decode_chunks(self):
        """DECODE_CHUNKS filtered by the instance's decode_chunk_cap —
        a live view (tests override DECODE_CHUNKS per instance)."""
        if self._decode_chunk_cap is None:
            return self.DECODE_CHUNKS
        return tuple(c for c in self.DECODE_CHUNKS
                     if c <= self._decode_chunk_cap) \
            or (min(self.DECODE_CHUNKS),)

    # ---- public API ---------------------------------------------------

    def _make_request(self, prompt: Sequence[int], max_new_tokens: int = 100,
                      sampling: Optional[SamplingParams] = None,
                      eos_token_id: Optional[int] = None,
                      stream_cb: Optional[Callable[[int], None]] = None,
                      seed: Optional[int] = None,
                      kv_source: Optional[dict] = None,
                      kv_export: bool = False,
                      kv_transfer_bytes: int = 0,
                      resume: Optional[dict] = None,
                      trace_ctx=None,
                      chunk_cap: Optional[int] = None,
                      adapter: Optional[str] = None) -> BatchRequest:
        """Validate and build one BatchRequest WITHOUT enqueueing it —
        submit()/submit_many() construct first so a bad spec can never
        leave siblings half-enqueued."""
        if not prompt:
            raise ValueError("empty prompt")
        if isinstance(resume, dict) and resume.get("adapter"):
            # a migrated-in request keeps its source adapter: serving
            # the continuation on base weights would silently change
            # the model mid-stream
            adapter = str(resume["adapter"])
        if isinstance(resume, dict) and resume.get("seed") is not None:
            # a live-migration resume MUST keep the source's seed: the
            # position-keyed PRNG ((seed, steps) per emitted position)
            # is what makes the continued sampled stream draw the same
            # tokens the unmigrated run would have
            seed = int(resume["seed"])
        if seed is None:
            seed = time.time_ns() % (1 << 31)
        req = BatchRequest(prompt=list(map(int, prompt)),
                           max_new_tokens=int(max_new_tokens),
                           sampling=sampling or SamplingParams(),
                           eos_token_id=eos_token_id, stream_cb=stream_cb,
                           seed=int(seed),
                           kv_source=(kv_source if isinstance(kv_source,
                                                              dict)
                                      else None),
                           kv_export=bool(kv_export),
                           adapter=(str(adapter) if adapter else None),
                           chunk_cap=max(0, int(chunk_cap or 0)),
                           # explicit ctx for callers submitting from a
                           # helper thread (SSE streams), ambient otherwise
                           trace_ctx=trace_ctx or trace.current())
        # cost-ledger seed for a submit-time prefetch (the worker pulls
        # the peer KV on its handler thread, then attributes here)
        req._kv_transfer_bytes = int(kv_transfer_bytes or 0)
        if isinstance(resume, dict) and resume.get("tokens"):
            # live-migration resume: pre-seed the emitted tokens. They
            # are never re-emitted (no _emit pass, so the stream
            # callback fires only for NEW tokens — zero duplicates) and
            # admission prefills prompt+tokens exactly like a
            # preemption re-admission, so the continuation is bitwise
            # the unmigrated run's tail.
            req.tokens = [int(t) for t in resume["tokens"]]
            if len(req.tokens) >= req.max_new_tokens:
                raise ValueError(
                    f"resume record carries {len(req.tokens)} emitted "
                    f"tokens >= max_new_tokens {req.max_new_tokens} — "
                    "the source should have completed, not migrated")
            spec_state = resume.get("spec")
            if (spec_state and self.speculative and self.spec_wave
                    and self._spec_adaptive and self.spec_gamma >= 1):
                from distributed_llm_inferencing_tpu.ops.speculative \
                    import AdaptiveSpecController
                # request-owned policy state (gamma/mode/acceptance)
                # migrates; throughput EMAs re-seed from THIS host's
                # shared arbitration state — they measure the host
                ctl = self._seed_wave_ctl(
                    AdaptiveSpecController(self.spec_gamma))
                ctl.load_state(spec_state)
                req._spec_ctl = ctl
        if len(req.prompt) + req.max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt ({len(req.prompt)}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds max_seq {self.max_seq}")
        if req.adapter:
            # LAST validation: pinning is the only step with a side
            # effect, so an earlier raise can never leak a refcount
            self._pin_lora(req.adapter)   # ValueError when not loaded
            self.metrics.inc("lora_requests")
        return req

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 100,
               sampling: Optional[SamplingParams] = None,
               eos_token_id: Optional[int] = None,
               stream_cb: Optional[Callable[[int], None]] = None,
               seed: Optional[int] = None,
               kv_source: Optional[dict] = None,
               kv_export: bool = False,
               kv_transfer_bytes: int = 0,
               resume: Optional[dict] = None,
               trace_ctx=None,
               chunk_cap: Optional[int] = None,
               adapter: Optional[str] = None) -> BatchRequest:
        req = self._make_request(prompt, max_new_tokens, sampling,
                                 eos_token_id, stream_cb, seed,
                                 kv_source, kv_export, kv_transfer_bytes,
                                 resume, trace_ctx, chunk_cap=chunk_cap,
                                 adapter=adapter)
        with self._lock:
            self.queue.append(req)
            depth = len(self.queue)
        self.metrics.inc("batcher_requests_submitted")
        self.metrics.gauge("batcher_queue_depth", depth)
        self._work.set()
        return req

    def submit_many(self, specs: Sequence[dict]) -> List[BatchRequest]:
        """Multi-submit entry for batched RPC dispatch (the worker's
        ``/inference_batch`` handler): validate and build every request
        FIRST (all-or-nothing — a ValueError enqueues nothing), then
        append them under ONE lock acquisition with one scheduler wake,
        preserving the caller's order end-to-end. One master dispatch
        batch therefore admits FIFO, exactly as submitted."""
        reqs: List[BatchRequest] = []
        try:
            for spec in specs:
                reqs.append(self._make_request(**spec))
        except Exception:
            # all-or-nothing: drop the adapter refcounts the already-
            # built siblings pinned, or a failing batch would pin its
            # adapters forever
            for r in reqs:
                self._release_lora(r)
            raise
        if not reqs:
            return []
        with self._lock:
            self.queue.extend(reqs)
            depth = len(self.queue)
        self.metrics.inc("batcher_requests_submitted", len(reqs))
        self.metrics.gauge("batcher_queue_depth", depth)
        self._work.set()
        return reqs

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="batcher")
            self._thread.start()

    def stop(self):
        """Stop the loop and fail every in-flight/queued request, so no
        client blocks until its timeout on an unloading worker."""
        self._stop.set()
        self._work.set()
        if self._thread:
            self._thread.join(timeout=30)
            self._thread = None
        for slot in range(self.slots):
            req = self.active[slot]
            if req is not None:
                req.error = req.error or "scheduler stopped"
                self._finish_slot(slot)
        with self._lock:
            drained = list(self.queue)
            self.queue.clear()
        for req in drained:
            self._fail_req(req, "scheduler stopped")

    def inflight(self) -> int:
        """Requests the scheduler still owes an answer (active slots +
        queue) — what a graceful drain waits on (runtime/worker.py
        _wait_idle polls this alongside its own handler count)."""
        with self._lock:
            queued = len(self.queue)
        return sum(a is not None for a in self.active) + queued

    def stats(self) -> dict:
        return {
            "slots": self.slots,
            "mesh": self.mesh_spec.axis_sizes(),
            "active": sum(a is not None for a in self.active),
            "queued": len(self.queue),
            "steps": self._step_count,
            "tokens_out": self._tokens_out,
            "block_size": self.block_size,
            "blocks_free": self.pool.free_count(),
            "chunk_sizes": sorted({key[0] for key in self._decode_fns
                                   if not isinstance(key[0], str)}),
            "chunked_admissions": self._chunked_admissions,
            "prefill_chunk": self.prefill_chunk,
            "decode_overlap": self.decode_overlap,
            "overlapped_dispatches": self._overlapped_dispatches,
            "speculative": self.speculative,
            "spec_accepted_tokens": self._spec_accepted,
            "spec_adaptive": (self._spec_ctl.stats()
                              if self._spec_ctl is not None else None),
            "spec_wave": self._spec_wave_stats(),
            "pool": self.pool.stats(),
            # host KV tier + routing advertisement (runtime/kvtier.py):
            # the digests ride the worker's /health body into the
            # master's per-node runtime snapshot; state.py strips them
            # from the PERSISTED node row (ephemeral routing state)
            "kvtier": (self.kvtier.stats()
                       if self.kvtier is not None else None),
            "prefix_digests": (self.kvtier.index.advertise()
                               if self.kvtier is not None else None),
            # resident-adapter advertisement: rides the worker's /health
            # body into the master's runtime snapshot the same way the
            # prefix digests do, feeding adapter-affinity routing
            "adapters": self.lora_stats(),
        }

    def _spec_wave_stats(self) -> Optional[dict]:
        """Aggregate view of wave-level speculation: per-request
        controllers live on the requests (BatchRequest._spec_ctl), so
        the batcher-level summary counts ACTIVE requests' modes/widths —
        the live width mix a scraper sees, not lifetime history."""
        if not self.spec_wave:
            return None
        ctls = [a._spec_ctl for a in self.active
                if a is not None and a._spec_ctl is not None]
        return {
            "dispatches": self._spec_wave_dispatches,
            "active_controllers": len(ctls),
            "drafting": sum(c.mode == "spec" for c in ctls),
            "plain": sum(c.mode == "plain" for c in ctls),
            "fallbacks": sum(c.fallbacks for c in ctls),
            "gamma_mean": (round(float(np.mean([c.gamma for c in ctls])),
                                 2) if ctls else None),
        }

    # ---- multi-LoRA adapters (models/lora.py) -------------------------

    def load_adapter(self, name: str, source: str) -> dict:
        """Make an adapter host-resident (worker ``POST /load_adapter``
        and the master's lazy dispatch-time load land here). Device slot
        assignment is deferred to the first admission that needs it.
        Idempotent for an already-resident name. Returns
        ``{name, rank, nbytes, evicted}`` — the caller emits the
        adapter-loaded / adapter-evicted events. ValueError on any
        problem (bad source, shape mismatch, store full of pinned
        adapters) — the request path NEVER falls back to base weights."""
        if self.mesh_spec.pp > 1:
            raise ValueError(
                "LoRA serving does not support pp > 1 (the pipelined "
                "chunk programs re-stage layers without the delta pack)")
        lora_mod.validate_base_model(self.cfg)
        with self._lora_lock:
            ad = self._lora_store.get(name)
            evicted: List[str] = []
            if ad is None:
                try:
                    ad = lora_mod.resolve(self.cfg, name, source,
                                          max_rank=self._lora_max_rank)
                    pinned = {n for n, c in self._lora_refs.items() if c}
                    evicted = self._lora_store.put(ad, pinned=pinned)
                except ValueError:
                    self.metrics.inc("lora_load_failures")
                    raise
                self.metrics.inc("lora_loads")
                self.metrics.inc("lora_evictions", len(evicted))
                # a host-evicted adapter cannot back a device slot: clear
                # its slot (refcount 0 by the pinned set) and rebuild
                dirty = False
                for i in range(1, len(self._lora_slot_names)):
                    if self._lora_slot_names[i] in evicted:
                        self._lora_slot_names[i] = None
                        dirty = True
                if dirty:
                    self._rebuild_lora_pack()
            self._gauge_lora()
            return {"name": ad.name, "rank": ad.rank, "nbytes": ad.nbytes,
                    "evicted": evicted}

    def unload_adapter(self, name: str) -> bool:
        """Drop an adapter from the host store and its device slot.
        Refuses (ValueError) while live requests reference it."""
        with self._lora_lock:
            if self._lora_refs.get(name, 0):
                raise ValueError(
                    f"adapter {name!r} has live requests; drain first")
            dirty = False
            for i in range(1, len(self._lora_slot_names)):
                if self._lora_slot_names[i] == name:
                    self._lora_slot_names[i] = None
                    dirty = True
            dropped = self._lora_store.drop(name)
            if dirty:
                self._rebuild_lora_pack()
            self._gauge_lora()
            return dropped

    def lora_stats(self) -> dict:
        with self._lora_lock:
            return {
                "resident": sorted(self._lora_store.names()),
                "slotted": [n for n in self._lora_slot_names[1:] if n],
                "slots": len(self._lora_slot_names) - 1,
                "host": self._lora_store.stats(),
                "active_refs": {n: c for n, c in self._lora_refs.items()
                                if c},
            }

    def _gauge_lora(self):
        st = self._lora_store.stats()
        self.metrics.gauge("lora_host_bytes", st["bytes"])
        self.metrics.gauge("lora_host_adapters", st["adapters"])

    def _pin_lora(self, name: str):
        """Submit-time refcount: pins the adapter against host eviction
        (and its slot, once assigned, against slot reuse) from the
        moment the request exists. ValueError when not host-resident —
        an unknown adapter is the caller's structured 400."""
        if self.program_hook is not None:
            raise ValueError(
                "LoRA adapters cannot ride multi-host lockstep serving "
                "(followers hold no adapter store to replay against)")
        with self._lora_lock:
            if self._lora_store.get(name) is None:
                raise ValueError(
                    f"unknown adapter {name!r} (POST /load_adapter first)")
            self._lora_refs[name] = self._lora_refs.get(name, 0) + 1

    def _release_lora(self, req: BatchRequest):
        """Exactly-once refcount release at the terminal accounting
        point (_observe_finished serves every outcome: finished, failed,
        migrated). The slot itself stays resident for affinity reuse —
        only slot pressure from a new adapter reclaims it."""
        if not req.adapter or req._lora_released:
            return
        req._lora_released = True
        with self._lora_lock:
            n = self._lora_refs.get(req.adapter, 0)
            if n > 1:
                self._lora_refs[req.adapter] = n - 1
            else:
                self._lora_refs.pop(req.adapter, None)

    def _assign_lora_slot(self, name: str) -> int:
        """Bind an adapter to a device pack slot at admission prep.
        Reuses the existing slot (refcounts keep it stable while any
        request references it), else takes a free slot, else evicts the
        least-recently-used refcount-0 slot. All pinned -> ValueError
        (the admission path fails the request with a clear error)."""
        with self._lora_lock:
            ad = self._lora_store.get(name)
            if ad is None:
                raise ValueError(
                    f"adapter {name!r} evicted from the host store "
                    "before admission (DLI_LORA_HOST_MB)")
            names = self._lora_slot_names
            if name in names:
                s = names.index(name)
            else:
                free = [i for i in range(1, len(names))
                        if names[i] is None]
                if free:
                    s = free[0]
                else:
                    idle = [i for i in range(1, len(names))
                            if not self._lora_refs.get(names[i], 0)]
                    if not idle:
                        raise ValueError(
                            f"adapter {name!r}: all {len(names) - 1} "
                            "device adapter slots are pinned by live "
                            "requests (DLI_LORA_SLOTS)")
                    s = min(idle, key=lambda i: self._lora_last_use.get(
                        names[i], 0))
                    self.metrics.inc("lora_evictions")
                names[s] = name
                self._rebuild_lora_pack()
            self._lora_seq += 1
            self._lora_last_use[name] = self._lora_seq
            return s

    def _rebuild_lora_pack(self):
        """Re-stack the device pack from the current slot assignment and
        swap the lora params tree. Shapes depend only on (slots,
        max_rank) — every rebuild hits the same compiled programs.
        Caller holds _lora_lock."""
        slot_ads = [None] + [
            (self._lora_store.peek(n) if n else None)
            for n in self._lora_slot_names[1:]]
        pack = lora_mod.build_pack(self.cfg, slot_ads, self._lora_max_rank)
        with self.mesh:
            pack_dev = jax.tree_util.tree_map(jnp.asarray, pack)
        p = dict(self.params)
        p["layers"] = dict(self.params["layers"], lora=pack_dev)
        self._params_lora = p

    # ---- compiled steps ----------------------------------------------

    # Args cross host->device as TWO packed arrays (int32 + f32) per
    # dispatch, unpacked on device: on a tunnel-attached chip every
    # eager transfer pays a network round trip, and 13 tiny arrays per
    # chunk cost more than the chunk itself.

    def _admit_jit(self, t: int, pb: int, b: int, use_lora: bool = False):
        """Wave-admission program: batched tail prefill + fused first-token
        sampling — one dispatch per (tail-bucket, prefix-bucket) group.
        ``use_lora`` variants append per-row adapter slot ids to the ints
        pack and gather the rank-r delta per row (ops/lora.py); base
        waves keep the base program — a zero-cost skip, not a masked
        delta."""
        key = (t, pb, b, use_lora)
        fn = self._prefill_fns.get(key)
        if fn is None:
            cfg = self.cfg
            nb = t // self.block_size
            pp, mesh, dummy = self.mesh_spec.pp, self.mesh, self._dummy

            def admit(p, ints, floats, paged):
                toks = ints[:b * t].reshape(b, t)
                tb = ints[b * t:b * (t + nb)].reshape(b, nb)
                pfb = ints[b * (t + nb):b * (t + nb + pb)].reshape(b, pb)
                rest = ints[b * (t + nb + pb):]
                if use_lora:
                    tl, pfl, seeds, steps, tks, ds, aids = \
                        rest.reshape(7, b)
                else:
                    tl, pfl, seeds, steps, tks, ds = rest.reshape(6, b)
                    aids = None
                temps, tps = floats
                if pp > 1:
                    from distributed_llm_inferencing_tpu.parallel import (
                        paged_pipeline)
                    last, paged = paged_pipeline.paged_prefill_tail_pp(
                        p, cfg, toks, tl, tb, pfb, pfl, paged, dummy,
                        mesh=mesh)
                else:
                    last, paged = transformer.paged_prefill_tail(
                        p, cfg, toks, tl, tb, pfb, pfl, paged,
                        lora_ids=aids)
                first = sample_batch(last, seeds, steps, temps, tks, tps,
                                     ds.astype(bool))
                return first, paged

            fn = jax.jit(admit, donate_argnums=(3,))
            self._prefill_fns[key] = fn
        return fn

    def _decode_jit(self, k: int, r: int, mb: int, use_lora: bool = False):
        """K-token decode chunk (transformer.paged_decode_chunk), one host
        sync per K tokens for all slots. ``tokens`` rides as its own
        argument — not packed into ``ints`` — so a double-buffered step
        can feed chunk N+1 the device-resident last tokens of chunk N
        without a host round trip (_step_overlapped). ``use_lora``
        variants append per-slot adapter ids to the ints pack."""
        fn = self._decode_fns.get((k, r, mb, use_lora))
        if fn is None:
            cfg, dummy = self.cfg, self._dummy
            pp, mesh = self.mesh_spec.pp, self.mesh

            def chunk(p, tokens, ints, floats, paged):
                bt = ints[:r * mb].reshape(r, mb)
                if use_lora:
                    (cl, seeds, steps0, tks, budget, eos_ids, ds,
                     aids) = ints[r * mb:].reshape(8, r)
                else:
                    (cl, seeds, steps0, tks, budget, eos_ids,
                     ds) = ints[r * mb:].reshape(7, r)
                    aids = None
                temps, tps = floats
                if pp > 1:
                    from distributed_llm_inferencing_tpu.parallel import (
                        paged_pipeline)
                    return paged_pipeline.paged_decode_chunk_pp(
                        p, cfg, k, tokens, paged, bt, cl, seeds, steps0,
                        temps, tks, tps, ds.astype(bool), budget, eos_ids,
                        dummy, mesh=mesh)
                return transformer.paged_decode_chunk(
                    p, cfg, k, tokens, paged, bt, cl, seeds, steps0, temps,
                    tks, tps, ds.astype(bool), budget, eos_ids, dummy,
                    lora_ids=aids)

            fn = jax.jit(chunk, donate_argnums=(4,))
            self._decode_fns[(k, r, mb, use_lora)] = fn
        return fn

    def _spec_jit(self, k: int, g: int, r: int, mb: int, hh: int,
                  use_lora: bool = False):
        """K speculative verify iterations
        (transformer.paged_speculative_chunk): up to (g+1)K tokens per
        slot per host sync. ``g`` is the compiled STATIC maximum draft
        width; the per-slot effective widths ride the ints pack as data
        (wave-level speculation), so one compiled program serves every
        width mix the per-request controllers produce. ``use_lora``
        variants append per-slot adapter ids after the widths."""
        key = ("spec", k, g, r, mb, hh, use_lora)
        fn = self._decode_fns.get(key)
        if fn is None:
            cfg, dummy = self.cfg, self._dummy
            pp, mesh = self.mesh_spec.pp, self.mesh

            def chunk(p, ints, floats, paged):
                bt = ints[:r * mb].reshape(r, mb)
                hist = ints[r * mb:r * (mb + hh)].reshape(r, hh)
                rest = ints[r * (mb + hh):]
                if use_lora:
                    (tokens, cl, seeds, steps0, tks, budget, eos_ids,
                     ds, gammas, aids) = rest.reshape(10, r)
                else:
                    (tokens, cl, seeds, steps0, tks, budget, eos_ids,
                     ds, gammas) = rest.reshape(9, r)
                    aids = None
                temps, tps = floats
                if pp > 1:
                    from distributed_llm_inferencing_tpu.parallel import (
                        paged_pipeline)
                    return paged_pipeline.paged_speculative_chunk_pp(
                        p, cfg, k, g, tokens, hist, paged, bt, cl, seeds,
                        steps0, temps, tks, tps, ds.astype(bool), budget,
                        eos_ids, dummy, gammas=gammas, mesh=mesh)
                return transformer.paged_speculative_chunk(
                    p, cfg, k, g, tokens, hist, paged, bt, cl, seeds,
                    steps0, temps, tks, tps, ds.astype(bool), budget,
                    eos_ids, dummy, gammas=gammas, lora_ids=aids)

            fn = jax.jit(chunk, donate_argnums=(3,))
            self._decode_fns[key] = fn
        return fn

    def warm_decode_programs(self) -> int:
        """AOT-compile (jit.lower().compile()) every decode-chunk program
        this scheduler can dispatch — the plain chunk per DECODE_CHUNKS
        size and, with speculation, each distinct ceil(k/(gamma+1))
        verify variant (plus the halved-gamma statics the wave-off global
        controller can request) — and install the compiled executables
        in the program cache.

        A speculative trajectory's chunk-size sequence is
        acceptance-dependent, so workload warmup cannot cover the
        program space: a late-appearing tail variant then pays its XLA
        compile inside a measured window (or a live request's ITL).
        Bench legs call this after their admission warmup; serving can
        call it at model-load time. Returns the number of programs
        compiled. No-op for programs already warm (AOT executables feed
        the persistent compilation cache, so repeat processes pay
        deserialization, not compilation)."""
        r, mb = self.slots, self.max_blocks
        paged_sds = jax.tree_util.tree_map(
            lambda a: (None if a is None else
                       jax.ShapeDtypeStruct(a.shape, a.dtype)),
            self.paged)
        floats = jax.ShapeDtypeStruct((2, r), jnp.float32)
        toks = jax.ShapeDtypeStruct((r,), jnp.int32)
        n = 0
        with self.mesh:
            for k in self.decode_chunks:
                fn = self._decode_jit(k, r, mb)
                if hasattr(fn, "lower"):   # not yet AOT-compiled
                    ints = jax.ShapeDtypeStruct((r * (mb + 7),), jnp.int32)
                    self._decode_fns[(k, r, mb, False)] = fn.lower(
                        self.params, toks, ints, floats,
                        paged_sds).compile()
                    n += 1
                if not (self.speculative and self.spec_gamma >= 1):
                    continue
                gs = {self.spec_gamma}
                if not self.spec_wave:
                    g = self.spec_gamma   # global-controller halvings
                    while g > 2:
                        g = max(2, g // 2)
                        gs.add(g)
                hh = self._hist.shape[1]
                for g in gs:
                    k_it = -(-k // (g + 1))
                    sfn = self._spec_jit(k_it, g, r, mb, hh)
                    if hasattr(sfn, "lower"):
                        ints = jax.ShapeDtypeStruct(
                            (r * (mb + hh + 9),), jnp.int32)
                        self._decode_fns[("spec", k_it, g, r, mb, hh,
                                          False)] = \
                            sfn.lower(self.params, ints, floats,
                                      paged_sds).compile()
                        n += 1
        return n

    # ---- program launch (shared by the scheduler and lockstep replay) --

    def _run_admit(self, a: dict) -> np.ndarray:
        """Launch one admission wave's program from a JSON-safe arg dict.
        Pure device-program execution: no scheduler state is read, so a
        follower replaying the leader's args evolves its cache shard
        bit-identically. Returns first tokens [B]."""
        toks = np.asarray(a["toks"], np.int32)
        tb = np.asarray(a["tail_alloc"], np.int32)
        pfb = np.asarray(a["pfb"], np.int32)
        b = toks.shape[0]
        use_lora = "aids" in a
        ints = np.concatenate([
            toks.reshape(-1), tb.reshape(-1), pfb.reshape(-1),
            np.asarray(a["tail_len"], np.int32),
            np.asarray(a["cached"], np.int32),
            np.asarray(a["seeds"], np.int32),
            np.asarray(a["steps"], np.int32),
            np.asarray(a["tks"], np.int32),
            np.asarray(a["ds"], np.int32)] + (
            [np.asarray(a["aids"], np.int32)] if use_lora else []))
        floats = np.stack([np.asarray(a["temps"], np.float32),
                           np.asarray(a["tps"], np.float32)])
        fn = self._admit_jit(toks.shape[1], pfb.shape[1], b, use_lora)
        with self.mesh:
            first, self.paged = fn(self._wave_params(use_lora),
                                   jnp.asarray(ints),
                                   jnp.asarray(floats), self.paged)
            return np.asarray(first)   # ONE host sync per admission wave

    def _run_decode(self, a: dict, tokens_dev=None, sync: bool = True):
        """Launch one decode chunk's program from a JSON-safe arg dict.
        Returns (toks [K, R], emits [K, R]) — host arrays when ``sync``
        (the default: ONE host sync per chunk), device arrays otherwise
        (the double-buffered step syncs two chunks at once).
        ``tokens_dev`` overrides ``a["tokens"]`` with a device-resident
        [R] token vector — chunk N's last sampled tokens feed chunk N+1
        without ever visiting the host."""
        bt = np.asarray(a["bt"], np.int32)
        r, mb = bt.shape
        use_lora = "aids" in a
        ints = np.concatenate([bt.reshape(-1)] + [
            np.asarray(a[key], np.int32) for key in
            ("cl", "seeds", "steps", "tks", "budget", "eos", "ds")] + (
            [np.asarray(a["aids"], np.int32)] if use_lora else []))
        floats = np.stack([np.asarray(a["temps"], np.float32),
                           np.asarray(a["tps"], np.float32)])
        fn = self._decode_jit(int(a["k"]), r, mb, use_lora)
        with self.mesh:
            with self.profiler.phase("dispatch"):
                tokens = (tokens_dev if tokens_dev is not None
                          else jnp.asarray(np.asarray(a["tokens"],
                                                      np.int32)))
                toks, emits, self.paged = fn(self._wave_params(use_lora),
                                             tokens,
                                             jnp.asarray(ints),
                                             jnp.asarray(floats),
                                             self.paged)
            if not sync:
                return toks, emits
            with self.profiler.phase("device_wait"):
                return jax.device_get((toks, emits))

    def _hist_deltas(self) -> list:
        """JSON-safe per-slot history deltas for the lockstep broadcast:
        ``[slot, offset, tokens]`` for every active row the followers are
        behind on. Non-empty only right after a slot (re)admission — every
        other append is derived from replayed program outputs on both
        sides — so the broadcast is O(new prompt), not O(slots * max_seq)
        per chunk. Advances the watermark."""
        out = []
        for r in range(self.slots):
            if self.active[r] is None:
                continue
            k = min(int(self.context_lens[r]) + 1, self.max_seq + 1)
            s = int(self._hist_synced[r])
            if k > s:
                out.append([r, s, self._hist[r, s:k].tolist()])
                self._hist_synced[r] = k
        return out

    def _apply_spec_hist(self, toks, keeps, cl):
        """Mirror a speculative chunk's kept tokens into the drafting
        history. Pure function of the program's (inputs, outputs), so the
        leader and every replaying follower evolve identical rows without
        the history ever riding the broadcast."""
        for r in range(keeps.shape[1]):
            pos = int(cl[r]) + 1
            kept = 0
            for t in range(keeps.shape[0]):
                for tok in toks[t, r, : int(keeps[t, r])]:
                    if pos <= self.max_seq:
                        self._hist[r, pos] = int(tok)
                    pos += 1
                    kept += 1
            if self._hist_synced is not None and kept:
                self._hist_synced[r] = min(self._hist_synced[r] + kept,
                                           self.max_seq + 1)

    def _apply_plain_hist(self, toks, emits, cl):
        """Mirror a PLAIN decode chunk's emitted tokens into the drafting
        history: the adaptive speculation controller interleaves plain
        chunks (fallback / probes) into a speculative batcher, and stale
        history rows would draft garbage (rejected — correct but wasted).
        The plain case IS the spec case at draft width 1 — ``emits`` is a
        monotone 0/1 keeps column — so the lockstep-critical watermark
        arithmetic lives once, in _apply_spec_hist. No-op when drafting
        is off."""
        if self._hist is None:
            return
        self._apply_spec_hist(np.asarray(toks)[:, :, None],
                              np.asarray(emits).astype(np.int32), cl)

    def _run_spec_decode(self, a: dict):
        """Launch one speculative chunk's program. Returns (toks
        [K, R, g+1], keeps [K, R], eos_seen [K, R]) as host arrays —
        ``eos_seen`` is cumulative per row, distinguishing an eos death
        from merely running out of chunk iterations."""
        bt = np.asarray(a["bt"], np.int32)
        if "hist" in a:
            hist = np.asarray(a["hist"], np.int32)
        else:   # lockstep replay: apply the leader's deltas to our copy
            for r, off, row in a.get("hist_delta") or []:
                self._hist[r, off:off + len(row)] = row
            hist = self._hist
        r, mb = bt.shape
        gammas = np.asarray(
            a.get("gammas") or [int(a["gamma"])] * r, np.int32)
        use_lora = "aids" in a
        ints = np.concatenate([bt.reshape(-1), hist.reshape(-1)] + [
            np.asarray(a[key], np.int32) for key in
            ("tokens", "cl", "seeds", "steps", "tks", "budget", "eos", "ds")
        ] + [gammas] + (
            [np.asarray(a["aids"], np.int32)] if use_lora else []))
        floats = np.stack([np.asarray(a["temps"], np.float32),
                           np.asarray(a["tps"], np.float32)])
        fn = self._spec_jit(int(a["k"]), int(a["gamma"]), r, mb,
                            hist.shape[1], use_lora)
        # draft+verify run fused in one device program; the profiler
        # attributes the whole dispatch+sync to the verify phase (the
        # host-side drafting state prep is tagged spec_draft by the step)
        with self.mesh:
            with self.profiler.phase("spec_verify"):
                toks, keeps, eos_seen, self.paged = fn(
                    self._wave_params(use_lora), jnp.asarray(ints),
                    jnp.asarray(floats), self.paged)
                return jax.device_get((toks, keeps, eos_seen))

    def _wave_params(self, use_lora: bool):
        """The parameter tree a wave's program runs against: the base
        tree, or — when any slot in the wave carries an adapter id — the
        LoRA-augmented tree whose ``layers`` dict gains the stacked
        device pack. Same structure and shapes every rebuild, so the
        use_lora=True program never recompiles across adapter mixes."""
        if not use_lora:
            return self.params
        if self._params_lora is None:
            raise RuntimeError(
                "wave carries adapter ids but no LoRA pack is built")
        return self._params_lora

    def replay(self, kind: str, args: dict):
        """Re-execute a program the lockstep leader broadcast. SPMD
        correctness requires every host to launch identical programs in
        identical order — the caller (LockstepFollower) provides the
        ordering; identical args provide the identity."""
        if kind == "admit":
            self._run_admit(args)
        elif kind == "decode":
            if self._hist is not None:
                # admission-time rows ride the broadcast (see
                # _dispatch_plain_chunk); appends derive from outputs
                for r, off, row in args.get("hist_delta") or []:
                    self._hist[r, off:off + len(row)] = row
            toks, emits = self._run_decode(args)
            # adaptive speculation interleaves plain chunks: followers
            # mirror the leader's history appends from program outputs
            self._apply_plain_hist(toks, emits,
                                   np.asarray(args["cl"], np.int32))
        elif kind == "spec_decode":
            toks, keeps, _ = self._run_spec_decode(args)
            if "hist" not in args:
                # mirror the leader's host-side history appends from the
                # program's own outputs (see _apply_spec_hist)
                self._apply_spec_hist(toks, keeps,
                                      np.asarray(args["cl"], np.int32))
        else:
            raise ValueError(f"unknown batcher program kind {kind!r}")

    # ---- scheduling ---------------------------------------------------

    def _bucket_tail(self, n: int) -> int:
        for m in TAIL_BUCKETS_X_BS:
            if n <= m * self.block_size:
                return min(m * self.block_size,
                           self.max_blocks * self.block_size)
        raise ValueError(f"tail of {n} tokens exceeds buckets")

    def _bucket_prefix(self, nb: int) -> int:
        for m in PREFIX_BUCKETS:
            if nb <= m:
                return min(m, self.max_blocks) if m else 0
        raise ValueError(f"prefix of {nb} blocks exceeds buckets")

    @staticmethod
    def _bucket_wave(n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return b

    def _shared_wave_blocks(self, wave: List[dict], prompt: List[int]) -> int:
        """Longest common full-block prefix (in blocks) between `prompt`
        and any prompt already in the admission wave."""
        bs = self.block_size
        best = 0
        for m in wave:
            n = 0
            for a, b in zip(m["prompt"], prompt):
                if a != b:
                    break
                n += 1
            best = max(best, n // bs)
        return best

    # ---- host KV tier (offload on evict, restore on admission) --------

    def _offload_evicted(self, evictions):
        """Eviction hook (native BlockPool.set_evict_hook): copy each
        evicted radix block's still-resident device KV pages into the
        host arena, keyed by the block's token-chain digest. Runs
        synchronously inside ``pool.alloc`` — after the block id returns
        to the free list but before any program that could overwrite it
        is dispatched, which is exactly the window where the device bytes
        are still the evicted prefix's KV. One batched device->host
        gather covers every block the alloc evicted."""
        if self.kvtier is None or self.program_hook is not None:
            return
        ev = [(b, toks) for b, toks in evictions if toks]
        if not ev:
            return
        # a restored block's arena entry stays resident (HostKVArena.get
        # keeps it), so its re-eviction needs no copy at all — filter
        # before the gather, which is a blocking device sync
        digs = [self.kvtier.block_digests(toks)[-1] for _, toks in ev]
        keep = [j for j, d in enumerate(digs)
                if not self.kvtier.arena.peek(d)]
        if not keep:
            return
        w0 = clock.now()
        idx = np.asarray([ev[j][0] for j in keep], np.int32)
        leaves = [lf for lf in self.paged if lf is not None]
        with self.mesh:
            pages = jax.device_get([lf[:, idx] for lf in leaves])
        stored = 0
        nbytes = 0
        for col, j in enumerate(keep):
            cols = [p[:, col] for p in pages]
            if self.kvtier.arena.put(digs[j], cols):
                stored += 1
                nbytes += sum(c.nbytes for c in cols)
        self.metrics.inc("kvtier_offloaded_blocks", stored)
        if self._admitting is not None and nbytes:
            # cost ledger: the alloc that evicted these blocks belongs to
            # the request currently admitting/growing — its ledger shows
            # the device->host traffic it displaced
            self._admitting._arena_offloaded_bytes += nbytes
        trace.get_tracer().record(
            "batcher.kv_offload", w0, clock.now(),
            attrs={"blocks": len(ev), "stored": stored})

    def _restore_jit(self, b: int, nleaves: int):
        """Scatter ``b`` restored blocks back into every paged-cache
        leaf at once (the block axis is axis 1) — the admission-side twin
        of ops/paged_kvcache.write_block_run, but for whole blocks whose
        contents come from the host arena rather than fresh prefill."""
        fn = self._restore_fns.get(b)
        if fn is None:
            def restore(ids, vals, *leaves):
                return tuple(lf.at[:, ids].set(v.astype(lf.dtype))
                             for lf, v in zip(leaves, vals))
            fn = jax.jit(restore,
                         donate_argnums=tuple(range(2, 2 + nleaves)))
            self._restore_fns[b] = fn
        return fn

    def _run_restore(self, blocks, pages):
        """Write arena pages for ``blocks`` back to device. Row count is
        bucketed to a power of two (padding rows target the reserved
        dummy block, whose content is never read) so restores of any
        length share a handful of compiled scatters."""
        nb = len(blocks)
        b = 1
        while b < nb:
            b *= 2
        ids = np.full((b,), self._dummy, np.int32)
        ids[:nb] = blocks
        live = [lf for lf in self.paged if lf is not None]
        vals = []
        for j, lf in enumerate(live):
            # one C-level stack per leaf, not a python copy per page —
            # this runs on the scheduler thread between decode chunks
            stacked = np.stack([pg[j] for pg in pages], axis=1)
            if b == nb and stacked.dtype == lf.dtype:
                vals.append(stacked)
                continue
            v = np.zeros((lf.shape[0], b) + tuple(lf.shape[2:]),
                         dtype=lf.dtype)
            v[:, :nb] = stacked
            vals.append(v)
        fn = self._restore_jit(b, len(live))
        with self.mesh:
            new_leaves = fn(jnp.asarray(ids),
                            tuple(jnp.asarray(v) for v in vals), *live)
        it = iter(new_leaves)
        self.paged = type(self.paged)(
            *[next(it) if lf is not None else None for lf in self.paged])

    def _restore_from_arena(self, prompt, n, prefix_blocks, cached):
        """Second-tier prefix lookup on a (partial) radix miss: restore
        the longest consecutive run of arena-held blocks that extends the
        radix match, register them in the radix tree, and return the
        extended (prefix_blocks, cached). Opportunistic — any failure
        (no free device blocks, arena LRU race) simply falls back to
        prefilling that span. In native arena mode the restored bytes
        are the exact evicted bytes, so downstream outputs are bitwise
        identical to a cold prefill; in int8 mode they are the
        bounded-error dequant (ops/kvblock_quant.py)."""
        bs = self.block_size
        start = cached // bs
        limit = (n - 1) // bs   # >=1 token must remain for the tail
        if start >= limit:
            return prefix_blocks, cached
        digs = self.kvtier.block_digests(prompt[:limit * bs])
        run = []
        for i in range(start, limit):
            if self.kvtier.arena.peek(digs[i]):
                run.append(digs[i])
            else:
                break
        if not run:
            return prefix_blocks, cached
        blocks = self.pool.alloc(len(run))
        if blocks is None:
            return prefix_blocks, cached
        pages = []
        for d in run:
            pg = self.kvtier.arena.get(d)
            if pg is None:   # LRU-dropped by our own alloc's offloads
                break
            pages.append(pg)
        if len(pages) < len(blocks):
            self.pool.release(blocks[len(pages):])
            blocks = blocks[:len(pages)]
        if not blocks:
            return prefix_blocks, cached
        w0 = clock.now()
        self._run_restore(blocks, pages)
        end = start + len(blocks)
        self.pool.insert_prefix(prompt[:end * bs], blocks, skip=start)
        self.metrics.inc("kvtier_restored_blocks", len(blocks))
        self.metrics.inc("kvtier_restored_tokens", len(blocks) * bs)
        if self._admitting is not None:
            self._admitting._arena_restored_bytes += sum(
                p.nbytes for pg in pages for p in pg)
        trace.get_tracer().record(
            "batcher.kv_restore", w0, clock.now(),
            attrs={"blocks": len(blocks), "tokens": len(blocks) * bs})
        return prefix_blocks + blocks, end * bs

    def _get_kv_fetcher(self):
        """The shared peer-fetch client (worker-injected), or a lazily
        built one for standalone batchers. None only if the import
        itself fails (no requests on the box)."""
        if self.kv_fetcher is None:
            try:
                from distributed_llm_inferencing_tpu.runtime.kvwire import (
                    KVFetchClient)
                self.kv_fetcher = KVFetchClient(metrics=self.metrics)
            except Exception:
                return None
        return self.kv_fetcher

    def _fetch_into_arena(self, url, model, prompt, limit,
                          start: int = 0, progress=None) -> int:
        """Pull the arena-missing chain digests of ``prompt``'s blocks
        ``[start, limit)`` from the peer at ``url`` into the LOCAL host
        arena. A native peer's bytes are its exact evicted/exported
        device bytes (restore stays bitwise identical to a cold
        prefill); an int8 peer ships quantized records that restore to
        a bounded-error dequant. Strictly opportunistic: ANY failure —
        transport, corrupt frame, peer missing the blocks, shape drift
        — degrades to recompute, never to a request failure. Returns
        the wire bytes stored (0 on failure).

        Single-flight: concurrent calls against the same (peer, model)
        — shared-prefix fan-in, the drain of a dying node's whole
        resident set — coalesce. The first caller leads and fetches the
        deduped union of every caller's still-missing digests (one
        socket, batched rounds while new waiters keep arriving);
        waiters block on the leader and find their blocks
        arena-resident, so each digest crosses the wire exactly once.
        ``progress(stream)``, if given, runs on the LEADER's thread
        after each block lands (the receive-overlap consumer hook)."""
        bs = self.block_size
        digs = self.kvtier.block_digests(prompt[:limit * bs])
        want = [d for d in digs[start:limit]
                if not self.kvtier.arena.peek(d)]
        if not want:
            return 0
        key = (str(url), str(model))
        with self._kvf_lock:
            fl = self._kvf_inflight.get(key)
            leader = fl is None
            if leader:
                # dict-as-ordered-set: consecutive digest order survives
                # the dedup, so the leader's batch streams in scatter
                # order
                fl = {"pending": dict.fromkeys(want, True),
                      "event": threading.Event()}
                self._kvf_inflight[key] = fl
            else:
                for d in want:
                    fl["pending"].setdefault(d, True)
        if not leader:
            self.metrics.inc("kv_prefetch_coalesced")
            # leader guarantees the event fires (finally below); the
            # timeout is a backstop so a stuck transfer can only stall
            # this caller as long as its own fetch could have
            fl["event"].wait(timeout=90.0)
            return 0
        total = 0
        try:
            while True:
                with self._kvf_lock:
                    batch = [d for d in fl["pending"]
                             if not self.kvtier.arena.peek(d)]
                    fl["pending"].clear()
                if not batch:
                    break
                total += self._wire_fetch(url, model, batch,
                                          progress=progress)
                # digests still missing after the round (peer didn't
                # have them / validation refused them) were cleared
                # above: only NEW waiters' digests survive into the
                # next round, so the loop terminates when arrivals do
        finally:
            with self._kvf_lock:
                self._kvf_inflight.pop(key, None)
            fl["event"].set()
        return total

    def _admit_fetched(self, digest, obj, expect) -> bool:
        """Shape/dtype-check one fetched block against the live paged
        leaves BEFORE the arena sees it: a buggy/mismatched peer
        (different model or cache config) must degrade to recompute,
        not crash the scheduler thread inside the restore scatter.
        Quantized records check their LOGICAL specs — what they will
        dequantize to at restore time."""
        if kvq.is_quantized_block(obj):
            specs = kvq.logical_specs(obj)
        else:
            specs = [(tuple(p.shape), p.dtype) for p in obj]
        if (len(specs) != len(expect)
                or any(shp != eshp or dt != edt
                       for (shp, dt), (eshp, edt) in zip(specs, expect))):
            self.metrics.inc("kv_transfer_failures")
            return False
        return self.kvtier.arena.put(digest, obj, count_offload=False)

    def _wire_fetch(self, url, model, want, progress=None) -> int:
        """One wire transfer of ``want`` digests (single-flight leader
        body). Streams frames through kvwire.FetchStream when
        DLI_KV_WIRE_OVERLAP is on — each block is validated and
        arena-admitted as its frame decodes, with ``progress`` driving
        the caller's overlap consumer — else one blocking fetch.
        Mid-stream faults keep the blocks that already landed (valid
        arena entries); the rest recomputes."""
        fetcher = self._get_kv_fetcher()
        if fetcher is None:
            return 0
        live = [lf for lf in self.paged if lf is not None]
        expect = [((lf.shape[0],) + tuple(lf.shape[2:]), lf.dtype)
                  for lf in live]
        w0 = clock.now()
        blocks = bytes_in = 0
        err = None
        try:
            # injected fetchers may implement only the blocking API;
            # overlap is an optimization, not a contract
            if self._wire_overlap and hasattr(fetcher, "fetch_stream"):
                stream = fetcher.fetch_stream(url, model, want)
                for d, obj in stream:
                    if self._admit_fetched(d, obj, expect):
                        blocks += 1
                        bytes_in += kvwire_mod.stored_nbytes(obj)
                        if progress is not None:
                            progress(stream)
            else:
                got = fetcher.fetch(url, model, want)
                for d in want:
                    obj = got.get(d)
                    if obj is None:
                        continue   # peer didn't have it: plain recompute
                    if self._admit_fetched(d, obj, expect):
                        blocks += 1
                        bytes_in += kvwire_mod.stored_nbytes(obj)
        except Exception as e:
            self.metrics.inc("kv_transfer_failures")
            err = str(e)[:200]
        elapsed = clock.now() - w0
        self.metrics.inc("kv_transfer_blocks", blocks)
        self.metrics.inc("kv_transfer_bytes", bytes_in)
        self.metrics.inc("kv_transfer_ms", elapsed * 1e3)
        attrs = {"peer": url, "blocks": blocks, "bytes": bytes_in}
        if err:
            attrs["error"] = err
        trace.get_tracer().record(
            "batcher.kv_fetch", w0, clock.now(), attrs=attrs)
        return bytes_in

    def prefetch_kv(self, prompt: Sequence[int], kv_source) -> int:
        """Caller-thread transfer for a disaggregated request: pull the
        prompt's prefix blocks from the ``kv_source`` peer into the host
        arena BEFORE submission. The worker calls this on its HTTP
        handler thread, so the wire transfer overlaps the decode loop —
        admission then finds the blocks arena-resident and pays only the
        device scatter, instead of stalling every co-resident decode
        stream behind a blocking fetch. Returns bytes transferred (0 on
        any failure: the request simply recomputes)."""
        if (self.kvtier is None or self.program_hook is not None
                or not isinstance(kv_source, dict)):
            return 0
        url = kv_source.get("url")
        if not url:
            return 0
        prompt = list(map(int, prompt))
        limit = (len(prompt) - 1) // self.block_size
        if limit <= 0:
            return 0
        try:
            return self._fetch_into_arena(
                url, str(kv_source.get("model") or ""), prompt, limit)
        except Exception:
            self.metrics.inc("kv_transfer_failures")
            return 0

    def _restore_from_peer(self, req, prompt, n, prefix_blocks, cached):
        """Scheduler-thread fallback of :meth:`prefetch_kv` for direct
        batcher users (the worker prefetches at submit time instead and
        clears ``kv_source``): pull the request's missing block digests
        from its designated peer into the local arena. With
        DLI_KV_WIRE_OVERLAP (the default) the transfer is
        receive-overlapped: as frames land in the arena, every ~8
        blocks the consecutive run scatters to device through the
        ordinary ``_restore_from_arena`` machinery WHILE the receiver
        thread keeps pulling later frames off the socket — scatter of
        block N overlaps receive of block N+1 instead of paying
        fetch-then-scatter serially. The achieved overlap (scatter
        seconds inside the transfer wall, as a fraction) lands in the
        ``kv_restore_overlap_ratio`` gauge. Returns the (possibly
        extended) ``(prefix_blocks, cached)``."""
        src = req.kv_source
        if (src is None or req._peer_fetch_done or self.kvtier is None
                or self.program_hook is not None):
            return prefix_blocks, cached
        url = src.get("url") if isinstance(src, dict) else None
        if not url:
            req._peer_fetch_done = True
            return prefix_blocks, cached
        bs = self.block_size
        start = cached // bs
        limit = (n - 1) // bs
        if start >= limit:
            return prefix_blocks, cached
        digs = self.kvtier.block_digests(prompt[:limit * bs])
        if all(self.kvtier.arena.peek(d) for d in digs[start:limit]):
            return prefix_blocks, cached   # nothing missing: no RPC, no flag
        req._peer_fetch_done = True
        state = {"pb": prefix_blocks, "cached": cached,
                 "arrived": 0, "overlap_s": 0.0}

        def scatter_ready(stream):
            # the overlap consumer: runs on THIS (scheduler) thread
            # between the leader's frame decodes; ~8-block chunks
            # amortize the per-scatter digest walk and jit dispatch
            state["arrived"] += 1
            if state["arrived"] < 8 and not stream.receiving_done:
                return
            state["arrived"] = 0
            t0 = clock.now()
            receiving = not stream.receiving_done
            state["pb"], state["cached"] = self._restore_from_arena(
                prompt, n, state["pb"], state["cached"])
            if receiving:
                state["overlap_s"] += clock.now() - t0

        w0 = clock.now()
        got = self._fetch_into_arena(
            url, str(src.get("model") or ""), prompt, limit, start=start,
            progress=scatter_ready if self._wire_overlap else None)
        req._kv_transfer_bytes += got
        wall = clock.now() - w0
        if got and self._wire_overlap and wall > 0:
            self.metrics.gauge("kv_restore_overlap_ratio",
                               min(1.0, state["overlap_s"] / wall))
        return state["pb"], state["cached"]

    def _export_request_kv(self, req, seq=None, n_ctx=None):
        """KV export into the host arena under token-chain digests —
        the blocks a peer's ``/kv_fetch`` will ask for. Two callers:

        - finish-time export for a disaggregated prefill pass
          (``kv_export`` dispatch flag): ``seq`` defaults to the PROMPT,
          whose KV the prefill pass just wrote in full;
        - a mid-generation migration snapshot (``_service_migrations``):
          ``seq`` is prompt+emitted tokens and ``n_ctx`` the slot's
          context length — only positions whose KV is actually on
          device export (the last emitted token's KV lands with the
          NEXT chunk's input, so it is prefilled on the destination).

        Runs while the request still owns its blocks (before release),
        so the device bytes are exactly the computed prefix. Skips
        blocks the eviction path already offloaded."""
        if (self.kvtier is None or self.program_hook is not None
                or req.error or not req._blocks):
            return
        bs = self.block_size
        seq = list(req.prompt) if seq is None else list(seq)
        n = len(seq) if n_ctx is None else min(int(n_ctx), len(seq))
        n_full = min(n // bs, len(req._blocks))
        if n_full <= 0:
            return
        digs = self.kvtier.block_digests(seq[:n_full * bs])
        keep = [i for i in range(n_full)
                if not self.kvtier.arena.peek(digs[i])]
        if not keep:
            return
        w0 = clock.now()
        idx = np.asarray([req._blocks[i] for i in keep], np.int32)
        leaves = [lf for lf in self.paged if lf is not None]
        with self.mesh:
            pages = jax.device_get([lf[:, idx] for lf in leaves])
        stored = 0
        for col, i in enumerate(keep):
            cols = [p[:, col] for p in pages]
            if self.kvtier.arena.put(digs[i], cols, count_offload=False):
                stored += 1
        self.metrics.inc("kvtier_exported_blocks", stored)
        trace.get_tracer().record(
            "batcher.kv_export", w0, clock.now(),
            attrs={"blocks": n_full, "stored": stored})

    # ---- live in-flight migration ------------------------------------

    def migrate_out(self, req: BatchRequest,
                    timeout: float = 10.0) -> Optional[dict]:
        """Snapshot + evict one in-flight request (worker ``POST
        /migrate_out``): ask the scheduler to export the request's KV
        through its last context position into the host arena and hand
        back a resume record at the next chunk boundary. Blocks until
        the request is terminal either way; returns the resume record,
        or None when the request completed/failed first (the
        migrate-vs-complete race — the caller answers 409 and the
        normal result stands), cannot migrate (multi-host lockstep), or
        the scheduler never serviced the flag within ``timeout``."""
        if self.program_hook is not None:
            return None          # lockstep: host-side evict can't ride
        req._migrate_requested = True
        self._work.set()
        if not req.done.wait(timeout):
            req._migrate_requested = False
            return None
        return req.resume_record if req._migrated else None

    def _service_migrations(self):
        """Run at every step boundary: snapshot+evict requests flagged
        by :meth:`migrate_out`. Active slots export their computed KV
        (the destination's ``/kv_fetch`` + arena restore turns the
        resume into a scatter + one-token tail prefill instead of a
        re-prefill); queued requests hand off their resume record alone
        — their KV, if any, is radix-resident and exports on eviction
        like always."""
        pending = any(a is not None and a._migrate_requested
                      for a in self.active)
        with self._lock:
            queued = [r for r in self.queue if r._migrate_requested]
            for r in queued:
                self.queue.remove(r)
        for req in queued:
            self._finish_migrated(req)
        if not pending:
            return
        for slot in range(self.slots):
            req = self.active[slot]
            if req is None or not req._migrate_requested:
                continue
            try:
                self._export_request_kv(
                    req, seq=req.prompt + req.tokens,
                    n_ctx=int(self.context_lens[slot]))
            except Exception as e:
                log.warning("migration KV export failed for slot %d "
                            "(%r); destination will recompute", slot, e)
            # free like a preemption: the radix keeps refcount-0
            # leaves warm, the arena holds the export for /kv_fetch
            self.pool.release(req._blocks)
            req._blocks = []
            self.active[slot] = None
            self.block_tables[slot, :] = self._dummy
            self.context_lens[slot] = 0
            if slot in self._admit_order:
                self._admit_order.remove(slot)
            self._finish_migrated(req)

    def _finish_migrated(self, req: BatchRequest):
        """Terminal "handed off" outcome. The resume record is
        everything a destination batcher needs to continue bitwise-
        exactly: emitted tokens (the stream cursor — the destination
        re-emits nothing), the seed whose position-keyed PRNG makes the
        continued sampled stream draw the same tokens, the sampler
        budget/eos, and the spec-controller policy state."""
        req.resume_record = {
            "prompt_tokens": list(req.prompt),
            "tokens": list(req.tokens),
            "seed": int(req.seed),
            "steps": len(req.tokens),
            "max_new_tokens": int(req.max_new_tokens),
            "eos_token_id": req.eos_token_id,
            "spec": (req._spec_ctl.export_state()
                     if req._spec_ctl is not None else None),
            "adapter": req.adapter,
        }
        req._migrated = True
        req.error = "migrated"
        req.finished_at = clock.now()
        self._observe_finished(req)
        req.done.set()

    def _gauge_stall_streak(self, req):
        """chunk_prefill_stall_streak = the WORST current streak across
        chunked-prefill requests, not the last writer's — one progressing
        prompt must not zero the gauge while another sits one stall from
        a 'pool exhausted' failure (``req`` is mid-admission, so it is
        not in the queue)."""
        with self._lock:
            worst = max((r._chunk_stalls for r in self.queue), default=0)
        self.metrics.gauge("chunk_prefill_stall_streak",
                           max(worst, req._chunk_stalls))

    def _sync_cache_metrics(self):
        """Mirror the native pool's lifetime radix counters — and the
        host arena's occupancy — into the metrics registry, so the
        cluster-metrics pipeline (master /api/cluster_metrics) sees them:
        until now prefix_hits/misses lived only in ``stats()["pool"]``,
        invisible to /metrics scrapes."""
        st = self.pool.stats()
        last = self._last_pool_stats
        for key, mname in (("prefix_hits", "radix_prefix_hits"),
                           ("prefix_misses", "radix_prefix_misses"),
                           ("evictions", "radix_evictions")):
            d = st[key] - last.get(key, 0)
            # inc even when 0: the counter must EXIST in /metrics from
            # the first step (a scraper can't tell "no hits yet" from
            # "metric not exported" otherwise)
            self.metrics.inc(mname, max(0, d))
            last[key] = st[key]
        if self.kvtier is not None:
            a = self.kvtier.arena.stats()
            self.metrics.gauge("kvtier_host_blocks", a["blocks"])
            # stored (possibly quantized) bytes — the honest budget
            # fraction; logical_bytes is the full-precision equivalent,
            # so stored/logical exposes the arena's compression ratio
            self.metrics.gauge("kvtier_host_bytes", a["bytes"])
            self.metrics.gauge("kvtier_logical_bytes", a["logical_bytes"])
            self.metrics.gauge(
                "kvtier_occupancy",
                a["bytes"] / max(1, a["capacity_bytes"]))

    def _prep_admit(self, req: BatchRequest) -> Optional[dict]:
        """Host-side admission prep: radix prefix match + block allocation.
        None if blocks are unavailable (caller decides preempt/requeue).

        For a preempted request the already-generated tokens are part of
        the prefill (generation resumes where it left off — streamed
        tokens are never re-emitted).
        """
        if req.adapter:
            # bind the adapter to a device slot now (not at submit):
            # slots are a wave-level resource, and admission is where
            # the request joins a wave. All-slots-pinned raises — the
            # caller fails the request rather than silently serving
            # base weights.
            req._lora_slot = self._assign_lora_slot(req.adapter)
        bs = self.block_size
        prompt = req.prompt + req.tokens
        n = len(prompt)
        # Leave >=1 token for the tail: prefill must produce the last
        # token's logits (a fully-cached prompt would have nothing to run).
        prefix_blocks, cached = self.pool.match_prefix(prompt[:n - 1])
        if self.kvtier is not None and self.program_hook is None:
            # tier 2b: a disaggregated request pulls its missing prefix
            # blocks from the prefill peer, receive-overlapped — the
            # consecutive runs scatter while later frames are still on
            # the wire (runtime/kvwire.py; any failure degrades to
            # recompute) ...
            prefix_blocks, cached = self._restore_from_peer(
                req, prompt, n, prefix_blocks, cached)
            # ... then tier 2: extend the radix match from the host
            # arena — the streamed tail plus anything already resident —
            # before falling back to recompute (multi-host lockstep opts
            # out: a host-initiated scatter cannot ride the program
            # broadcast)
            prefix_blocks, cached = self._restore_from_arena(
                prompt, n, prefix_blocks, cached)
        tail_alloc = []
        partial = False
        try:
            tail_len = n - cached
            cap = (self.prefill_chunk * bs) if self.prefill_chunk else None
            if cap is not None and tail_len > cap:
                # chunked prefill: run only the next `cap` tokens (block
                # aligned — `cached` is whole blocks and cap is too), so
                # >= 1 token always remains for the sampling admission
                partial = True
                tail_len = cap
                n = cached + cap
            t = self._bucket_tail(tail_len)      # may raise ValueError
            tail_alloc = self.pool.alloc(t // bs)
            if tail_alloc is None:
                self.pool.release(prefix_blocks)
                return None
            pb = max(self._bucket_prefix(len(prefix_blocks)), 1)
        except ValueError:
            # refuse-the-request path: drop the references this prep took,
            # or repeated oversized requests pin radix blocks forever
            self.pool.release(prefix_blocks)
            self.pool.release(tail_alloc or [])
            raise
        return {"t": t, "pb": pb, "n": n, "cached": cached,
                "tail_len": tail_len, "prompt": prompt, "partial": partial,
                "prefix_blocks": prefix_blocks, "tail_alloc": tail_alloc}

    def _admit_wave(self):
        """Admit queued requests into free slots as bucketed waves: one
        batched program per (tail, prefix) bucket group.

        Chunked-prefill (partial) members need no slot — their chunk only
        writes KV into the radix cache — so a long prompt keeps making
        admission progress even when every decode slot is busy. One
        partial per wave: it requeues to the front, and pulling the queue
        past a front request that is mid-prefill would break FIFO order.
        """
        wave: List[dict] = []
        taken: set = set()
        while True:
            free = [i for i, a in enumerate(self.active)
                    if a is None and i not in taken]
            if not free:
                # no decode slot — only worth popping if the head could
                # chunk-admit (needs no slot); cheap length pre-filter,
                # the authoritative partial decision is _prep_admit's
                cap = (self.prefill_chunk or 0) * self.block_size
                with self._lock:
                    head = self.queue[0] if self.queue else None
                if (head is None or cap == 0 or head._noslot_bounce
                        or len(head.prompt) + len(head.tokens) - 1 <= cap):
                    break
            with self._lock:
                req = self.queue.popleft() if self.queue else None
            if req is None:
                break
            req._noslot_bounce = False   # re-marked below if it bounces again
            if req._cancelled:
                self._fail_req(req, "cancelled")
                continue
            try:
                # cost-ledger attribution window: arena offloads fired
                # by this prep's allocs bill to this request
                self._admitting = req
                prep = self._prep_admit(req)
            except ValueError as e:
                self._fail_req(req, str(e))
                continue
            finally:
                self._admitting = None
            if (prep is not None and wave
                    and (self._shared_wave_blocks(wave, prep["prompt"])
                         * self.block_size > prep["cached"])):
                # an earlier wave member is about to insert a longer shared
                # prefix into the radix cache than this request would hit
                # now — defer one chunk so the re-match reuses those blocks
                # (saves both the blocks and the prefill compute)
                self.pool.release(prep["prefix_blocks"])
                self.pool.release(prep["tail_alloc"])
                self._requeue_front(req)
                break
            if prep is None:
                if wave:
                    # part of the wave is already allocated — admit it now,
                    # retry this request FIRST next step
                    self._requeue_front(req)
                    break
                # Free memory by preempting the youngest slot, then retry
                # this request FIRST next step (it goes in front of the
                # preempted one, or ping-pong would starve it).
                preempted = self._preempt_youngest()
                if not preempted and not self._admit_order:
                    # no active slots to free: this prompt can never fit
                    self._fail_req(req, "KV block pool exhausted")
                else:
                    self._requeue_front(req)
                break
            prep["req"] = req
            if prep["partial"]:
                prep["slot"] = None
                wave.append(prep)
                break
            if not free:
                # a full admission does need a slot; put the request back
                # and run whatever the wave already holds. Mark it so the
                # no-slot pre-filter above stops re-popping (and
                # re-prepping) it every step until a slot frees.
                req._noslot_bounce = True
                self.pool.release(prep["prefix_blocks"])
                self.pool.release(prep["tail_alloc"])
                self._requeue_front(req)
                break
            prep["slot"] = free[0]
            taken.add(free[0])
            wave.append(prep)

        if not wave:
            return
        groups: dict = {}
        for m in wave:
            groups.setdefault((m["t"], m["pb"]), []).append(m)
        for (t, pb), members in groups.items():
            self._admit_group(t, pb, members)

    def _admit_group(self, t: int, pb: int, members: List[dict]):
        """One batched admission program for wave members sharing a
        (tail-bucket, prefix-bucket); rows padded to a power-of-two wave
        size (padding rows write only the reserved dummy block)."""
        bs = self.block_size
        b = self._bucket_wave(len(members))
        if self.mesh_spec.pp > 1:   # wave rows microbatch over pp stages
            b = -(-b // self.mesh_spec.pp) * self.mesh_spec.pp
        toks = np.zeros((b, t), np.int32)
        tail_len = np.ones((b,), np.int32)
        tail_blocks = np.full((b, t // bs), self._dummy, np.int32)
        pfb = np.full((b, pb), self._dummy, np.int32)
        cached = np.zeros((b,), np.int32)
        seeds = np.zeros((b,), np.int32)
        steps = np.zeros((b,), np.int32)
        temps = np.full((b,), 1.0, np.float32)
        tks = np.zeros((b,), np.int32)
        tps = np.ones((b,), np.float32)
        ds = np.zeros((b,), bool)
        aids = np.zeros((b,), np.int32)
        for j, m in enumerate(members):
            req = m["req"]
            toks[j, :m["tail_len"]] = \
                m["prompt"][m["cached"]:m["cached"] + m["tail_len"]]
            tail_len[j] = m["tail_len"]
            tail_blocks[j, :] = m["tail_alloc"]
            pfb[j, :len(m["prefix_blocks"])] = m["prefix_blocks"]
            cached[j] = m["cached"]
            sp = req.sampling
            seeds[j] = req.seed
            steps[j] = len(req.tokens)
            temps[j] = sp.temperature
            tks[j] = sp.top_k
            tps[j] = sp.top_p
            ds[j] = sp.do_sample
            aids[j] = req._lora_slot

        admit_args = {
            "toks": toks.tolist(), "tail_len": tail_len.tolist(),
            "tail_alloc": tail_blocks.tolist(), "pfb": pfb.tolist(),
            "cached": cached.tolist(), "seeds": seeds.tolist(),
            "steps": steps.tolist(), "temps": temps.tolist(),
            "tks": tks.tolist(), "tps": tps.tolist(), "ds": ds.tolist(),
        }
        if aids.any():
            # the key's PRESENCE selects the lora program variant — a
            # base-only wave compiles/runs the unaugmented program, and
            # lockstep followers replaying the args pick the same one
            admit_args["aids"] = aids.tolist()
        w0 = clock.now()
        for m in members:
            # cost ledger: queue phase ends when the FIRST wave carrying
            # the request starts dispatching (chunked-prefill passes and
            # preemption re-admissions keep the original stamp)
            if m["req"].admitted_at is None:
                m["req"].admitted_at = w0
        if self.program_hook is not None:
            first = self.program_hook("admit", admit_args,
                                      lambda: self._run_admit(admit_args))
        else:
            first = self._run_admit(admit_args)
        w1 = clock.now()
        self.metrics.observe("batcher_admit_wave", w1 - w0)
        trace.get_tracer().record(
            "batcher.admit_wave", w0, w1,
            attrs={"members": len(members), "rows": b,
                   "tail_bucket": t, "prefix_bucket": pb})
        for j, m in enumerate(members):
            self._post_admit(m, int(first[j]))

    def _post_admit(self, m: dict, first: int):
        """Register one admitted wave member: release padding blocks, enter
        the prompt's full blocks into the radix cache, bind the slot, and
        emit the fused-sampled first token.

        Chunked-prefill members (m["partial"]) stop after the radix
        registration: their KV now lives in the prefix cache, so the
        request requeues (front) and the next wave's match_prefix resumes
        one chunk further — no slot is bound and the chunk program's
        sampled token is discarded (it isn't the prompt's last position).
        """
        req, slot = m["req"], m["slot"]
        bs = self.block_size
        n, cached, tail_len = m["n"], m["cached"], m["tail_len"]
        tail_alloc, prefix_blocks = m["tail_alloc"], m["prefix_blocks"]
        # prefill amortization counters (bench --scenario prefix_cache
        # A/Bs the cluster-wide cached fraction): tokens served from the
        # cache tiers vs tokens actually run through prefill — counted at
        # real admission, not at prep (a rolled-back wave-overflow prep
        # would double count), and only BEYOND the request's own prior
        # extent (a resumed chunk pass re-matching its own pass-N-1
        # blocks is not a cache win)
        self.metrics.inc("prefill_cached_tokens",
                         max(0, cached - req._prefill_counted))
        self.metrics.inc("prefill_uncached_tokens", tail_len)
        # cost ledger mirrors the cluster counters' exact expressions, so
        # a request's record reconciles with the kvtier metrics deltas
        req._cost_cached += max(0, cached - req._prefill_counted)
        req._cost_uncached += tail_len
        req._prefill_counted = max(req._prefill_counted, n)
        tail_real = tail_alloc[: -(-tail_len // bs)]
        self.pool.release(tail_alloc[len(tail_real):])  # padding blocks

        # register the prompt's full blocks in the radix cache
        n_full = n // bs
        skip = cached // bs
        if n_full > skip:
            self.pool.insert_prefix(m["prompt"][:n_full * bs],
                                    tail_real[:n_full - skip], skip)

        if m.get("partial"):
            # drop our references — the radix keeps the chunk's blocks
            # alive (refcount-0 leaves evict only under pool pressure,
            # in which case the re-match simply re-prefills that chunk)
            self.pool.release(prefix_blocks)
            self.pool.release(tail_real)
            self._chunked_admissions += 1
            if n > req._chunk_high:
                req._chunk_high = n
                req._chunk_stalls = 0
                self._gauge_stall_streak(req)
            else:
                # eviction between passes undid progress; bounded, or two
                # pool-sized prompts could re-prefill each other forever.
                # Surfaced as a counter + streak gauge so operators see
                # cache-pressure thrash BEFORE it becomes a stall/failure
                # (docs/serving.md "Prefix-cache tier").
                req._chunk_stalls += 1
                self.metrics.inc("chunk_prefill_stalls")
                self._gauge_stall_streak(req)
                if req._chunk_stalls > 4:
                    self.metrics.inc("chunk_prefill_stall_failures")
                    self._fail_req(req, "KV block pool exhausted "
                                        "(chunked prefill made no progress)")
                    return
            if not req._cancelled:
                self._requeue_front(req)
            else:
                self._fail_req(req, "cancelled")
            return

        req._blocks = prefix_blocks + tail_real
        req._kv_peak = max(req._kv_peak, len(req._blocks))
        self.block_tables[slot, :] = self._dummy
        owned = prefix_blocks + tail_real
        self.block_tables[slot, :len(owned)] = owned
        self.context_lens[slot] = n
        self.active[slot] = req
        self._admit_order.append(slot)
        if self._hist is not None:
            known = m["prompt"][: self.max_seq + 1]
            self._hist[slot, : len(known)] = known
            self._hist_synced[slot] = 0   # row rewritten: full re-sync
        if req.first_token_at is None:
            req.first_token_at = clock.now()
        self._emit(req, first)
        if self._hist is not None and req.tokens:
            # the fused-sampled first token extends the history
            self._hist[slot, min(n, self.max_seq)] = req.tokens[-1]
        if req.done.is_set() or len(req.tokens) >= req.max_new_tokens:
            self._finish_slot(slot)

    def _requeue_front(self, req: BatchRequest):
        """Put a request back at the queue head (chunked-prefill resume,
        preemption, wave overflow) — one counted path for every retry."""
        self.metrics.inc("batcher_requeues")
        with self._lock:
            self.queue.appendleft(req)

    def _fail_req(self, req: BatchRequest, error: Optional[str] = None):
        """Terminal failure for a request that never reaches _finish_req
        (cancelled in queue, admission refusal, pool exhaustion, scheduler
        stop/error) — same metrics/trace accounting as a normal finish, so
        submitted always reconciles with completed+failed."""
        req.error = req.error or error or "failed"
        req.finished_at = req.finished_at or clock.now()
        self._observe_finished(req)
        req.done.set()

    def _emit(self, req: BatchRequest, token: int):
        """Append a sampled token; mark done on eos (eos not kept)."""
        if req.eos_token_id is not None and token == req.eos_token_id:
            self._finish_req(req)
            return
        now = clock.now()
        if req._last_emit_at is not None:
            # per-GAP inter-token latency: near-zero inside a chunk's
            # burst, chunk-sized at boundaries, and stall-sized across a
            # preemption/re-prefill — a per-request mean would average
            # that 2s pause invisible
            gap = now - req._last_emit_at
            self.metrics.observe("batcher_inter_token", gap)
            # per-request gap list for the cost record's ITL p95 (the
            # SLO evaluator's per-request signal); bounded by the
            # request's own max_new_tokens, freed with the request
            req._gaps.append(gap)
        req._last_emit_at = now
        req.tokens.append(token)
        self._tokens_out += 1
        if req.stream_cb:
            try:
                req.stream_cb(token)
            except Exception as e:
                # delivery is best-effort (the client likely vanished),
                # but a broken callback must not fail silently forever
                if not getattr(req, "_stream_cb_warned", False):
                    req._stream_cb_warned = True
                    log.warning("stream callback failed for request "
                                "%s (%r); further tokens buffered only",
                                getattr(req, "request_tag", "?"), e)

    def _finish_req(self, req: BatchRequest):
        if req.kv_export:
            # disaggregated prefill pass: park the prompt's KV in the
            # host arena (while the blocks are still owned) so the
            # decode peer's /kv_fetch finds it
            try:
                self._export_request_kv(req)
            except Exception as e:
                # export is best-effort; the peer recomputes — but the
                # disagg plan paid for this prefill expecting a transfer
                log.warning("kv export failed for request %s (%r); "
                            "decode peer will recompute",
                            getattr(req, "request_tag", "?"), e)
        self.pool.release(req._blocks)
        req._blocks = []
        req.finished_at = clock.now()
        self._observe_finished(req)   # before done.set(): a waiter may
        req.done.set()                # scrape /metrics|/api/trace at once

    def _cost_record(self, req: BatchRequest, end: float) -> dict:
        """Assemble the request's cost-ledger record. The three phases
        partition [submitted_at, end) exactly — queue ends when the
        first admission wave starts dispatching, prefill ends at the
        first token, decode ends at finish — so queue + prefill + decode
        sum to the e2e span by construction (preemption re-prefills land
        in the decode phase, where the stall actually happened)."""
        admitted = req.admitted_at if req.admitted_at is not None else end
        first = req.first_token_at if req.first_token_at is not None \
            else admitted
        gaps = sorted(req._gaps)
        cost = {
            "queue_ms": round(max(0.0, admitted - req.submitted_at) * 1e3,
                              3),
            "prefill_ms": round(max(0.0, first - admitted) * 1e3, 3),
            "decode_ms": round(max(0.0, end - first) * 1e3, 3),
            "prefill_cached_tokens": req._cost_cached,
            "prefill_uncached_tokens": req._cost_uncached,
            "decode_tokens": len(req.tokens),
            "weight_passes": req._weight_passes,
            "kv_blocks_peak": req._kv_peak,
            "arena_restored_bytes": req._arena_restored_bytes,
            "arena_offloaded_bytes": req._arena_offloaded_bytes,
            "kv_transfer_bytes": req._kv_transfer_bytes,
            "spec_accepted_tokens": req._spec_acc,
            "spec_rejected_tokens": req._spec_rej,
            "spec_drafted_tokens": req._spec_drafted,
            "preemptions": req._preemptions,
        }
        if gaps:
            cost["itl_p95_ms"] = round(
                gaps[min(len(gaps) - 1, int(len(gaps) * 0.95))] * 1e3, 3)
            cost["itl_max_ms"] = round(gaps[-1] * 1e3, 3)
        return cost

    def _observe_finished(self, req: BatchRequest):
        """Per-request histograms + retroactive trace spans, reconstructed
        from the request's own timestamps (the scheduler thread has no
        ambient trace context — the link rides req.trace_ctx), plus the
        cost-ledger record the worker returns with the result."""
        self._release_lora(req)   # every terminal outcome funnels here
        m = self.metrics
        m.inc("batcher_requests_migrated" if req._migrated
              else "batcher_requests_failed" if req.error
              else "batcher_requests_completed")
        end = req.finished_at or clock.now()
        if not req._migrated:
            # a migrated-out request's [submit, handoff) span is not a
            # served request — feeding it into the latency histograms
            # would skew the SLO inputs low and double-count the request
            # across the fleet (the destination's sample is the real one)
            m.observe("batcher_e2e_latency", end - req.submitted_at)
            if req.first_token_at is not None:
                m.observe("batcher_ttft",
                          req.first_token_at - req.submitted_at)
        cost = req.cost = self._cost_record(req, end)
        tr = trace.get_tracer()
        attrs = {"tokens": len(req.tokens), "preemptions": req._preemptions,
                 "queue_ms": cost["queue_ms"],
                 "prefill_ms": cost["prefill_ms"],
                 "decode_ms": cost["decode_ms"]}
        if req.error:
            attrs["error"] = req.error
        g = tr.record("batcher.request", req.submitted_at, end,
                      parent=req.trace_ctx, attrs=attrs)
        if req.first_token_at is not None:
            tr.record("batcher.ttft", req.submitted_at, req.first_token_at,
                      parent=g)
            tr.record("batcher.decode", req.first_token_at, end, parent=g,
                      attrs={"tokens": len(req.tokens)})
        # trace tail-sampling: errored and SLO-violating requests keep
        # their spans in the tracer's retained ring, so the postmortem
        # doesn't race the main ring's oldest-first eviction (a
        # migrated-out request is a handoff, not an error worth a slot)
        if (req.error and not req._migrated) or tsdb_mod.cost_within_slo(
                cost, self._slo_targets) is False:
            tr.retain(g.trace_id)

    def _finish_slot(self, slot: int):
        req = self.active[slot]
        self.active[slot] = None
        self.block_tables[slot, :] = self._dummy
        self.context_lens[slot] = 0
        if slot in self._admit_order:
            self._admit_order.remove(slot)
        if req is not None and not req.done.is_set():
            self._finish_req(req)

    def _preempt_youngest(self) -> bool:
        """Free the most recently admitted slot, requeueing its request."""
        if not self._admit_order:
            return False
        self.metrics.inc("batcher_preemptions")
        slot = self._admit_order.pop()
        req = self.active[slot]
        self.active[slot] = None
        self.block_tables[slot, :] = self._dummy
        self.context_lens[slot] = 0
        if req is not None:
            self.pool.release(req._blocks)
            req._blocks = []
            req._preemptions += 1
            if req._preemptions > 5:
                self._fail_req(req, "preempted repeatedly: KV pool too small")
            else:
                # generated tokens are kept; re-admission prefills
                # prompt+tokens and resumes (see _prep_admit)
                self._requeue_front(req)
        return True

    def _ensure_growth(self, slot: int, k: int = 1) -> bool:
        """Make sure the slot owns every block a k-step chunk can write
        (positions [cl, cl + min(k, remaining) - 1]) — allocated up front
        so the whole chunk runs without host intervention."""
        req = self.active[slot]
        pos0 = int(self.context_lens[slot])
        k_eff = max(1, min(k, req.max_new_tokens - len(req.tokens)))
        bi0 = pos0 // self.block_size
        bi1 = (pos0 + k_eff - 1) // self.block_size
        if bi1 >= self.max_blocks:
            return False
        need = [bi for bi in range(bi0, bi1 + 1)
                if self.block_tables[slot, bi] == self._dummy]
        if not need:
            return True
        self._admitting = req   # bill growth-triggered offloads here too
        try:
            got = self.pool.alloc(len(need))
        finally:
            self._admitting = None
        if got is None:
            return False
        for bi, blk in zip(need, got):
            self.block_tables[slot, bi] = blk
        req._blocks.extend(got)
        req._kv_peak = max(req._kv_peak, len(req._blocks))
        return True

    # ---- the step -----------------------------------------------------

    def step(self) -> int:
        """Admit a wave + one K-token decode chunk. Returns active slots."""
        t0 = time.perf_counter()
        busy = 0
        work0 = (self._step_count, self._tokens_out)
        prof_rec = self.profiler.step_begin()
        try:
            busy = self._step_inner()
            return busy
        finally:
            # the hot-path gauges the dashboard and /metrics surface: how
            # deep the queue is, how full the slots are, how much KV
            # headroom remains — refreshed every scheduler step
            m = self.metrics
            with self.profiler.phase("bookkeeping"):
                if busy:   # idle polls would drown the step histogram
                    m.observe("batcher_step", time.perf_counter() - t0)
                m.gauge("batcher_queue_depth", len(self.queue))
                active_slots = sum(a is not None for a in self.active)
                m.gauge("batcher_active_slots", active_slots)
                if busy:   # idle polls would peg occupancy at 0
                    m.gauge("batcher_batch_occupancy",
                            active_slots / self.slots)
                m.gauge("batcher_free_kv_blocks", self.pool.free_count())
                self._sync_cache_metrics()
            # idle polls are discarded — the profile attributes steps
            # that did work, not the wait-for-work loop. "Did work" is
            # dispatched-or-emitted, NOT end-of-step occupancy: a short
            # request can admit, decode, and finish inside ONE step
            # (busy == 0 on return), and that step is exactly the kind
            # the profile must see
            did_work = bool(busy) or \
                (self._step_count, self._tokens_out) != work0
            self.profiler.step_end(prof_rec, keep=did_work, active=busy)

    def _step_inner(self) -> int:
        # service migration snapshots first: a flagged slot must not
        # ride another chunk (its exported KV would go stale) and its
        # freed slot/blocks are admission capacity this same step
        self._service_migrations()
        # drop cancelled slots next — frees their blocks for admission
        for slot in range(self.slots):
            req = self.active[slot]
            if req is not None and req._cancelled:
                req.error = req.error or "cancelled"
                self._finish_slot(slot)

        with self.profiler.phase("admit"):
            self._admit_wave()

        active = [i for i, a in enumerate(self.active) if a is not None]
        if not active:
            return 0

        with self.profiler.phase("host_prep"):
            # chunk size: cover the largest remaining budget in one
            # dispatch when the overshoot is small (dead compute beats a
            # round trip); otherwise the largest chunk some slot can fill
            max_rem = max(self.active[i].max_new_tokens
                          - len(self.active[i].tokens) for i in active)
            chunks = self.decode_chunks
            # per-request brownout cap (req.chunk_cap, from the master's
            # rung-3 decode_chunk_cap dispatch field): the tightest cap
            # among active riders clamps the wave — the filtered set is
            # a subset of decode_chunks (or its warmed min fallback), so
            # no unwarmed program shape is ever requested
            caps = [self.active[i].chunk_cap for i in active
                    if self.active[i].chunk_cap > 0]
            if caps:
                chunks = tuple(c for c in chunks if c <= min(caps)) \
                    or (min(chunks),)
            up = min((c for c in chunks if c >= max_rem),
                     default=None)
            if up is not None and up - max_rem <= self.CHUNK_OVERSHOOT_MAX:
                k = up
            else:
                k = next(c for c in chunks if c <= max_rem)

            # growth blocks for every position this chunk can write
            for slot in range(self.slots):
                while (self.active[slot] is not None
                       and not self._ensure_growth(slot, k)):
                    # _preempt_youngest may free `slot` itself — the loop
                    # condition re-checks before retrying
                    if not self._preempt_youngest():
                        self.active[slot].error = \
                            "cannot grow KV allocation"
                        self._finish_slot(slot)
                        break
            active = [i for i, a in enumerate(self.active) if a is not None]
            if not active:
                return 0

            r = self.slots
            tokens = np.zeros((r,), np.int32)
            seeds = np.zeros((r,), np.int32)
            steps = np.zeros((r,), np.int32)
            temps = np.full((r,), 1.0, np.float32)
            tks = np.zeros((r,), np.int32)
            tps = np.ones((r,), np.float32)
            ds = np.zeros((r,), bool)
            budget = np.zeros((r,), np.int32)
            eos = np.full((r,), -1, np.int32)
            aids = np.zeros((r,), np.int32)
            for i in active:
                req = self.active[i]
                tokens[i] = req.tokens[-1]
                seeds[i] = req.seed
                steps[i] = len(req.tokens)
                temps[i] = req.sampling.temperature
                tks[i] = req.sampling.top_k
                tps[i] = req.sampling.top_p
                ds[i] = req.sampling.do_sample
                budget[i] = min(k, req.max_new_tokens - len(req.tokens))
                if req.eos_token_id is not None:
                    eos[i] = req.eos_token_id
                aids[i] = req._lora_slot

            decode_args = {
                "k": int(k),
                "tokens": tokens.tolist(), "bt": self.block_tables.tolist(),
                "cl": self.context_lens.tolist(), "seeds": seeds.tolist(),
                "steps": steps.tolist(), "temps": temps.tolist(),
                "tks": tks.tolist(), "tps": tps.tolist(), "ds": ds.tolist(),
                "budget": budget.tolist(), "eos": eos.tolist(),
            }
            if aids.any():
                # key PRESENCE selects the lora program variant (see
                # _admit_group); a base-only wave pays zero delta cost
                decode_args["aids"] = aids.tolist()
        if self.speculative:
            return self._step_speculative(active, decode_args)
        if self._overlap_eligible(active, k):
            return self._step_overlapped(active, decode_args, k)
        self._dispatch_plain_chunk(active, decode_args)
        return len([a for a in self.active if a is not None])

    def _dispatch_plain_chunk(self, active, decode_args: dict) -> int:
        """One plain K-token decode chunk: dispatch (hook-aware), sync,
        emit, finish dead slots. Shared by the plain step and the
        adaptive-speculation fallback/probe path. Returns tokens
        emitted."""
        k = int(decode_args["k"])
        budget = decode_args["budget"]
        w0 = clock.now()
        if self.program_hook is not None:
            if self._hist is not None:
                # adaptive fallback under lockstep: a freshly-admitted
                # row's prompt region must still reach the followers, or
                # _apply_plain_hist would advance the watermark past a
                # hole the next spec probe's delta then skips forever
                decode_args = dict(decode_args,
                                   hist_delta=self._hist_deltas())
            toks, emits = self.program_hook(
                "decode", decode_args, lambda: self._run_decode(decode_args))
        else:
            toks, emits = self._run_decode(decode_args)
        self._step_count += 1
        w1 = clock.now()
        self.metrics.observe("batcher_decode_chunk", w1 - w0)
        trace.get_tracer().record(
            "batcher.decode_chunk", w0, w1,
            attrs={"k": k, "slots": len(active)})
        # drafting history stays current even when the adaptive controller
        # runs plain chunks in a speculative batcher — pure function of
        # program outputs, so lockstep followers mirror it in replay()
        self._apply_plain_hist(toks, emits,
                               np.asarray(decode_args["cl"], np.int32))
        return self._emit_chunk_outputs(active, toks, emits, k,
                                        budget=budget)

    def _emit_chunk_outputs(self, active, toks, emits, passes: int,
                            budget=None) -> int:
        """Shared emit/finish/amortization epilogue for [K, R]-shaped
        chunk outputs (plain and overlapped paths; the speculative path's
        outputs are [K, R, G+1] keeps-shaped and handled in place).
        ``budget`` enables the stopped-before-budget eos inference —
        overlapped pairs are provably eos-free and pass None. Returns
        tokens emitted."""
        emitted = 0
        with self.profiler.phase("emit"):
            for i in active:
                req = self.active[i]
                # emits[:, i] is True exactly for this slot's emitted
                # prefix (monotone: once dead — eos or budget — never
                # true again; the device masks eos out, so _emit's eos
                # branch can't re-trigger)
                cnt = int(emits[:, i].sum())
                for tok in toks[:cnt, i]:
                    self._emit(req, int(tok))
                emitted += cnt
                req._weight_passes += passes
                self.context_lens[i] += cnt
                hit_eos = (budget is not None
                           and cnt < int(budget[i]))  # stopped pre-budget
                if hit_eos or len(req.tokens) >= req.max_new_tokens:
                    self._finish_slot(i)
        # amortization: emitted tokens per weight-streaming pass (one
        # pass per decode iteration) — THE number continuous batching
        # exists to raise. Gauge for live /metrics, counters for
        # windowed ratios (bench.py takes per-rep deltas).
        self.metrics.gauge("decode_tokens_per_weight_pass",
                           emitted / passes if passes else 0.0)
        self.metrics.inc("batcher_weight_passes", passes)
        self.metrics.inc("batcher_tokens_emitted", emitted)
        return emitted

    def _overlap_eligible(self, active, k: int) -> bool:
        """True when a chunk pair can dispatch back-to-back with no host
        decision in between: single-host, nothing queued (admission waits
        a chunk otherwise), and every active slot provably emits exactly
        ``k`` tokens per chunk twice over — no eos stop-check, no
        streaming callback wanting tokens at chunk granularity, budget
        covering both chunks — with growth blocks for 2k pre-allocated."""
        if not self.decode_overlap or self.program_hook is not None:
            return False
        with self._lock:
            if self.queue:
                return False
        for i in active:
            req = self.active[i]
            if (req.eos_token_id is not None or req.stream_cb is not None
                    or req.max_new_tokens - len(req.tokens) < 2 * k):
                return False
        # growth extension may fail at the pool/max_blocks edge: the step
        # then simply runs single-chunk (already-granted blocks stay with
        # their slots — they back the very next chunk)
        return all(self._ensure_growth(i, 2 * k) for i in active)

    def _step_overlapped(self, active, args_a: dict, k: int) -> int:
        """Double-buffered decode: dispatch chunk B fed by chunk A's
        device-resident last-iteration tokens, then sync the PAIR once —
        A's device->host token transfer rides under B's compute, and the
        per-chunk dispatch round trip is paid once per 2k tokens.
        Eligibility (_overlap_eligible) guarantees A emits exactly k per
        active slot, so B's context/step offsets advance deterministically
        host-side without seeing A's tokens."""
        # _overlap_eligible's 2k growth ran AFTER the step snapshotted the
        # block tables — refresh, or chunk B scatters into blocks its
        # table doesn't know (A ignores entries past its write range:
        # gathers are position-masked below cl0)
        args_a = dict(args_a, bt=self.block_tables.tolist())
        cl_b = list(args_a["cl"])
        st_b = list(args_a["steps"])
        for i in active:
            cl_b[i] += k
            st_b[i] += k
        args_b = dict(args_a, cl=cl_b, steps=st_b)
        w0 = clock.now()
        toks_a, emits_a = self._run_decode(args_a, sync=False)
        toks_b, emits_b = self._run_decode(args_b, tokens_dev=toks_a[-1],
                                           sync=False)
        self._step_count += 2
        self._overlapped_dispatches += 1
        self.metrics.inc("batcher_overlapped_dispatches")
        with self.profiler.phase("device_wait"):
            toks_a, emits_a, toks_b, emits_b = jax.device_get(
                (toks_a, emits_a, toks_b, emits_b))  # ONE sync for the pair
        w1 = clock.now()
        self.metrics.observe("batcher_decode_chunk", (w1 - w0) / 2)
        self.metrics.observe("batcher_decode_chunk", (w1 - w0) / 2)
        trace.get_tracer().record(
            "batcher.decode_chunk", w0, w1,
            attrs={"k": 2 * k, "slots": len(active), "overlapped": True})

        toks = np.concatenate([toks_a, toks_b], axis=0)
        emits = np.concatenate([emits_a, emits_b], axis=0)
        self._emit_chunk_outputs(active, toks, emits, 2 * k)
        return len([a for a in self.active if a is not None])

    def _step_speculative(self, active, decode_args: dict) -> int:
        """Dispatch a speculative chunk instead of a plain decode chunk:
        ceil(k / (gamma+1)) verify iterations cover the same token budget
        when drafts miss, and up to (gamma+1)x fewer dispatches when they
        hit. Block growth was already ensured for k tokens — accepted
        cache writes never exceed the budget, and rejected scratch
        entries scatter to the dummy block.

        Wave mode (``spec_wave``, default): per-slot draft widths from
        per-request controllers, one shared verify pass
        (_step_spec_wave). Off: this pre-wave path — ONE global
        controller arbitrates one gamma for the whole wave, and gamma 0
        runs the entire chunk plain (the wave-wide cliff wave mode
        exists to remove). Every chunk's (acceptance, emitted, elapsed)
        feeds back, with fresh-compile dispatches excluded from the
        throughput EMAs."""
        if self.spec_wave:
            return self._step_spec_wave(active, decode_args)
        ctl = self._spec_ctl
        gamma = ctl.choose() if ctl is not None else self.spec_gamma
        m = self.metrics
        if ctl is not None:
            m.gauge("spec_mode", 1.0 if gamma else 0.0)
            m.gauge("spec_gamma_current", float(gamma or ctl.gamma))
            acc = ctl.acceptance()
            if acc is not None:
                m.gauge("spec_acceptance_rate", acc)
        if gamma == 0:
            # controller fallback — or spec_gamma=0 with adaptivity off,
            # where a degenerate zero-draft chunk has nothing to verify:
            # both run the plain program (ctl may be None in the latter)
            k = int(decode_args["k"])
            compiled = (k, self.slots, self.max_blocks,
                        "aids" in decode_args) not in self._decode_fns
            w0 = clock.now()
            emitted = self._dispatch_plain_chunk(active, decode_args)
            if ctl is not None:
                ctl.record("plain", emitted=emitted,
                           elapsed_s=clock.now() - w0, compiled=compiled)
            return len([a for a in self.active if a is not None])

        g1 = gamma + 1
        k_it = -(-int(decode_args["k"]) // g1)
        args = dict(decode_args, k=k_it, gamma=gamma)
        spec_key = ("spec", k_it, gamma, self.slots, self.max_blocks,
                    self._hist.shape[1], "aids" in decode_args)
        compiled = spec_key not in self._decode_fns
        w0 = clock.now()
        if self.program_hook is not None:
            # the lockstep mirror ships JSON: broadcast only per-slot
            # history deltas (non-empty just after admissions); followers
            # derive every other append from the replayed program's
            # outputs, so the broadcast is O(new tokens), never
            # O(slots * max_seq) per chunk
            args["hist_delta"] = self._hist_deltas()
            local = dict(args, hist=self._hist)
            toks, keeps, eos_seen = self.program_hook(
                "spec_decode", args, lambda: self._run_spec_decode(local))
        else:
            args["hist"] = self._hist
            toks, keeps, eos_seen = self._run_spec_decode(args)
        self._step_count += 1
        w1 = clock.now()
        self.metrics.observe("batcher_decode_chunk", w1 - w0)
        trace.get_tracer().record(
            "batcher.spec_chunk", w0, w1,
            attrs={"k": k_it, "gamma": gamma, "slots": len(active)})
        self._apply_spec_hist(toks, keeps,
                              np.asarray(decode_args["cl"], np.int32))

        per = self._emit_spec_outputs(
            active, toks, keeps, eos_seen, k_it,
            np.full((self.slots,), gamma, np.int32))
        emitted = sum(cnt for (_, cnt, _, _) in per.values())
        live_iters = sum(live for (_, _, live, _) in per.values())
        accepted = emitted - live_iters
        # amortization: a verify iteration streams the weights once
        # however wide the draft is — that width is the whole speedup
        m.gauge("decode_tokens_per_weight_pass",
                emitted / k_it if k_it else 0.0)
        m.inc("batcher_weight_passes", k_it)
        m.inc("batcher_tokens_emitted", emitted)
        if ctl is not None:
            ctl.record("spec", emitted=emitted,
                       elapsed_s=clock.now() - w0,
                       drafted=gamma * live_iters, accepted=accepted,
                       compiled=compiled)
            if ctl.fallbacks:
                m.gauge("spec_fallbacks", float(ctl.fallbacks))
        return len([a for a in self.active if a is not None])

    def _emit_spec_outputs(self, active, toks, keeps, eos_seen,
                           k_it: int, gammas) -> dict:
        """Shared emit/accounting epilogue for [K, R, G+1]-shaped
        speculative outputs — the single definition both arbitration
        modes use (wave-off passes a uniform width vector), so the most
        correctness-sensitive bookkeeping in the batcher cannot drift
        between DLI_SPEC_WAVE settings. Per slot: emit the kept tokens,
        advance context/ledger counters, finish on the device's
        cumulative eos flag or an exhausted budget (a slot may
        legitimately emit fewer than its budget when every draft missed
        — 1 token/iteration). Returns {slot: (req, cnt, live, drafted)}
        for the callers' controller feedback."""
        out = {}
        with self.profiler.phase("emit"):
            for i in active:
                req = self.active[i]
                g_i = int(gammas[i])
                cnt = int(keeps[:, i].sum())
                for t in range(keeps.shape[0]):
                    for tok in toks[t, i, : int(keeps[t, i])]:
                        self._emit(req, int(tok))
                # speedup accounting: tokens beyond one-per-iteration
                live = int((keeps[:, i] > 0).sum())
                acc_i = cnt - live
                drafted_i = g_i * live
                self._spec_accepted += acc_i
                req._weight_passes += k_it
                req._spec_acc += acc_i
                req._spec_rej += max(0, drafted_i - acc_i)
                req._spec_drafted += drafted_i
                self.context_lens[i] += cnt
                out[i] = (req, cnt, live, drafted_i)
                if bool(eos_seen[-1, i]) \
                        or len(req.tokens) >= req.max_new_tokens:
                    self._finish_slot(i)
        return out

    def _seed_wave_ctl(self, ctl):
        """Seed a fresh per-request controller from the batcher's shared
        arbitration state: the throughput EMAs and probe clocks carry
        over (they measure the host/workload, not the request), and when
        the fleet measurements already say drafting loses — the same
        hysteresis rule the controller applies itself — the request
        starts in plain mode instead of re-discovering the inversion
        over its own (possibly whole) lifetime. Probes keep both arms
        measured at the fleet cadence, so a workload shift flips the
        verdict back within probe_every chunks."""
        sh = self._wave_shared
        ctl.spec_tps = sh["spec_tps"]
        ctl.plain_tps = sh["plain_tps"]
        ctl._since_plain_probe = sh["since_plain_probe"]
        ctl._since_probe = sh["since_probe"]
        if (ctl.spec_tps is not None and ctl.plain_tps is not None
                and ctl.spec_tps < ctl.plain_tps * ctl.hysteresis):
            ctl.mode = "plain"
        return ctl

    def _sync_wave_shared(self, ctl):
        """Write one controller's arbitration state back to the shared
        store (last writer wins: active controllers tick in lockstep, so
        any of them is a good fleet clock)."""
        sh = self._wave_shared
        sh["spec_tps"] = ctl.spec_tps
        sh["plain_tps"] = ctl.plain_tps
        sh["since_plain_probe"] = ctl._since_plain_probe
        sh["since_probe"] = ctl._since_probe

    def _step_spec_wave(self, active, decode_args: dict) -> int:
        """Wave-level batched speculation: ONE fused draft+verify program
        serves the whole active wave, with per-slot draft widths riding
        as data (transformer.paged_speculative_chunk ``gammas``).

        Each active request consults its OWN AdaptiveSpecController for
        this chunk's width: 0 means the slot rides the shared verify
        pass as plain decode (one exact token per iteration — including
        its plain-arm probes, which measure what riding actually costs
        it), so one draft-hostile request never drags its chunk-mates
        off the speculative path. The compiled program's gamma stays the
        configured static maximum — width mixes change DATA, never the
        compile key. Only when EVERY slot chooses 0 does the step run a
        true plain chunk (cheaper than a degenerate all-width-0 verify).

        Greedy rows are bitwise identical to plain decode at any width
        assignment (argmax acceptance); sampled rows keep the exact
        target distribution per position (ops/speculative.py
        accept_rejection_batch position-keyed PRNG), and the lockstep
        broadcast carries the widths in the args, so followers replay
        the identical program."""
        from distributed_llm_inferencing_tpu.ops.speculative import (
            AdaptiveSpecController)
        m = self.metrics
        g_max = self.spec_gamma
        with self.profiler.phase("spec_draft"):
            gammas = np.zeros((self.slots,), np.int32)
            for i in active:
                req = self.active[i]
                if self._spec_adaptive and g_max >= 1:
                    if req._spec_ctl is None:
                        req._spec_ctl = self._seed_wave_ctl(
                            AdaptiveSpecController(g_max))
                    gammas[i] = req._spec_ctl.choose()
                else:
                    gammas[i] = max(0, g_max)
        drafting = [i for i in active if gammas[i] > 0]
        riding = [i for i in active if gammas[i] == 0]
        m.gauge("spec_wave_drafting_slots", float(len(drafting)))
        m.gauge("spec_wave_gamma_mean",
                float(np.mean([gammas[i] for i in active])))
        m.gauge("spec_mode", 1.0 if drafting else 0.0)

        if not drafting:
            # every controller (or an explicit zero-draft spec_gamma)
            # says plain this chunk: run a true plain program and feed
            # each request's controller its own slice of the measurement
            k = int(decode_args["k"])
            compiled = (k, self.slots, self.max_blocks,
                        "aids" in decode_args) not in self._decode_fns
            reqs = {i: self.active[i] for i in active}
            before = {i: len(r.tokens) for i, r in reqs.items()}
            w0 = clock.now()
            self._dispatch_plain_chunk(active, decode_args)
            dt = clock.now() - w0
            for i, req in reqs.items():
                if req._spec_ctl is not None:
                    req._spec_ctl.record(
                        "plain", emitted=len(req.tokens) - before[i],
                        elapsed_s=dt, compiled=compiled)
                    self._sync_wave_shared(req._spec_ctl)
            return len([a for a in self.active if a is not None])

        g1 = g_max + 1
        k_it = -(-int(decode_args["k"]) // g1)
        args = dict(decode_args, k=k_it, gamma=g_max,
                    gammas=gammas.tolist())
        spec_key = ("spec", k_it, g_max, self.slots, self.max_blocks,
                    self._hist.shape[1], "aids" in decode_args)
        compiled = spec_key not in self._decode_fns
        w0 = clock.now()
        if self.program_hook is not None:
            # lockstep: widths are scheduler decisions, so they ride the
            # broadcast args; history still ships as per-slot deltas
            with self.profiler.phase("spec_draft"):
                args["hist_delta"] = self._hist_deltas()
            local = dict(args, hist=self._hist)
            toks, keeps, eos_seen = self.program_hook(
                "spec_decode", args, lambda: self._run_spec_decode(local))
        else:
            args["hist"] = self._hist
            toks, keeps, eos_seen = self._run_spec_decode(args)
        self._step_count += 1
        self._spec_wave_dispatches += 1
        w1 = clock.now()
        m.inc("spec_wave_dispatches")
        m.observe("batcher_decode_chunk", w1 - w0)
        trace.get_tracer().record(
            "batcher.spec_wave_chunk", w0, w1,
            attrs={"k": k_it, "gamma_max": g_max, "slots": len(active),
                   "drafting": len(drafting), "riding": len(riding)})
        self._apply_spec_hist(toks, keeps,
                              np.asarray(decode_args["cl"], np.int32))

        per = self._emit_spec_outputs(active, toks, keeps, eos_seen,
                                      k_it, gammas)
        emitted = sum(cnt for (_, cnt, _, _) in per.values())
        drafted_total = sum(d for (_, _, _, d) in per.values())
        accepted_total = emitted - sum(
            live for (_, _, live, _) in per.values())
        dt = w1 - w0
        for i, (req, cnt, live, drafted_i) in per.items():
            if req._spec_ctl is None:
                continue
            if int(gammas[i]) > 0:
                req._spec_ctl.record("spec", emitted=cnt, elapsed_s=dt,
                                     drafted=drafted_i,
                                     accepted=cnt - live,
                                     compiled=compiled)
            else:
                req._spec_ctl.record("plain", emitted=cnt, elapsed_s=dt,
                                     compiled=compiled)
            self._sync_wave_shared(req._spec_ctl)
        # THE headline metric: emitted tokens per weight-streaming pass.
        # A verify iteration streams the weights once however wide the
        # per-slot drafts are — wave speculation exists to push this
        # past plain batching's 1.0-per-live-slot.
        m.gauge("decode_tokens_per_weight_pass",
                emitted / k_it if k_it else 0.0)
        m.inc("batcher_weight_passes", k_it)
        m.inc("batcher_tokens_emitted", emitted)
        m.inc("spec_wave_drafted_tokens", drafted_total)
        m.inc("spec_wave_accepted_tokens", accepted_total)
        m.inc("spec_wave_plain_rides", len(riding))
        if drafted_total:
            m.gauge("spec_acceptance_rate", accepted_total / drafted_total)
        return len([a for a in self.active if a is not None])

    # ---- background loop ----------------------------------------------

    def _loop(self):
        while not self._stop.is_set():
            try:
                busy = self.step()
            except Exception as e:
                # e.g. the lockstep hook reporting a degraded slice: fail
                # every waiter fast instead of letting them block to their
                # timeouts against a dead scheduler
                for slot in range(self.slots):
                    if self.active[slot] is not None:
                        self.active[slot].error = f"scheduler error: {e}"
                        self._finish_slot(slot)
                with self._lock:
                    drained = list(self.queue)
                    self.queue.clear()
                for req in drained:
                    self._fail_req(req, f"scheduler error: {e}")
                self._stop.set()
                return
            if not busy and not self.queue:
                self._work.wait(timeout=0.05)
                self._work.clear()


def _backend(cfg: ModelConfig, num_devices: int = 1) -> str:
    from distributed_llm_inferencing_tpu.models.transformer import (
        _cfg_backend)
    return _cfg_backend(cfg, num_devices, op="paged")
