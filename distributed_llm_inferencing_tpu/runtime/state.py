"""Master-side persistent state: nodes, placement plans, request queue.

SQLite via stdlib — the same durability model as the reference's Django ORM
over SQLite (reference: master/master/settings.py:58-63,
master/dashboard/models.py:4-62) with the same three entities:

- nodes     ≙ WorkerNode      (models.py:4-17)
- plans     ≙ ModelShard      (models.py:19-31) — but a plan is partition-
              spec metadata (parallel/plan.py), not a weight-file pointer
- requests  ≙ InferenceRequest (models.py:33-62), including the
              mark_completed/mark_failed lifecycle (models.py:52-62)

Thread-safe: one connection guarded by a lock (the reference shared ORM
state across raw threads unguarded, SURVEY.md §5.2).
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

_SCHEMA = """
CREATE TABLE IF NOT EXISTS nodes (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT UNIQUE NOT NULL,
    host TEXT NOT NULL,
    port INTEGER NOT NULL,
    is_active INTEGER DEFAULT 0,
    consecutive_failures INTEGER DEFAULT 0,
    breaker_state TEXT DEFAULT 'closed',
    breaker_opened_at REAL,
    draining INTEGER DEFAULT 0,
    last_heartbeat REAL,
    added_at REAL,
    info TEXT DEFAULT '{}'
);
CREATE TABLE IF NOT EXISTS plans (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    model_name TEXT NOT NULL,
    plan TEXT NOT NULL,
    node_id INTEGER,
    is_loaded INTEGER DEFAULT 0,
    created_at REAL
);
CREATE TABLE IF NOT EXISTS requests (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    model_name TEXT NOT NULL,
    prompt TEXT NOT NULL,
    status TEXT DEFAULT 'pending',
    result TEXT,
    error TEXT,
    node_id INTEGER,
    attempts INTEGER DEFAULT 0,
    excluded_nodes TEXT DEFAULT '[]',
    next_attempt_at REAL DEFAULT 0,
    max_new_tokens INTEGER,
    max_length INTEGER,
    sampling TEXT DEFAULT '{}',
    created_at REAL,
    started_at REAL,
    completed_at REAL,
    execution_time REAL,
    tokens_per_s REAL
);
"""

# Columns added after the seed schema: an existing on-disk DB (the
# master's sqlite file survives restarts by design) is upgraded in
# place at open.
_MIGRATIONS = {
    "nodes": (("breaker_state", "TEXT DEFAULT 'closed'"),
              ("breaker_opened_at", "REAL"),
              ("draining", "INTEGER DEFAULT 0")),
    "requests": (("excluded_nodes", "TEXT DEFAULT '[]'"),
                 ("next_attempt_at", "REAL DEFAULT 0")),
}


def _row_to_dict(cur, row):
    return {d[0]: row[i] for i, d in enumerate(cur.description)}


class Store:
    def __init__(self, path: str = ":memory:"):
        self._lock = threading.RLock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        with self._lock, self._db:
            self._db.executescript(_SCHEMA)
            for table, cols in _MIGRATIONS.items():
                have = {r[1] for r in self._db.execute(
                    f"PRAGMA table_info({table})")}
                for col, decl in cols:
                    if col not in have:
                        self._db.execute(
                            f"ALTER TABLE {table} ADD COLUMN {col} {decl}")

    def _all(self, sql, args=()) -> List[Dict[str, Any]]:
        with self._lock:
            cur = self._db.execute(sql, args)
            return [_row_to_dict(cur, r) for r in cur.fetchall()]

    def _one(self, sql, args=()) -> Optional[Dict[str, Any]]:
        rows = self._all(sql, args)
        return rows[0] if rows else None

    def _exec(self, sql, args=()) -> int:
        with self._lock, self._db:
            cur = self._db.execute(sql, args)
            return cur.lastrowid

    # ---- nodes -------------------------------------------------------

    def add_node(self, name: str, host: str, port: int,
                 is_active: bool = False) -> int:
        return self._exec(
            "INSERT INTO nodes (name, host, port, is_active, added_at) "
            "VALUES (?,?,?,?,?)", (name, host, port, int(is_active), time.time()))

    def get_node(self, node_id: int):
        return self._one("SELECT * FROM nodes WHERE id=?", (node_id,))

    def find_node(self, host: str, port: int):
        return self._one("SELECT * FROM nodes WHERE host=? AND port=?",
                         (host, port))

    def list_nodes(self, active_only=False):
        q = "SELECT * FROM nodes"
        if active_only:
            q += " WHERE is_active=1"
        return self._all(q + " ORDER BY id")

    def update_node(self, node_id: int, **fields):
        if "info" in fields and not isinstance(fields["info"], str):
            fields["info"] = json.dumps(fields["info"])
        sets = ", ".join(f"{k}=?" for k in fields)
        self._exec(f"UPDATE nodes SET {sets} WHERE id=?",
                   (*fields.values(), node_id))

    def remove_node(self, node_id: int):
        self._exec("DELETE FROM nodes WHERE id=?", (node_id,))

    def node_url(self, node) -> str:
        # ≙ WorkerNode.get_url (reference models.py:16-17)
        return f"http://{node['host']}:{node['port']}"

    # ---- plans -------------------------------------------------------

    def add_plan(self, model_name: str, plan: dict,
                 node_id: Optional[int] = None) -> int:
        return self._exec(
            "INSERT INTO plans (model_name, plan, node_id, created_at) "
            "VALUES (?,?,?,?)",
            (model_name, json.dumps(plan), node_id, time.time()))

    def list_plans(self, model_name: Optional[str] = None):
        rows = self._all(
            "SELECT * FROM plans" +
            (" WHERE model_name=?" if model_name else "") + " ORDER BY id",
            (model_name,) if model_name else ())
        for r in rows:
            r["plan"] = json.loads(r["plan"])
        return rows

    def mark_plan_loaded(self, plan_id: int, node_id: int, loaded=True):
        self._exec("UPDATE plans SET is_loaded=?, node_id=? WHERE id=?",
                   (int(loaded), node_id, plan_id))

    # ---- requests ----------------------------------------------------

    def submit_request(self, model_name: str, prompt: str,
                       max_new_tokens: Optional[int] = 100,
                       sampling: Optional[dict] = None,
                       max_length: Optional[int] = None) -> int:
        return self._exec(
            "INSERT INTO requests (model_name, prompt, max_new_tokens, "
            "max_length, sampling, created_at) VALUES (?,?,?,?,?,?)",
            (model_name, prompt, max_new_tokens, max_length,
             json.dumps(sampling or {}), time.time()))

    def get_request(self, req_id: int):
        r = self._one("SELECT * FROM requests WHERE id=?", (req_id,))
        if r:
            r["sampling"] = json.loads(r["sampling"] or "{}")
            r["excluded_nodes"] = json.loads(r.get("excluded_nodes") or "[]")
        return r

    def claim_next_pending(self) -> Optional[Dict[str, Any]]:
        """Atomically move the oldest *due* pending request to processing.
        A request parked by a backoff retry (``next_attempt_at`` in the
        future) is invisible until its delay elapses — the dispatcher's
        idle poll re-examines the queue on its own cadence."""
        with self._lock:
            row = self._one(
                "SELECT * FROM requests WHERE status='pending' "
                "AND next_attempt_at<=? ORDER BY id LIMIT 1",
                (time.time(),))
            if row is None:
                return None
            self._exec(
                "UPDATE requests SET status='processing', started_at=? "
                "WHERE id=?", (time.time(), row["id"]))
            row["sampling"] = json.loads(row["sampling"] or "{}")
            row["excluded_nodes"] = json.loads(
                row.get("excluded_nodes") or "[]")
            return row

    def requeue(self, req_id: int, excluded_node_id: Optional[int] = None,
                delay_s: float = 0.0, last_node_id: Optional[int] = None):
        """Failover retry: back to pending with the attempt counted, the
        failed node recorded for cross-attempt exclusion, and the next
        attempt parked ``delay_s`` into the future (backoff).
        ``last_node_id`` records where this attempt ran (the row's
        node_id) — a timeout retry prefers that node, since it still
        holds the in-flight generation."""
        with self._lock, self._db:
            extra = ""
            args: list = []
            if excluded_node_id is not None:
                row = self._one("SELECT excluded_nodes FROM requests "
                                "WHERE id=?", (req_id,))
                seen = json.loads((row or {}).get("excluded_nodes") or "[]")
                if excluded_node_id not in seen:
                    seen.append(excluded_node_id)
                extra += ", excluded_nodes=?"
                args.append(json.dumps(seen))
            if last_node_id is not None:
                extra += ", node_id=?"
                args.append(last_node_id)
            self._db.execute(
                "UPDATE requests SET status='pending', attempts=attempts+1, "
                f"next_attempt_at=?{extra} WHERE id=?",
                (time.time() + max(0.0, delay_s), *args, req_id))

    def recover_stale_processing(self, max_attempts: Optional[int] = None
                                 ) -> int:
        """Requeue requests stranded in 'processing' by a master crash —
        the reference left these stuck forever (no recovery path at all,
        SURVEY.md §5.3). Called once at master startup.

        Recovery counts as an attempt: a poison request that kills its
        worker (or the master) must not be re-dispatched forever across
        restarts, so anything at ``max_attempts`` fails permanently here
        instead of re-entering the queue.
        """
        with self._lock, self._db:
            failed = 0
            if max_attempts is not None:
                cur = self._db.execute(
                    "UPDATE requests SET status='failed', completed_at=?, "
                    "error='abandoned after repeated crash recovery "
                    "(poison request?)' WHERE status='processing' "
                    "AND attempts+1>=?", (time.time(), max_attempts))
                failed = cur.rowcount
            cur = self._db.execute(
                "UPDATE requests SET status='pending', attempts=attempts+1, "
                "next_attempt_at=0 WHERE status='processing'")
            return cur.rowcount + failed

    def mark_completed(self, req_id: int, result: str, node_id: int,
                       execution_time: float, tokens_per_s: float):
        # ≙ InferenceRequest.mark_completed (reference models.py:52-56)
        self._exec(
            "UPDATE requests SET status='completed', result=?, node_id=?, "
            "completed_at=?, execution_time=?, tokens_per_s=? WHERE id=?",
            (result, node_id, time.time(), execution_time, tokens_per_s, req_id))

    def mark_failed(self, req_id: int, error: str):
        # ≙ InferenceRequest.mark_failed (reference models.py:58-62)
        self._exec(
            "UPDATE requests SET status='failed', error=?, completed_at=? "
            "WHERE id=?", (error, time.time(), req_id))

    def recent_requests(self, limit: int = 20):
        return self._all(
            "SELECT * FROM requests ORDER BY id DESC LIMIT ?", (limit,))

    def counts(self) -> Dict[str, int]:
        rows = self._all(
            "SELECT status, COUNT(*) AS n FROM requests GROUP BY status")
        return {r["status"]: r["n"] for r in rows}
