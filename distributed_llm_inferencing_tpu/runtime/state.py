"""Master-side persistent state: nodes, placement plans, request queue.

SQLite via stdlib — the same durability model as the reference's Django ORM
over SQLite (reference: master/master/settings.py:58-63,
master/dashboard/models.py:4-62) with the same three entities:

- nodes     ≙ WorkerNode      (models.py:4-17)
- plans     ≙ ModelShard      (models.py:19-31) — but a plan is partition-
              spec metadata (parallel/plan.py), not a weight-file pointer
- requests  ≙ InferenceRequest (models.py:33-62), including the
              mark_completed/mark_failed lifecycle (models.py:52-62)

Thread-safe: one connection guarded by a lock (the reference shared ORM
state across raw threads unguarded, SURVEY.md §5.2).
"""

from __future__ import annotations

import json
import logging
import os
import sqlite3
import threading
from typing import Any, Callable, Dict, List, Optional

from distributed_llm_inferencing_tpu.utils import clock, locks
from distributed_llm_inferencing_tpu.utils.faults import mutation_enabled

log = logging.getLogger("dli_tpu.state")

# Every status write below is an instance of a transition DECLARED in
# runtime/lifecycle.py; tools/dlilint/check_lifecycle.py verifies the
# SQL sites against that table (source guard, durability mechanism,
# attempt accounting), so a new status or an edit to a WHERE clause
# must update the declared machine — or fail CI.

_SCHEMA = """
CREATE TABLE IF NOT EXISTS nodes (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT UNIQUE NOT NULL,
    host TEXT NOT NULL,
    port INTEGER NOT NULL,
    is_active INTEGER DEFAULT 0,
    consecutive_failures INTEGER DEFAULT 0,
    breaker_state TEXT DEFAULT 'closed',
    breaker_opened_at REAL,
    draining INTEGER DEFAULT 0,
    last_heartbeat REAL,
    added_at REAL,
    info TEXT DEFAULT '{}'
);
CREATE TABLE IF NOT EXISTS plans (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    model_name TEXT NOT NULL,
    plan TEXT NOT NULL,
    node_id INTEGER,
    is_loaded INTEGER DEFAULT 0,
    created_at REAL
);
CREATE TABLE IF NOT EXISTS requests (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    model_name TEXT NOT NULL,
    prompt TEXT NOT NULL,
    status TEXT DEFAULT 'pending',
    result TEXT,
    error TEXT,
    node_id INTEGER,
    attempts INTEGER DEFAULT 0,
    excluded_nodes TEXT DEFAULT '[]',
    next_attempt_at REAL DEFAULT 0,
    max_new_tokens INTEGER,
    max_length INTEGER,
    sampling TEXT DEFAULT '{}',
    created_at REAL,
    started_at REAL,
    completed_at REAL,
    execution_time REAL,
    tokens_per_s REAL
);
CREATE TABLE IF NOT EXISTS events (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    ts REAL NOT NULL,
    type TEXT NOT NULL,
    severity TEXT DEFAULT 'info',
    node_id INTEGER,
    request_id INTEGER,
    trace_id TEXT,
    data TEXT DEFAULT '{}'
);
CREATE INDEX IF NOT EXISTS idx_events_request ON events(request_id);
CREATE INDEX IF NOT EXISTS idx_events_type ON events(type);
-- the dispatcher's claim query and the due-time probe both filter on
-- status; without this, every claim scans the whole requests table,
-- which turns a long-lived master (or a 100k-request dlisim run) into
-- an O(n^2) dispatch plane. Pending rows are few at any instant, so
-- the index keeps both queries proportional to the backlog, not the
-- history.
CREATE INDEX IF NOT EXISTS idx_requests_status ON requests(status);
CREATE TABLE IF NOT EXISTS meta (
    key TEXT PRIMARY KEY,
    value TEXT,
    updated_at REAL
);
"""

# Columns added after the seed schema: an existing on-disk DB (the
# master's sqlite file survives restarts by design) is upgraded in
# place at open.
_MIGRATIONS = {
    "nodes": (("breaker_state", "TEXT DEFAULT 'closed'"),
              ("breaker_opened_at", "REAL"),
              ("draining", "INTEGER DEFAULT 0")),
    "requests": (("excluded_nodes", "TEXT DEFAULT '[]'"),
                 ("next_attempt_at", "REAL DEFAULT 0"),
                 # per-request cost-ledger record (JSON: queue/prefill/
                 # decode phase ms, cached/uncached prefill tokens, KV
                 # peak, spec accounting — runtime/batcher.py), persisted
                 # at completion and served via /api/requests/<id>/cost
                 ("cost", "TEXT"),
                 # live-migration resume record (JSON: emitted tokens,
                 # seed, sampler position, spec-controller state) and
                 # the kv_source transfer hint — persisted so a
                 # re-dispatch AND any later failover retry resume
                 # mid-stream instead of re-prefilling (FailSafe,
                 # arxiv 2511.14116)
                 ("resume", "TEXT"),
                 ("kv_source", "TEXT"),
                 # client-supplied submit idempotency key: a submit
                 # retry (the client's ack was lost — e.g. the leader
                 # of an HA pair died between committing the row and
                 # answering) dedupes onto the existing row instead of
                 # creating a second request that would generate twice
                 ("client_tag", "TEXT"),
                 # overload-control plane (docs/robustness.md "Overload
                 # control"): declared SLO class drives claim priority
                 # and the shedding ladder; tenant names the token
                 # bucket that admitted the request. Defaults keep
                 # pre-migration rows on the middle tier.
                 ("slo_class", "TEXT DEFAULT 'throughput'"),
                 ("tenant", "TEXT DEFAULT 'default'"),
                 # multi-LoRA serving (models/lora.py): the adapter a
                 # request names rides the row end-to-end — dispatch
                 # lazily loads it on the chosen node, failover retries
                 # and migration resumes keep serving the SAME adapter
                 ("adapter", "TEXT")),
}

# Declared SLO classes (request body field ``slo_class``) and their
# claim priorities — lower number claims first. Anything outside the
# tuple is a structured 400 at api_submit; NULL (pre-migration rows)
# coalesces to 'throughput' in the CASE below.
SLO_CLASSES = ("latency", "throughput", "batch")
_SLO_PRIORITY_SQL = ("CASE slo_class WHEN 'latency' THEN 0 "
                     "WHEN 'batch' THEN 2 ELSE 1 END")

# Deadline-style aging for the priority claim: a pending request's
# effective priority drops by one tier per CLAIM_AGING_S seconds of
# wait, so batch work cannot starve behind a sustained latency-tier
# stream. The anti-starvation bound this buys (model-checked in
# tools/dliverify, asserted at 1000 nodes by the dlisim overload
# sweep): once a request has waited 2x aging (the full priority span),
# no later submit can sort ahead of it, so with the admission plane's
# pending-depth cap Q it is claimed within ceil(Q / claim_batch)
# further waves. <= 0 disables aging (pure class priority, id order).
CLAIM_AGING_S = float(os.environ.get("DLI_SCHED_AGING_S", "30"))


def _strip_ephemeral(info):
    """Drop ephemeral scheduler payloads (Store.EPHEMERAL_SCHEDULER_KEYS,
    e.g. the prefix-digest advertisement) from a worker /health body
    before it is persisted as the node's info row. Non-destructive: the
    caller's dict is not mutated — the master's in-memory runtime
    snapshot still sees the full advertisement."""
    if not isinstance(info, dict) or "loaded_models" not in info:
        return info
    out = dict(info)
    models = []
    for m in out.get("loaded_models") or []:
        sch = m.get("scheduler") if isinstance(m, dict) else None
        if isinstance(sch, dict) and any(
                k in sch for k in Store.EPHEMERAL_SCHEDULER_KEYS):
            m = dict(m)
            m["scheduler"] = {k: v for k, v in sch.items()
                              if k not in Store.EPHEMERAL_SCHEDULER_KEYS}
        models.append(m)
    out["loaded_models"] = models
    return out


def _row_to_dict(cur, row):
    return {d[0]: row[i] for i, d in enumerate(cur.description)}


class Store:
    # Tables a replication snapshot carries (runtime/replication.py):
    # the whole persisted control-plane state, in FK-safe load order.
    REPL_TABLES = ("nodes", "plans", "requests", "events", "meta")

    def __init__(self, path: str = ":memory:", *,
                 group_commit: bool = False,
                 flush_interval: Optional[float] = None,
                 on_flush: Optional[Callable[[], None]] = None):
        self._lock = locks.rlock("state.store")
        # Replicated control plane (runtime/replication.py): when an op
        # hook is installed, every committed write — synchronous or
        # group-commit — is handed to it as (sql, args) pairs IN COMMIT
        # ORDER (the hook runs under the store lock, immediately after
        # the transaction lands), so a standby replaying the stream in
        # order reconstructs a byte-identical store, autoincrement ids
        # included. The replication barrier hook (leader side) runs
        # after a barriered write's local commit and may wait for a
        # standby ack — with a timeout, never forever.
        self._op_hook: Optional[Callable[[list], None]] = None
        self._repl_barrier: Optional[Callable[[], None]] = None
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        with self._lock, self._db:
            self._db.executescript(_SCHEMA)
            for table, cols in _MIGRATIONS.items():
                have = {r[1] for r in self._db.execute(
                    f"PRAGMA table_info({table})")}
                for col, decl in cols:
                    if col not in have:
                        self._db.execute(
                            f"ALTER TABLE {table} ADD COLUMN {col} {decl}")
            # after the migrations: the index's column must exist first
            self._db.execute(
                "CREATE INDEX IF NOT EXISTS idx_requests_client_tag "
                "ON requests(client_tag)")
        # Group-commit write-behind (the master's dispatch hot path): the
        # per-request status writes (requeue/complete/fail) queue up and
        # land in ONE transaction per flush cycle instead of one
        # transaction + lock round trip each — N dispatcher threads
        # completing a batch coalesce their writes naturally while a
        # flush is in progress. Durability barrier: every status write
        # blocks until its op is committed, so a status the API can
        # serve (and a requeue a dispatcher can re-claim) is always on
        # disk first. DLI_STORE_FLUSH_MS>0 adds an explicit
        # accumulation window per flush; the default (0) batches purely
        # by backpressure. A crash mid-buffer leaves rows 'processing'
        # for recover_stale_processing() at next startup — the same
        # contract a crash mid-UPDATE always had.
        self._gc_enabled = bool(group_commit)
        self._gc_on_flush = on_flush
        if self._gc_enabled:
            if flush_interval is None:
                flush_interval = float(
                    os.environ.get("DLI_STORE_FLUSH_MS", 0)) / 1e3
            self._gc_interval = max(0.0, flush_interval)
            self._gc_cv = locks.condition("state.gc")
            self._gc_flush_lock = locks.lock("state.gc_flush")
            # re-entrancy guard: a write submitted FROM INSIDE a flush
            # (the flush-failure journal event) must only buffer — the
            # self-flush fallbacks below would re-acquire the flush
            # lock this thread already holds
            self._gc_local = threading.local()
            self._gc_buf: List[tuple] = []
            self._gc_enqueued = 0       # ticket of the newest buffered op
            self._gc_flushed = 0        # ticket of the newest committed op
            self._gc_wake = threading.Event()
            self._gc_stop = threading.Event()
            self._gc_thread = threading.Thread(
                target=self._gc_loop, daemon=True, name="store-flush")
            self._gc_thread.start()

    # ---- group-commit plumbing --------------------------------------

    def _submit_write(self, sql: str, args: tuple, barrier: bool):
        """Route one UPDATE through the write-behind buffer (group
        commit) or execute it synchronously when group commit is off.
        ``barrier=True`` waits for the commit — the durability barrier
        in front of any client-visible terminal status."""
        if not self._gc_enabled:
            self._exec(sql, args)
            if barrier and self._repl_barrier is not None:
                self._repl_barrier()
            return
        with self._gc_cv:
            self._gc_buf.append((sql, args))
            self._gc_enqueued += 1
            ticket = self._gc_enqueued
        self._gc_wake.set()
        if getattr(self._gc_local, "in_flush", False):
            # submitted from inside this thread's own flush (journal
            # event on a flush failure): it is buffered; the enclosing
            # flush's retry cycle — or close()'s final flush — owns it.
            # Self-flushing here would deadlock on _gc_flush_lock.
            return
        if self._gc_stop.is_set():
            # flusher gone (a dispatcher finishing its in-flight RPC after
            # close()): without this, a barrier=False write would sit in
            # the buffer forever and the terminal status would be lost
            self._flush_writes()
            return
        if barrier:
            while True:
                with self._gc_cv:
                    if self._gc_flushed >= ticket:
                        break
                    self._gc_cv.wait(timeout=1.0)
                    if self._gc_flushed >= ticket:
                        break
                if self._gc_stop.is_set():
                    # flusher gone (close() raced this write): any thread
                    # may flush — _flush_writes is safe to call anywhere
                    self._flush_writes()
            # Replication half of the durability barrier (HA pairs,
            # runtime/replication.py): a client-visible terminal status
            # additionally waits for a standby ack — bounded by the
            # hook's own timeout, which degrades to leader-only
            # durability with a journaled `replication-lag` event
            # rather than ever wedging a dispatch thread on a dead
            # peer. No-op outside HA or with DLI_HA_REPL_BARRIER off.
            if self._repl_barrier is not None:
                self._repl_barrier()

    def _flush_writes(self):
        # One flusher at a time: swap -> commit -> publish must be atomic
        # against other flushers, or a concurrent caller (barrier waiter
        # self-flushing after close(), or close() itself) could swap an
        # empty buffer, read the latest ticket, and publish it while THIS
        # flush still holds uncommitted ops — the barrier would report
        # durability for writes not yet on disk.
        with self._gc_flush_lock:
            self._gc_local.in_flush = True
            try:
                self._flush_locked()
            finally:
                self._gc_local.in_flush = False

    def _flush_locked(self):
        with self._gc_cv:
            ops, self._gc_buf = self._gc_buf, []
            ticket = self._gc_enqueued
        if ops:
            try:
                with self._lock:
                    with self._db:
                        for sql, args in ops:
                            self._db.execute(sql, args)
                    if self._op_hook is not None:
                        # committed batch -> one sequenced op-log frame
                        # (runtime/replication.py). Under the store
                        # lock so frames observe commit order.
                        self._op_hook(list(ops))
            except Exception as e:
                # sqlite hiccup (disk full, I/O error): the 'with
                # _db' transaction rolled back, so nothing reached
                # disk. Put the batch back AHEAD of anything
                # buffered since (order preserved) and leave the
                # ticket unpublished — barrier waiters correctly
                # stay blocked until a later flush succeeds.
                with self._gc_cv:
                    self._gc_buf[:0] = ops
                # flight recorder (runtime/events.py): a durability
                # failure is exactly the decision record a
                # postmortem needs. The event's own INSERT lands in
                # this same (currently failing) buffer — it rides
                # the ring immediately and the table once a flush
                # succeeds (the in_flush guard keeps it from
                # re-entering this flush); one event per failed
                # flush, so a long outage grows the buffer by one
                # row per retry cycle, not per blocked write.
                from distributed_llm_inferencing_tpu.runtime import \
                    events
                events.emit("store-flush-failed", error=repr(e)[:200],
                            ops=len(ops))
                raise
        with self._gc_cv:
            self._gc_flushed = max(self._gc_flushed, ticket)
            self._gc_cv.notify_all()
        if ops and self._gc_on_flush is not None:
            # e.g. the master's dispatcher wake event: a flushed requeue
            # is now claimable, don't wait out the idle poll to see it
            self._gc_on_flush()

    def _gc_loop(self):
        while not self._gc_stop.is_set():
            self._gc_wake.wait(timeout=0.5)
            if self._gc_stop.is_set():
                break
            self._gc_wake.clear()
            if self._gc_interval:
                # the group window: let concurrent dispatchers pile
                # their writes into this flush's transaction
                clock.sleep(self._gc_interval)
            try:
                self._flush_writes()
            except Exception:
                # The batch went back on the buffer. The flusher MUST
                # survive: if this thread died with _gc_stop unset,
                # every barrier=True writer would wait forever with no
                # recourse. Retry on the next cycle instead.
                log.exception("group-commit flush failed; "
                              "ops re-buffered, will retry")
                self._gc_wake.set()
                clock.sleep(0.5)
        try:
            self._flush_writes()
        except Exception:
            with self._gc_cv:
                n_lost = len(self._gc_buf)
            log.exception("final group-commit flush failed; "
                          "%d op(s) still buffered", n_lost)

    def flush(self):
        """Synchronously flush the write-behind buffer (no-op when group
        commit is off). Readers that must see *their own process's*
        buffered writes — the ``/api/events`` query path reading events
        emitted microseconds ago — call this instead of sprinkling
        barriers over every emit."""
        if self._gc_enabled:
            self._flush_writes()

    def close(self):
        """Flush buffered writes and stop the flusher. Idempotent."""
        if self._gc_enabled and self._gc_thread is not None:
            self._gc_stop.set()
            self._gc_wake.set()
            self._gc_thread.join(timeout=5)
            self._gc_thread = None
            self._flush_writes()

    def _all(self, sql, args=()) -> List[Dict[str, Any]]:
        with self._lock:
            cur = self._db.execute(sql, args)
            return [_row_to_dict(cur, r) for r in cur.fetchall()]

    def _one(self, sql, args=()) -> Optional[Dict[str, Any]]:
        rows = self._all(sql, args)
        return rows[0] if rows else None

    def _exec(self, sql, args=(), replicate: bool = True) -> int:
        with self._lock:
            with self._db:
                cur = self._db.execute(sql, args)
                rowid = cur.lastrowid
            if replicate and self._op_hook is not None:
                self._op_hook([(sql, args)])
            return rowid

    # ---- replication (runtime/replication.py) ------------------------

    def set_op_hook(self, hook: Optional[Callable[[list], None]]):
        """Install the committed-write hook the HA op-log shipper feeds
        on. Called with [(sql, args), ...] under the store lock, after
        the transaction committed."""
        self._op_hook = hook

    def set_repl_barrier(self, hook: Optional[Callable[[], None]]):
        """Install the standby-ack barrier hook run after a barriered
        write's local commit (leader side; must be timeout-bounded)."""
        self._repl_barrier = hook

    def apply_ops(self, ops) -> None:
        """Standby side: apply one replicated op frame in order, in ONE
        transaction. The ops are the leader's original parameterized
        SQL — WHERE guards included — so a replayed frame keeps every
        lifecycle invariant the leader's write had: a stale requeue or
        migrate op replayed after a terminal status is a no-op, never a
        resurrection (frame-level dedup by sequence number lives in the
        HA controller; this just executes). The op hook deliberately
        does NOT fire: a replica mirrors the leader's log, it does not
        re-originate it."""
        with self._lock, self._db:
            for sql, args in ops:
                self._db.execute(sql, tuple(args))

    def dump_tables(self) -> Dict[str, dict]:
        """Full-state snapshot for standby resync: every replicated
        table's rows, column-named, plus the AUTOINCREMENT high-water
        marks. The (multi-MB) TSDB snapshot meta row stays out — it is
        the leader's private ring dump, never replicated, and a standby
        rebuilds its own TSDB from scrapes."""
        out: Dict[str, dict] = {}
        with self._lock:
            for table in self.REPL_TABLES:
                cur = self._db.execute(f"SELECT * FROM {table}")
                cols = [d[0] for d in cur.description]
                rows = [list(r) for r in cur.fetchall()]
                if table == "meta":
                    ki = cols.index("key")
                    rows = [r for r in rows if r[ki] != "tsdb_snapshot"]
                out[table] = {"cols": cols, "rows": rows}
            try:
                cur = self._db.execute(
                    "SELECT name, seq FROM sqlite_sequence")
                out["_sqlite_sequence"] = {
                    "rows": [list(r) for r in cur.fetchall()]}
            except sqlite3.OperationalError:
                # lazily created: absent on a store that never did an
                # AUTOINCREMENT insert — nothing to carry
                out["_sqlite_sequence"] = {"rows": []}
        return out

    def snapshot_with(self, fn):
        """``(dump_tables(), fn())`` atomically under the store lock.
        The HA shipper pairs a snapshot with the op-log high-water mark
        this way: the op hook appends under this same lock right after
        each commit, so a seq read inside the critical section is
        exactly the last write the dump contains — read outside it, a
        write committing between the two would be labeled into the gap
        and silently never reach the standby."""
        with self._lock:
            return self.dump_tables(), fn()

    def load_tables(self, snap: Dict[str, dict]) -> None:
        """Replace the whole store with a leader snapshot (standby
        first-contact / post-divergence resync). Explicit ids — AND the
        replicated ``sqlite_sequence`` high-water marks — keep the
        AUTOINCREMENT counters in step with the leader (it never reuses
        an id after a DELETE), so the op stream that follows replays
        onto identical rowids."""
        with self._lock, self._db:
            for table in self.REPL_TABLES:
                data = snap.get(table)
                if not isinstance(data, dict):
                    continue
                self._db.execute(f"DELETE FROM {table}")
                cols = data.get("cols") or []
                if not cols:
                    continue
                ph = ",".join("?" for _ in cols)
                self._db.executemany(
                    f"INSERT INTO {table} ({','.join(cols)}) "
                    f"VALUES ({ph})",
                    [tuple(r) for r in data.get("rows") or []])
            seqs = (snap.get("_sqlite_sequence") or {}).get("rows") or []
            # sqlite_sequence only exists after some AUTOINCREMENT
            # insert — force it into existence with a seed cycle, then
            # overwrite it with the leader's counters. The clear is
            # UNCONDITIONAL: a standby on a reused file has counters of
            # its own, and a fresh leader's empty snapshot must reset
            # them too or the op stream replays onto diverged rowids.
            self._db.execute(
                "INSERT INTO events (ts, type) VALUES (0, '_seed')")
            self._db.execute(
                "DELETE FROM events WHERE type='_seed'")
            self._db.execute("DELETE FROM sqlite_sequence")
            if seqs:
                self._db.executemany(
                    "INSERT INTO sqlite_sequence (name, seq) "
                    "VALUES (?,?)",
                    [(str(n), int(s)) for n, s in seqs])

    # ---- nodes -------------------------------------------------------

    def add_node(self, name: str, host: str, port: int,
                 is_active: bool = False) -> int:
        return self._exec(
            "INSERT INTO nodes (name, host, port, is_active, added_at) "
            "VALUES (?,?,?,?,?)", (name, host, port, int(is_active), clock.now()))

    def get_node(self, node_id: int):
        return self._one("SELECT * FROM nodes WHERE id=?", (node_id,))

    def find_node(self, host: str, port: int):
        return self._one("SELECT * FROM nodes WHERE host=? AND port=?",
                         (host, port))

    def list_nodes(self, active_only=False):
        q = "SELECT * FROM nodes"
        if active_only:
            q += " WHERE is_active=1"
        return self._all(q + " ORDER BY id")

    def update_node(self, node_id: int, **fields):
        if "info" in fields and not isinstance(fields["info"], str):
            fields["info"] = json.dumps(_strip_ephemeral(fields["info"]))
        sets = ", ".join(f"{k}=?" for k in fields)
        self._exec(f"UPDATE nodes SET {sets} WHERE id=?",
                   (*fields.values(), node_id))

    def remove_node(self, node_id: int):
        self._exec("DELETE FROM nodes WHERE id=?", (node_id,))

    # kept out of the persisted node row: ephemeral routing state that is
    # re-advertised on every health scrape and only consumed from the
    # master's in-memory per-node runtime snapshot (_note_runtime). The
    # prefix-digest advertisement alone is up to a few KB per model per
    # sweep — persisting it would grow every health write for data that
    # is stale the moment the next scrape lands.
    EPHEMERAL_SCHEDULER_KEYS = ("prefix_digests",)

    def node_url(self, node) -> str:
        # ≙ WorkerNode.get_url (reference models.py:16-17)
        return f"http://{node['host']}:{node['port']}"

    # ---- plans -------------------------------------------------------

    def add_plan(self, model_name: str, plan: dict,
                 node_id: Optional[int] = None) -> int:
        return self._exec(
            "INSERT INTO plans (model_name, plan, node_id, created_at) "
            "VALUES (?,?,?,?)",
            (model_name, json.dumps(plan), node_id, clock.now()))

    def list_plans(self, model_name: Optional[str] = None):
        rows = self._all(
            "SELECT * FROM plans" +
            (" WHERE model_name=?" if model_name else "") + " ORDER BY id",
            (model_name,) if model_name else ())
        for r in rows:
            r["plan"] = json.loads(r["plan"])
        return rows

    def mark_plan_loaded(self, plan_id: int, node_id: int, loaded=True):
        self._exec("UPDATE plans SET is_loaded=?, node_id=? WHERE id=?",
                   (int(loaded), node_id, plan_id))

    # ---- requests ----------------------------------------------------

    def submit_request(self, model_name: str, prompt: str,
                       max_new_tokens: Optional[int] = 100,
                       sampling: Optional[dict] = None,
                       max_length: Optional[int] = None,
                       client_tag: Optional[str] = None,
                       slo_class: str = "throughput",
                       tenant: str = "default",
                       adapter: Optional[str] = None) -> int:
        """New request row; ``client_tag`` is the client's submit
        idempotency key — a tagged re-submit (the ack was lost: an HA
        leader died between committing the row and answering, or the
        response connection broke) returns the EXISTING row's id
        instead of creating a duplicate that would generate twice.
        SELECT-then-INSERT is atomic under the store lock, and the
        INSERT replicates with the tag so the dedup holds on the
        standby that takes over."""
        with self._lock:
            if client_tag:
                row = self._one(
                    "SELECT id FROM requests WHERE client_tag=?",
                    (client_tag,))
                if row:
                    return row["id"]
            return self._exec(
                "INSERT INTO requests (model_name, prompt, "
                "max_new_tokens, max_length, sampling, created_at, "
                "client_tag, slo_class, tenant, adapter) "
                "VALUES (?,?,?,?,?,?,?,?,?,?)",
                (model_name, prompt, max_new_tokens, max_length,
                 json.dumps(sampling or {}), clock.now(), client_tag,
                 slo_class, tenant, adapter))

    def find_client_tag(self, client_tag: str) -> Optional[int]:
        """The request id a submit idempotency key already names, or
        None (the api_submit fast path — lets the response mark the
        dedup explicitly)."""
        row = self._one("SELECT id FROM requests WHERE client_tag=?",
                        (client_tag,))
        return row["id"] if row else None

    @staticmethod
    def _parse_json_cols(row):
        for key in ("cost", "resume", "kv_source"):
            if row.get(key):
                try:
                    row[key] = json.loads(row[key])
                except ValueError:
                    row[key] = None

    def get_request(self, req_id: int):
        r = self._one("SELECT * FROM requests WHERE id=?", (req_id,))
        if r:
            r["sampling"] = json.loads(r["sampling"] or "{}")
            r["excluded_nodes"] = json.loads(r.get("excluded_nodes") or "[]")
            self._parse_json_cols(r)
        return r

    def claim_next_pending(self) -> Optional[Dict[str, Any]]:
        """Atomically move the oldest *due* pending request to processing.
        A request parked by a backoff retry (``next_attempt_at`` in the
        future) is invisible until its delay elapses — the dispatcher's
        idle poll re-examines the queue on its own cadence."""
        rows = self.claim_next_pending_many(1)
        return rows[0] if rows else None

    def claim_next_pending_many(self, limit: int = 1,
                                max_priority: Optional[int] = None
                                ) -> List[Dict[str, Any]]:
        """Atomically claim up to ``limit`` due pending requests in ONE
        locked transaction (single SELECT + executemany status flip) —
        the multiplexed dispatcher's entry point.

        Order is SLO-class priority (latency=0 < throughput=1 <
        batch=2) with deadline-style aging: every ``CLAIM_AGING_S``
        seconds of wait lowers a row's effective priority by one tier,
        ties break by id (submission order). A request that has waited
        the full priority span (2x aging) therefore outranks ANY fresh
        submit — the anti-starvation bound dliverify model-checks.
        With aging disabled the order is pure class priority then id;
        pre-migration rows (NULL slo_class) sit on the throughput tier.

        ``max_priority`` filters by *declared* class (not the aged
        value): the overload ladder's final rung passes 0 so a browned-
        out master claims only latency-tier work. Aging deliberately
        does not bypass the filter — rung 4 means "nothing but latency
        runs", and admission of lower tiers was already shut off two
        rungs earlier, so the filtered backlog is bounded."""
        with self._lock:
            now = clock.now()
            sel = ("SELECT * FROM requests WHERE status='pending' "
                   "AND next_attempt_at<=?")
            args: List[Any] = [now]
            if max_priority is not None:
                sel += " AND " + _SLO_PRIORITY_SQL + "<=?"
                args.append(int(max_priority))
            if CLAIM_AGING_S > 0:
                sel += (" ORDER BY (" + _SLO_PRIORITY_SQL +
                        " - (?-created_at)/?), id LIMIT ?")
                args += [now, CLAIM_AGING_S]
            else:
                sel += " ORDER BY " + _SLO_PRIORITY_SQL + ", id LIMIT ?"
            rows = self._all(sel, (*args, int(limit)))
            if not rows:
                return []
            flips = [(now, r["id"]) for r in rows]
            with self._db:
                # sql is walrus-bound IN the call so the lifecycle
                # checker resolves the literal's delivery mechanism AND
                # the op hook ships the identical statement
                self._db.executemany(
                    sql := ("UPDATE requests SET status='processing', "
                            "started_at=? WHERE id=?"), flips)
            if self._op_hook is not None:
                # claims replicate too: a standby's dashboard shows the
                # same processing rows, and takeover's
                # recover_stale_processing finds exactly the claims the
                # dead leader held in flight
                self._op_hook([(sql, a) for a in flips])
            for row in rows:
                row["started_at"] = now
                row["sampling"] = json.loads(row["sampling"] or "{}")
                row["excluded_nodes"] = json.loads(
                    row.get("excluded_nodes") or "[]")
                self._parse_json_cols(row)
            return rows

    def note_dispatch_node(self, req_id: int, node_id: int,
                           barrier: bool = False) -> None:
        """Record where a claimed request is being dispatched, BEFORE
        the RPC leaves. Status untouched — this is not a lifecycle
        transition, just the row's ``node_id`` hint — and the
        status='processing' guard keeps a slow write off a row that
        meanwhile went terminal. What it buys: the claim's replicated
        state names the node holding the in-flight generation, so a
        lease takeover's re-dispatch (and a restarted solo master's
        crash recovery) pins back to that node and joins/replays the
        worker's idempotent generation instead of re-running it on a
        peer. ``barrier=True`` (the master passes it when the HA
        durability barrier is armed) additionally waits for a standby
        ack, closing the last window: there is no kill point where a
        worker can be generating a request whose location the standby
        does not know."""
        self._submit_write(
            "UPDATE requests SET node_id=? WHERE id=? AND "
            "status='processing'", (node_id, req_id), barrier=barrier)

    def requeue(self, req_id: int, excluded_node_id: Optional[int] = None,
                delay_s: float = 0.0, last_node_id: Optional[int] = None):
        """Failover retry: back to pending with the attempt counted, the
        failed node recorded for cross-attempt exclusion, and the next
        attempt parked ``delay_s`` into the future (backoff).
        ``last_node_id`` records where this attempt ran (the row's
        node_id) — a timeout retry prefers that node, since it still
        holds the in-flight generation.

        Like the terminal writes this flows through the group-commit
        buffer and waits for the commit: a requeue must be claim-visible
        the moment it returns (dispatchers and tests read their own
        writes), and the read side of the ``excluded_nodes``
        read-modify-write stays safe because a request has at most one
        in-flight status op at a time."""
        extra = ""
        args: list = []
        if mutation_enabled("requeue_exclusion"):
            # dliverify mutation gate (docs/static_analysis.md): drop
            # the failed-node exclusion — the PR 2 bug where a retry
            # could land straight back on the node it just failed on.
            # Test-only; DLI_VERIFY_MUTATIONS is never set in prod.
            excluded_node_id = None
        if excluded_node_id is not None:
            row = self._one("SELECT excluded_nodes FROM requests "
                            "WHERE id=?", (req_id,))
            seen = json.loads((row or {}).get("excluded_nodes") or "[]")
            if excluded_node_id not in seen:
                seen.append(excluded_node_id)
            extra += ", excluded_nodes=?"
            args.append(json.dumps(seen))
        if last_node_id is not None:
            extra += ", node_id=?"
            args.append(last_node_id)
        self._submit_write(
            "UPDATE requests SET status='pending', attempts=attempts+1, "
            f"next_attempt_at=?{extra} WHERE id=?",
            (clock.now() + max(0.0, delay_s), *args, req_id),
            barrier=True)

    def requeue_migrated(self, req_id: int, resume: dict,
                         kv_source: Optional[dict] = None,
                         excluded_node_id: Optional[int] = None):
        """Live-migration handoff (the worker answered the in-flight
        dispatch with a 303 + resume record): back to pending WITHOUT
        burning an attempt — a handoff is not a failure — with the
        resume record and the kv_source hint (the source worker's host
        arena) persisted on the row, so the re-dispatch AND any later
        failover retry resume mid-stream instead of re-prefilling
        (FailSafe, arxiv 2511.14116). The migrated-off node joins
        ``excluded_nodes`` (the re-pick must not hand the request
        straight back to the node being drained) and ``node_id`` clears
        so the sticky-retry pin cannot either — a SOFT steer, not a
        death sentence: ``_pick_node`` falls back to excluded nodes
        whenever nothing else is schedulable, so excluding a healthy
        source can never strand the request. Guarded WHERE
        status='processing': a handoff racing a terminal write must
        never resurrect a finished row (the dliverify
        ``migrate_vs_complete`` scenario model-checks this)."""
        extra = ""
        args: list = []
        if excluded_node_id is not None:
            row = self._one("SELECT excluded_nodes FROM requests "
                            "WHERE id=?", (req_id,))
            seen = json.loads((row or {}).get("excluded_nodes") or "[]")
            if excluded_node_id not in seen:
                seen.append(excluded_node_id)
            extra += ", excluded_nodes=?"
            args.append(json.dumps(seen))
        if kv_source is not None:
            extra += ", kv_source=?"
            args.append(json.dumps(kv_source))
        self._submit_write(
            "UPDATE requests SET status='pending', next_attempt_at=0, "
            f"node_id=NULL, resume=?{extra} "
            "WHERE id=? AND status='processing'",
            (json.dumps(resume or {}), *args, req_id), barrier=True)

    def set_kv_source(self, req_id: int, kv_source: Optional[dict]):
        """Persist a disaggregated dispatch's transfer hint on the row:
        if the decode node dies mid-request, the failover retry
        re-dispatches with the hint intact — recovery costs one KV
        fetch from the still-alive prefill peer, not a re-prefill."""
        self._exec("UPDATE requests SET kv_source=? WHERE id=?",
                   (json.dumps(kv_source) if kv_source else None, req_id))

    def recover_stale_processing(self, max_attempts: Optional[int] = None
                                 ) -> int:
        """Requeue requests stranded in 'processing' by a master crash —
        the reference left these stuck forever (no recovery path at all,
        SURVEY.md §5.3). Called once at master startup.

        Recovery counts as an attempt: a poison request that kills its
        worker (or the master) must not be re-dispatched forever across
        restarts, so anything at ``max_attempts`` fails permanently here
        instead of re-entering the queue.
        """
        with self._lock:
            applied = []
            with self._db:
                failed = 0
                if max_attempts is not None:
                    args = (clock.now(), max_attempts)
                    failed = self._db.execute(
                        sql := ("UPDATE requests SET status='failed', "
                                "completed_at=?, "
                                "error='abandoned after repeated crash "
                                "recovery (poison request?)' "
                                "WHERE status='processing' "
                                "AND attempts+1>=?"), args).rowcount
                    applied.append((sql, args))
                recovered = self._db.execute(
                    sql := ("UPDATE requests SET status='pending', "
                            "attempts=attempts+1, next_attempt_at=0 "
                            "WHERE status='processing'"), ()).rowcount
                applied.append((sql, ()))
            if self._op_hook is not None:
                # a lease takeover's recovery replicates like any other
                # write: the WHERE status='processing' guards make the
                # replayed ops exact on a replica whose rows match
                self._op_hook(applied)
            return recovered + failed

    def mark_completed(self, req_id: int, result: str, node_id: int,
                       execution_time: float, tokens_per_s: float,
                       barrier: bool = True,
                       cost: Optional[dict] = None):
        # ≙ InferenceRequest.mark_completed (reference models.py:52-56).
        # Terminal status: with barrier=True the write is committed
        # before this returns. barrier=False still upholds the
        # durability-before-client-visibility rule — reads only ever
        # see committed state, so a status poll cannot observe
        # 'completed' before the commit lands; what it relaxes is THIS
        # caller blocking on the flush. The master's batch demultiplexer
        # uses that: a barrier wait per sub-request would hold up
        # reading the next result line off the stream. The cost record
        # rides the same UPDATE, so row and ledger commit atomically
        # (group-commit safe: one op, one transaction slot).
        # NOT IN terminal guard: a request reaches exactly ONE terminal
        # state — the first terminal write wins and a later racer
        # (e.g. a user cancel's mark_failed racing this completion)
        # no-ops instead of flipping a client-visible verdict. The
        # dliverify `terminal_once` scenario model-checks this under
        # every interleaving.
        self._submit_write(
            "UPDATE requests SET status='completed', result=?, node_id=?, "
            "completed_at=?, execution_time=?, tokens_per_s=?, cost=? "
            "WHERE id=? AND status NOT IN ('completed','failed')",
            (result, node_id, clock.now(), execution_time, tokens_per_s,
             json.dumps(cost) if cost is not None else None,
             req_id), barrier=barrier)

    def mark_failed(self, req_id: int, error: str, barrier: bool = True):
        # ≙ InferenceRequest.mark_failed (reference models.py:58-62);
        # terminal — same barrier semantics and NOT IN terminal guard
        # as mark_completed (first terminal write wins)
        self._submit_write(
            "UPDATE requests SET status='failed', error=?, completed_at=? "
            "WHERE id=? AND status NOT IN ('completed','failed')",
            (error, clock.now(), req_id), barrier=barrier)

    def recent_requests(self, limit: int = 20):
        return self._all(
            "SELECT * FROM requests ORDER BY id DESC LIMIT ?", (limit,))

    def counts(self) -> Dict[str, int]:
        rows = self._all(
            "SELECT status, COUNT(*) AS n FROM requests GROUP BY status")
        return {r["status"]: r["n"] for r in rows}

    def pending_by_class(self) -> Dict[str, int]:
        """Pending-queue depth per SLO class. The overload ladder's
        rung-4 de-escalation signal (master._overload_signals): at the
        top rung the dispatcher claims only latency work, so measuring
        ALL pending would hold the ladder up forever on the very rows
        the rung froze."""
        rows = self._all(
            "SELECT slo_class, COUNT(*) AS n FROM requests "
            "WHERE status='pending' GROUP BY slo_class")
        return {r["slo_class"]: r["n"] for r in rows}

    def pending_by_model(self) -> Dict[str, int]:
        """Pending-queue depth per model (the per-model ``queue_pending``
        gauges on the master's health cadence)."""
        rows = self._all(
            "SELECT model_name, COUNT(*) AS n FROM requests "
            "WHERE status='pending' GROUP BY model_name")
        return {r["model_name"]: r["n"] for r in rows}

    def next_pending_due(self) -> Optional[float]:
        """Earliest ``next_attempt_at`` among pending rows (None when
        the pending queue is empty). The dispatcher polls on its wake
        event; a discrete-event driver (tools/dlisim) instead jumps the
        virtual clock straight to this instant when every due request
        has been claimed and only parked ones remain."""
        row = self._one("SELECT MIN(COALESCE(next_attempt_at, 0)) AS t "
                        "FROM requests WHERE status='pending'")
        return float(row["t"]) if row and row["t"] is not None else None

    # ---- flight-recorder events (runtime/events.py) ------------------

    def append_event(self, ts: float, etype: str, severity: str,
                     node_id: Optional[int], request_id: Optional[int],
                     trace_id: Optional[str], data_json: str):
        """Persist one journal event through the group-commit buffer
        (barrier=False: an event is durable within a flush cycle; the
        journal's in-memory ring covers the gap for same-process
        readers via :meth:`flush`)."""
        self._submit_write(
            "INSERT INTO events (ts, type, severity, node_id, "
            "request_id, trace_id, data) VALUES (?,?,?,?,?,?,?)",
            (ts, etype, severity, node_id, request_id, trace_id,
             data_json), barrier=False)

    def prune_events(self, retain: int):
        """Cap the events table at ``retain`` rows (oldest dropped),
        through the same buffered path as the inserts so retention
        costs no extra transaction."""
        self._submit_write(
            "DELETE FROM events WHERE id <= "
            "(SELECT COALESCE(MAX(id), 0) FROM events) - ?",
            (max(0, int(retain)),), barrier=False)

    def query_events(self, etype: Optional[str] = None,
                     node_id: Optional[int] = None,
                     request_id: Optional[int] = None,
                     since: Optional[float] = None,
                     until: Optional[float] = None,
                     since_seq: Optional[int] = None,
                     limit: int = 500) -> List[Dict[str, Any]]:
        """Filtered journal read, oldest-first within the newest
        ``limit`` matches. A bounded window needs BOTH ends server-side:
        keeping the newest N since ``since`` and post-filtering by end
        time would drop exactly the in-window rows once enough newer
        events exist (the journey's node-context bug class). Callers
        that just emitted (the API handlers) run :meth:`flush` first so
        reads see their own writes.

        ``since_seq`` is the pagination cursor: strictly-after the given
        autoincrement rowid. ``since`` (a wall-clock ``ts>=`` bound)
        cannot paginate — two events stamped in the same second are
        skipped or double-served across pages — so pages chain on the
        last row's ``id`` instead, which is unique and monotone in
        emit order."""
        where, args = [], []
        if etype:
            where.append("type=?")
            args.append(str(etype))
        if since_seq is not None:
            where.append("id>?")
            args.append(int(since_seq))
        if node_id is not None:
            where.append("node_id=?")
            args.append(int(node_id))
        if request_id is not None:
            where.append("request_id=?")
            args.append(int(request_id))
        if since is not None:
            where.append("ts>=?")
            args.append(float(since))
        if until is not None:
            where.append("ts<=?")
            args.append(float(until))
        sql = "SELECT * FROM events"
        if where:
            sql += " WHERE " + " AND ".join(where)
        sql += " ORDER BY id DESC LIMIT ?"
        rows = self._all(sql, (*args, max(1, int(limit))))
        rows.reverse()
        for r in rows:
            try:
                r["data"] = json.loads(r.get("data") or "{}")
            except ValueError:
                r["data"] = {}
        return rows

    def count_events(self) -> int:
        row = self._one("SELECT COUNT(*) AS n FROM events")
        return int(row["n"]) if row else 0

    # ---- durable key/value metadata (TSDB snapshots etc.) ------------

    def set_meta(self, key: str, value: str, replicate: bool = True):
        """Durable master-side metadata (one synchronous transaction —
        callers are background loops, and a multi-MB TSDB snapshot does
        not belong in the group-commit buffer ahead of status writes).
        ``replicate=False`` keeps a key out of the HA op-log — the TSDB
        ring snapshot is the one user: it is this process's private
        dump, and shipping megabytes per cycle would starve the status
        stream for data a standby rebuilds from scrapes anyway."""
        self._exec("INSERT OR REPLACE INTO meta (key, value, updated_at) "
                   "VALUES (?,?,?)", (key, value, clock.now()),
                   replicate=replicate)

    def get_meta(self, key: str) -> Optional[str]:
        row = self._one("SELECT value FROM meta WHERE key=?", (key,))
        return row["value"] if row else None
