"""Dashboard pages: self-contained HTML+JS, no external assets.

Mirrors the reference's three screens and polling behavior
(reference: master/dashboard/templates/dashboard/{dashboard,
node_management,inference}.html) — stat cards + recent table (10s poll),
node add/remove with live utilization columns (10s poll), inference
submit/poll/view (2s status poll) — with TPU device stats in place of
CPU/GPU percent and no CDN dependencies (the reference pulled Bootstrap
and jQuery from CDNs, base.html:9-11,56-58).
"""

_STYLE = """
<style>
:root { --bg:#0f1419; --card:#1a2129; --text:#e6e8ea; --muted:#8a939e;
        --accent:#4da3ff; --ok:#3fb76f; --bad:#e0565b; --warn:#e0a33c; }
* { box-sizing:border-box; margin:0; }
body { background:var(--bg); color:var(--text);
       font:14px/1.5 system-ui,-apple-system,sans-serif; display:flex; }
nav { width:200px; min-height:100vh; background:var(--card); padding:20px 0; }
nav h1 { font-size:15px; padding:0 16px 16px; color:var(--accent); }
nav a { display:block; padding:10px 16px; color:var(--text);
        text-decoration:none; }
nav a:hover, nav a.active { background:#232c36; }
main { flex:1; padding:24px; max-width:1100px; }
h2 { font-size:18px; margin-bottom:16px; }
.cards { display:grid; grid-template-columns:repeat(4,1fr); gap:12px;
         margin-bottom:24px; }
.card { background:var(--card); border-radius:8px; padding:16px; }
.card .num { font-size:26px; font-weight:600; }
.card .label { color:var(--muted); font-size:12px; }
table { width:100%; border-collapse:collapse; background:var(--card);
        border-radius:8px; overflow:hidden; }
th, td { text-align:left; padding:9px 12px; border-bottom:1px solid #232c36;
         font-size:13px; }
th { color:var(--muted); font-weight:500; }
.pill { padding:2px 8px; border-radius:10px; font-size:12px; }
.pill.completed,.pill.online { background:#153f28; color:var(--ok); }
.pill.failed,.pill.offline { background:#47191b; color:var(--bad); }
.pill.pending { background:#3d3010; color:var(--warn); }
.pill.processing { background:#10304d; color:var(--accent); }
input, select, textarea { background:#10161c; color:var(--text);
  border:1px solid #2a3440; border-radius:6px; padding:8px; width:100%;
  font:inherit; }
button { background:var(--accent); color:#08131f; border:0; padding:9px 16px;
  border-radius:6px; font:inherit; font-weight:600; cursor:pointer; }
button:hover { filter:brightness(1.1); }
form .row { margin-bottom:12px; }
label { display:block; color:var(--muted); font-size:12px;
        margin-bottom:4px; }
pre.result { background:#10161c; padding:12px; border-radius:6px;
  white-space:pre-wrap; margin-top:12px; min-height:60px; }
.grid2 { display:grid; grid-template-columns:1fr 1fr; gap:24px; }
.muted { color:var(--muted); }
.charts { display:grid; grid-template-columns:repeat(3,1fr); gap:12px;
          margin-bottom:24px; }
.chart svg { width:100%; height:64px; display:block; }
.chart .legend { font-size:11px; color:var(--muted); }
.chart .legend b { font-weight:500; }
</style>
"""


# HTML-escape for every server-sourced string interpolated into innerHTML
# (node names, model names etc. arrive via the unauthenticated JSON API —
# without this, a crafted model_name is stored XSS against the operator).
_ESC = """
function esc(s) { return String(s).replace(/[&<>"']/g, c => (
  {'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c])); }
"""


def _nav(active: str) -> str:
    items = [("/", "Dashboard"), ("/nodes", "Nodes"), ("/inference", "Inference")]
    links = "".join(
        f'<a href="{h}" class="{"active" if h == active else ""}">{t}</a>'
        for h, t in items)
    return (f'<nav><h1>TPU Inference</h1>{links}'
            f'<div style="padding:16px" class="muted">'
            f'distributed_llm_inferencing_tpu</div></nav>')


DASHBOARD = f"""<!doctype html><html><head><title>Dashboard</title>{_STYLE}
</head><body>{_nav("/")}<main>
<h2>Cluster Dashboard</h2>
<div class="cards">
  <div class="card"><div class="num" id="n-nodes">–</div>
    <div class="label">active nodes</div></div>
  <div class="card"><div class="num" id="n-pending">–</div>
    <div class="label">pending</div></div>
  <div class="card"><div class="num" id="n-processing">–</div>
    <div class="label">processing</div></div>
  <div class="card"><div class="num" id="n-completed">–</div>
    <div class="label">completed</div></div>
  <div class="card"><div class="num" id="ha-role" style="font-size:18px">–</div>
    <div class="label" id="ha-detail">control plane (/api/ha)</div></div>
</div>
<h2>Batched Serving</h2>
<table><thead><tr><th>Node</th><th>Model</th><th>Mesh</th>
<th>Slots</th><th>Queued</th><th>Tokens out</th><th>Blocks free</th>
<th>Prefix hit rate</th></tr></thead>
<tbody id="serving"><tr><td colspan="8" class="muted">no batched models
</td></tr></tbody></table>
<h2 style="margin-top:24px">Cluster Metrics
  <span class="muted" style="font-size:12px">(scraped from each worker's
  /metrics; request timeline at <a href="/api/trace"
  style="color:var(--accent)">/api/trace</a> — load in Perfetto)</span></h2>
<table><thead><tr><th>Node</th><th>Status</th><th>Requests</th>
<th>Tokens</th><th>TTFT p50 (ms)</th><th>ITL p50 (ms)</th><th>Queue</th>
<th>Free KV blocks</th></tr></thead>
<tbody id="clustermetrics"><tr><td colspan="8" class="muted">no workers
</td></tr></tbody></table>
<h2 style="margin-top:24px">Telemetry
  <span class="muted" style="font-size:12px">(master TSDB —
  <a href="/api/timeseries" style="color:var(--accent)">/api/timeseries</a>;
  per-request cost at /api/requests/&lt;id&gt;/cost)</span></h2>
<div class="cards" id="slo-cards">
  <div class="card"><div class="num" id="slo-att">–</div>
    <div class="label">SLO attainment (5m)</div></div>
  <div class="card"><div class="num" id="slo-burn">–</div>
    <div class="label">burn rate (5m)</div></div>
  <div class="card"><div class="num" id="slo-viol">–</div>
    <div class="label">violations / requests</div></div>
  <div class="card"><div class="num" id="slo-targets">–</div>
    <div class="label">targets TTFT / ITL p95 (ms)</div></div>
</div>
<div class="charts" id="charts"></div>
<h2 style="margin-top:24px">Flight Recorder
  <span class="muted" style="font-size:12px">(durable event journal —
  <a href="/api/events" style="color:var(--accent)">/api/events</a>;
  per-request journey at /api/requests/&lt;id&gt;/journey; event ticks
  overlay the sparklines above, so a goodput dip lines up with the
  flip/migration/trip that caused it)</span></h2>
<table><thead><tr><th>Time</th><th>Severity</th><th>Type</th><th>Node</th>
<th>Request</th><th>Detail</th></tr></thead>
<tbody id="events"><tr><td colspan="6" class="muted">no events</td></tr>
</tbody></table>
<h2 style="margin-top:24px">Recent Requests</h2>
<table><thead><tr><th>ID</th><th>Model</th><th>Status</th><th>tok/s</th>
<th>Latency (s)</th><th>Node</th></tr></thead>
<tbody id="recent"></tbody></table>
<script>{_ESC}
async function refresh() {{
  try {{
    const ns = await (await fetch('/api/nodes/status')).json();
    document.getElementById('n-nodes').textContent =
      ns.nodes.filter(n => n.is_active).length;
    // replicated control plane (runtime/replication.py): which master
    // this page is served by, the lease term, and peer replication
    // state — on a standby this whole dashboard reads the replica
    try {{
      const ha = await (await fetch('/api/ha')).json();
      if (ha.enabled) {{
        const acked = (ha.peers || []).map(p => p.acked_seq).join('/');
        document.getElementById('ha-role').textContent =
          (ha.is_leader ? 'leader' : 'standby') + ' · term ' + ha.term;
        document.getElementById('ha-detail').textContent =
          'op-log ' + ha.log_seq + ' · peers acked ' + (acked || '–');
      }} else {{
        document.getElementById('ha-role').textContent = 'solo';
      }}
    }} catch (e) {{ /* HA surface best-effort */ }}
    // live continuous-batcher internals (runtime/batcher.py stats(),
    // carried on /health -> node info): slots, queue, prefix-cache hits
    const rows = [];
    for (const n of ns.nodes)
      for (const m of n.loaded_models || [])
        if (m.serving === 'batched' && m.scheduler) {{
          const s = m.scheduler, p = s.pool || {{}};
          const hits = p.prefix_hits || 0, miss = p.prefix_misses || 0;
          const hr = (hits + miss) ? (100 * hits / (hits + miss)).toFixed(0) + '%' : '–';
          const mesh = Object.entries(s.mesh || {{}}).filter(e => e[1] > 1)
            .map(e => e.join('=')).join(' ') || '1 chip';
          rows.push(`<tr><td>${{esc(n.name)}}</td><td>${{esc(m.name)}}</td>`+
            `<td>${{esc(mesh)}}</td><td>${{s.active}}/${{s.slots}}</td>`+
            `<td>${{s.queued}}</td><td>${{s.tokens_out}}</td>`+
            `<td>${{s.blocks_free}}</td><td>${{hr}}</td></tr>`);
        }}
    document.getElementById('serving').innerHTML = rows.join('') ||
      '<tr><td colspan="8" class="muted">no batched models</td></tr>';
    // per-node metrics: the master's /api/cluster_metrics scrape
    // (counters summed, histogram p50s interpolated master-side).
    // Guarded separately: a slow/failed scrape must not freeze the
    // request counters and tables below it.
    try {{
    const cm = await (await fetch('/api/cluster_metrics')).json();
    const ms = (h, k) => h && h[k] && h[k].p50 != null ?
      (h[k].p50 * 1000).toFixed(1) : '–';
    document.getElementById('clustermetrics').innerHTML =
      (cm.nodes || []).map(n => {{
        const c = n.counters || {{}}, g = n.gauges || {{}},
              h = n.histograms || {{}};
        const st = n.scraped ? 'online' : 'offline';
        return `<tr><td>${{esc(n.name)}}</td>`+
          `<td><span class="pill ${{st}}">${{n.scraped ? 'scraped'
            : esc(n.error || 'unreachable')}}</span></td>`+
          `<td>${{c.requests_completed ?? 0}}</td>`+
          `<td>${{c.tokens_generated ?? 0}}</td>`+
          `<td>${{ms(h, 'batcher_ttft_seconds')}}</td>`+
          `<td>${{ms(h, 'batcher_inter_token_seconds')}}</td>`+
          `<td>${{g.batcher_queue_depth ?? '–'}}</td>`+
          `<td>${{g.batcher_free_kv_blocks ?? '–'}}</td></tr>`;
      }}).join('') ||
      '<tr><td colspan="8" class="muted">no workers</td></tr>';
    }} catch (e) {{ console.error(e); }}
    const r = await (await fetch('/api/inference/recent')).json();
    for (const k of ['pending','processing','completed'])
      document.getElementById('n-'+k).textContent = r.counts[k] || 0;
    document.getElementById('recent').innerHTML = r.requests.map(q =>
      `<tr><td>${{q.id}}</td><td>${{esc(q.model_name)}}</td>`+
      `<td><span class="pill ${{q.status}}">${{q.status}}</span></td>`+
      `<td>${{q.tokens_per_s ? q.tokens_per_s.toFixed(1) : ''}}</td>`+
      `<td>${{q.execution_time ? q.execution_time.toFixed(2) : ''}}</td>`+
      `<td>${{q.node_id ?? ''}}</td></tr>`).join('');
  }} catch (e) {{ console.error(e); }}
}}
refresh(); setInterval(refresh, 10000);  // 10s, like reference dashboard.html:119-134

// ---- telemetry charts: live sparklines off the master TSDB ----------
const TS_METRICS = [
  ['tokens_generated', 'tok/s (rate, per node)'],
  ['decode_tokens_per_weight_pass', 'tokens / weight pass (per node)'],
  ['spec_wave_accepted_tokens', 'spec accepted tok/s (rate, per node)'],
  ['batcher_queue_depth', 'queue depth (per node)'],
  ['batcher_free_kv_blocks', 'free KV blocks (per node)'],
  ['prefix_hit_ratio', 'prefix-cache hit ratio'],
  ['lora_requests', 'LoRA adapter requests/s (rate, per node)'],
  ['lora_host_adapters', 'LoRA adapters resident in host store (per node)'],
  ['kv_transfer_bytes', 'KV transfer B/s (rate, per node)'],
  ['kv_wire_compression', 'KV wire compression (logical/sent, per node)'],
  ['worker_role', 'role (0 mixed / 1 prefill / 2 decode)'],
  ['breaker_state', 'breaker (0 closed / 1 half-open / 2 open)'],
  ['slo_attainment', 'SLO attainment (master)'],
  ['queue_pending', 'pending queue depth (master)'],
  ['overload_level', 'overload ladder rung (master)'],
  ['admit_rejected', 'admission refusals/s (429 rate, master)'],
  ['shed_batch', 'shed batch/s (rate, master)'],
  ['shed_throughput', 'shed throughput/s (rate, master)'],
];
const TS_COLORS = ['#4da3ff','#3fb76f','#e0a33c','#e0565b','#b07cf0',
                   '#52c7d8','#8a939e'];
const SEV_COLORS = {{info:'#52c7d8', warning:'#e0a33c', error:'#e0565b'}};
function sparkline(series, w, h, evts) {{
  // shared y-scale across the metric's nodes so lines are comparable
  let lo = Infinity, hi = -Infinity;
  for (const s of series) for (const [, v] of s.points) {{
    if (v < lo) lo = v; if (v > hi) hi = v; }}
  if (!isFinite(lo)) return '<svg></svg>';
  if (hi === lo) {{ hi = lo + 1; }}
  let t0 = Infinity, t1 = -Infinity;
  for (const s of series) for (const [t] of s.points) {{
    if (t < t0) t0 = t; if (t > t1) t1 = t; }}
  if (t1 === t0) t1 = t0 + 1;
  const x = t => 2 + (w - 4) * (t - t0) / (t1 - t0);
  const y = v => h - 3 - (h - 6) * (v - lo) / (hi - lo);
  // flight-recorder overlay: one dashed tick per journal event inside
  // this chart's time window, colored by severity — the dip and its
  // cause share an x coordinate
  const ticks = (evts || []).filter(ev => ev.ts >= t0 && ev.ts <= t1)
    .map(ev => `<line x1="${{x(ev.ts).toFixed(1)}}" `
      + `x2="${{x(ev.ts).toFixed(1)}}" y1="0" y2="${{h}}" `
      + `stroke="${{SEV_COLORS[ev.severity] || '#8a939e'}}" `
      + `stroke-width="1" stroke-dasharray="2,3" opacity="0.7">`
      + `<title>${{esc(ev.type)}}</title></line>`).join('');
  const lines = series.map((s, i) =>
    `<polyline fill="none" stroke="${{TS_COLORS[i % TS_COLORS.length]}}"
      stroke-width="1.5" points="${{s.points.map(
        ([t, v]) => x(t).toFixed(1) + ',' + y(v).toFixed(1)).join(' ')}}"/>`
  ).join('');
  return `<svg viewBox="0 0 ${{w}} ${{h}}" preserveAspectRatio="none">`
    + `<text x="2" y="10" fill="#8a939e" font-size="9">`
    + `${{hi.toPrecision(3)}}</text>`
    + `<text x="2" y="${{h - 1}}" fill="#8a939e" font-size="9">`
    + `${{lo.toPrecision(3)}}</text>` + ticks + lines + '</svg>';
}}
async function refreshTelemetry() {{
  try {{
    const slo = await (await fetch('/api/slo')).json();
    const att = slo.attainment_fast;
    document.getElementById('slo-att').textContent =
      att != null ? (att * 100).toFixed(1) + '%' : '–';
    document.getElementById('slo-burn').textContent =
      slo.burn_rate_fast != null ? slo.burn_rate_fast.toFixed(2) : '–';
    document.getElementById('slo-viol').textContent =
      `${{slo.violations_total ?? 0}} / ${{slo.requests_total ?? 0}}`;
    const t = slo.targets || {{}};
    document.getElementById('slo-targets').textContent =
      `${{t.ttft_ms ?? '–'}} / ${{t.itl_p95_ms ?? '–'}}`;
    // all series fetched in parallel: a refresh costs one RTT, not
    // sum-of-latencies, and one slow endpoint can't stall the rest —
    // the flight-recorder journal rides the same parallel fetch
    const [evResult, ...results] = await Promise.all(
      [fetch('/api/events?limit=120').then(r => r.json())
         .catch(() => ({{}}))].concat(TS_METRICS.map(([m]) =>
      fetch('/api/timeseries?metric=' + encodeURIComponent(m))
        .then(r => r.json()).catch(() => ({{}})))));
    const evts = evResult.events || [];
    const cards = TS_METRICS.map(([m, title], j) => {{
      // >= 2: a one-point polyline draws nothing and reads as a broken
      // chart — show the placeholder until a line can exist
      const series = (results[j].series || [])
        .filter(s => s.points.length >= 2);
      const legend = series.map((s, i) =>
        `<b style="color:${{TS_COLORS[i % TS_COLORS.length]}}">●</b> `
        + esc(s.node)).join(' ');
      return `<div class="card chart"><div class="label">`
        + `${{esc(title)}}</div>`
        + (series.length ? sparkline(series, 260, 64, evts)
                         : '<div class="muted">no samples</div>')
        + `<div class="legend">${{legend}}</div></div>`;
    }});
    document.getElementById('charts').innerHTML = cards.join('');
    // flight-recorder table: newest first, request ids link to the
    // merged journey view
    document.getElementById('events').innerHTML =
      evts.slice(-25).reverse().map(ev => {{
        const sev = ev.severity || 'info';
        const cls = sev === 'error' ? 'failed'
          : sev === 'warning' ? 'pending' : 'processing';
        const req = ev.request_id != null
          ? `<a href="/api/requests/${{ev.request_id}}/journey" `
            + `style="color:var(--accent)">#${{ev.request_id}}</a>` : '–';
        return `<tr><td>${{new Date(ev.ts * 1000)
            .toLocaleTimeString()}}</td>`
          + `<td><span class="pill ${{cls}}">${{esc(sev)}}</span></td>`
          + `<td>${{esc(ev.type)}}</td>`
          + `<td>${{ev.node != null ? esc(ev.node)
                    : (ev.node_id ?? '–')}}</td>`
          + `<td>${{req}}</td>`
          + `<td class="muted">${{esc(JSON.stringify(
              ev.data || {{}}))}}</td></tr>`;
      }}).join('') ||
      '<tr><td colspan="6" class="muted">no events</td></tr>';
  }} catch (e) {{ console.error(e); }}
}}
refreshTelemetry(); setInterval(refreshTelemetry, 10000);
</script></main></body></html>"""


NODES = f"""<!doctype html><html><head><title>Nodes</title>{_STYLE}
</head><body>{_nav("/nodes")}<main>
<h2>Worker Nodes</h2>
<table><thead><tr><th>ID</th><th>Name</th><th>Address</th><th>Status</th>
<th>Role</th>
<th>Devices</th><th>CPU %</th><th>Mem %</th><th>Models</th><th>In-flight</th>
<th>Queue</th><th>Free KV</th><th>Arena</th><th>Adapters</th>
<th>Lat EWMA</th>
<th>Prefix hit</th>
<th></th></tr></thead><tbody id="nodes"></tbody></table>
<h2 style="margin-top:24px">Placement Plans</h2>
<table><thead><tr><th>ID</th><th>Model</th><th>Mesh</th><th>Devices</th>
<th>HBM/device</th><th>Max seq</th><th>Node</th><th>Loaded</th><th></th>
</tr></thead>
<tbody id="plans"><tr><td colspan="9" class="muted">no plans</td></tr>
</tbody></table>
<div class="row" style="margin-top:8px">
  <label>Checkpoint path for deploys (empty = random-init demo)</label>
  <input id="deploy-ckpt" placeholder="/path/to/native/checkpoint">
  <span id="deploy-msg" class="muted"></span></div>
<h2 style="margin-top:24px">Add Node</h2>
<div class="grid2"><form id="add">
  <div class="row"><label>Name</label><input name="name" required></div>
  <div class="row"><label>Host</label><input name="host" required
       placeholder="127.0.0.1"></div>
  <div class="row"><label>Port</label><input name="port" value="8100"></div>
  <button>Add Node</button> <span id="add-msg" class="muted"></span>
</form>
<form id="mkplan">
  <h3 style="margin:0 0 8px">Create Placement Plan</h3>
  <div class="row"><label>Model</label><input name="model" value="gpt2"></div>
  <div class="row"><label>Mesh (tp pp dp sp ep)</label>
    <div style="display:flex;gap:8px">
      <input name="tp" value="1"><input name="pp" value="1">
      <input name="dp" value="1"><input name="sp" value="1">
      <input name="ep" value="1"></div></div>
  <div class="row"><label>Max seq</label>
    <input name="max_seq" value="2048"></div>
  <button>Create Plan</button> <span id="mkplan-msg" class="muted"></span>
</form></div>
<script>{_ESC}
function gib(b) {{ return b >= 2**30 ? (b/2**30).toFixed(1)+' GiB'
  : b >= 2**20 ? (b/2**20).toFixed(1)+' MiB' : (b/2**10).toFixed(0)+' KiB'; }}
async function refreshPlans() {{
  // shard-placement visibility (≙ reference node_management.html:154-171,
  // which showed ModelShard rows): placement plans + where they landed
  const r = await (await fetch('/api/plans')).json();
  document.getElementById('plans').innerHTML = (r.plans || []).map(p => {{
    const plan = p.plan || {{}};
    const mesh = Object.entries(plan.mesh || {{}}).filter(e => e[1] > 1)
      .map(e => e.join('=')).join(' ') || '1 chip';
    return `<tr><td>${{p.id}}</td><td>${{esc(p.model_name)}}</td>`+
    `<td>${{esc(mesh)}}</td><td>${{plan.num_devices ?? ''}}</td>`+
    `<td>${{plan.hbm_per_device_estimate ?
            gib(plan.hbm_per_device_estimate) : ''}}</td>`+
    `<td>${{plan.max_seq ?? ''}}</td><td>${{p.node_id ?? '–'}}</td>`+
    `<td><span class="pill ${{p.is_loaded ? 'online' : 'pending'}}">`+
    `${{p.is_loaded ? 'deployed' : 'planned'}}</span></td>`+
    `<td>${{p.is_loaded ? '' :
      `<button onclick="deployPlan(${{p.id}})">Deploy</button>`}}</td></tr>`;
  }}).join('') || '<tr><td colspan="9" class="muted">no plans</td></tr>';
}}
async function deployPlan(id) {{
  // ≙ the mutation surface the reference kept in Django admin
  // (admin.py:9-13 was the only way to mark a shard loaded); here the
  // deploy actually pushes the plan to a worker via /load_shard
  const ckpt = document.getElementById('deploy-ckpt').value.trim();
  const body = ckpt ? {{checkpoint_path: ckpt}} : {{allow_random_init: true}};
  const res = await fetch('/api/plans/deploy/'+id,
    {{method:'POST', body:JSON.stringify(body)}});
  const j = await res.json();
  document.getElementById('deploy-msg').textContent =
    j.status === 'success' ? ('plan '+id+' deployed') : j.message;
  refreshPlans();
}}
async function refresh() {{
  refreshPlans();
  const r = await (await fetch('/api/nodes/status')).json();
  document.getElementById('nodes').innerHTML = r.nodes.map(n => {{
    // device inventory: prefer the stale-gated live snapshot (n.devices,
    // nulled past SCHED_STALE_S like queue depth), fall back to the
    // registration-time resources blob for never-scraped nodes
    const devList = n.devices || (n.resources && n.resources.devices) || [];
    const byKind = {{}};
    devList.forEach(d => {{
      const kind = d.kind || d.platform || 'dev';
      const mem = d.memory_bytes ? ' '+gib(d.memory_bytes) : '';
      const k = kind + mem;
      byKind[k] = (byKind[k] || 0) + 1;
    }});
    const dev = esc(Object.entries(byKind)
      .map(e => `${{e[1]}}x ${{e[0]}}`).join(', '));
    const models = n.loaded_models.map(m =>
      `${{esc(m.name)}} [${{esc(m.serving === 'batched' ? 'batched'
        : Object.entries(m.mesh || {{}}).filter(e=>e[1]>1)
        .map(e=>e.join('=')).join(' ') || '1 chip')}}]`).join('<br>');
    // breaker-aware status: closed=online, open=tripped offline,
    // half_open=probing its way back, draining=finishing in-flight work
    const st = n.draining ? 'draining'
      : (n.breaker || (n.is_active ? 'closed' : 'open'));
    const stCls = st === 'closed' ? 'online'
      : st === 'open' ? 'offline' : 'pending';
    const stTxt = (st === 'closed' ? 'online'
      : st === 'open' ? 'tripped' : st.replace('_', '-'))
      + (n.strikes ? ` (${{n.strikes}} strikes)` : '');
    return `<tr><td>${{n.id}}</td><td>${{esc(n.name)}}</td>`+
    `<td>${{esc(n.host)}}:${{esc(n.port)}}</td>`+
    `<td><span class="pill ${{stCls}}">${{stTxt}}</span></td>`+
    // disaggregation role (mutable via POST /role — the elastic
    // rebalancer flips pools at runtime): null means the worker's
    // advertisement went stale past SCHED_STALE_S, same cutoff as the
    // queue/arena columns — render the dash, not a frozen role
    `<td>${{n.role != null ? esc(n.role) : '–'}}</td>`+
    `<td>${{dev}}</td>`+
    `<td>${{n.resources && n.resources.cpu != null ? n.resources.cpu : ''}}</td>`+
    `<td>${{n.resources && n.resources.memory != null ? n.resources.memory : ''}}</td>`+
    `<td>${{models}}</td><td>${{n.inflight}}</td>`+
    // queue-aware scheduler inputs: worker-reported batcher queue
    // depth, free KV blocks, and the master's completion-latency EWMA
    `<td>${{n.queue_depth ?? '–'}}</td>`+
    `<td>${{n.free_kv_blocks ?? '–'}}</td>`+
    // host-arena occupancy: >90% triggers the prefill-pick avoidance
    `<td>${{n.arena_occupancy != null
        ? Math.round(n.arena_occupancy*100)+'%' : '–'}}</td>`+
    / resident LoRA adapters (count + host bytes) — stale-gated like
    // queue depth; the names ride a hover title
    `<td>${{n.adapters != null && n.adapters.resident.length
        ? `<span title="${{n.adapters.resident.join(', ')}}">`
          + n.adapters.resident.length+' ('+gib(n.adapters.bytes)+')</span>'
        : '–'}}</td>`+
    `<td>${{n.latency_ewma_ms != null ? n.latency_ewma_ms+' ms' : '–'}}</td>`+
    // prefix-cache tier outcome: the node's radix hit ratio (affinity
    // routing should drive this UP on shared-prefix traffic)
    `<td>${{n.prefix_hit_ratio != null
        ? Math.round(n.prefix_hit_ratio*100)+'%' : '–'}}</td>`+
    `<td><button onclick="removeNode(${{n.id}})">Remove</button></td></tr>`;
  }}).join('');
}}
async function removeNode(id) {{
  await fetch('/api/nodes/remove/'+id, {{method:'POST'}});
  refresh();
}}
document.getElementById('add').addEventListener('submit', async e => {{
  e.preventDefault();
  const f = new FormData(e.target);
  const body = {{name:f.get('name'), host:f.get('host'),
                port:parseInt(f.get('port'))}};
  const res = await fetch('/api/nodes/add',
    {{method:'POST', body:JSON.stringify(body)}});
  const j = await res.json();
  document.getElementById('add-msg').textContent =
    j.status === 'success' ? 'added' : j.message;
  refresh();
}});
document.getElementById('mkplan').addEventListener('submit', async e => {{
  e.preventDefault();
  const f = new FormData(e.target);
  const mesh = {{}};
  for (const ax of ['tp','pp','dp','sp','ep'])
    mesh[ax] = parseInt(f.get(ax)) || 1;
  const body = {{model_name: f.get('model'), mesh: mesh,
                max_seq: parseInt(f.get('max_seq')) || 2048}};
  const res = await fetch('/api/plans/create',
    {{method:'POST', body:JSON.stringify(body)}});
  const j = await res.json();
  document.getElementById('mkplan-msg').textContent =
    j.status === 'success' ? ('plan '+j.plan_id+' created') : j.message;
  refreshPlans();
}});
refresh(); setInterval(refresh, 10000);  // 10s, like node_management.html:221-229
</script></main></body></html>"""


INFERENCE = f"""<!doctype html><html><head><title>Inference</title>{_STYLE}
</head><body>{_nav("/inference")}<main>
<div class="grid2">
<div>
<h2>Run Inference</h2>
<form id="run">
  <div class="row"><label>Model</label><input name="model" value="gpt2"></div>
  <div class="row"><label>Prompt</label>
    <textarea name="prompt" rows="5" required></textarea></div>
  <div class="row"><label>Max new tokens</label>
    <input name="max_new_tokens" value="100"></div>
  <div class="row"><label>Temperature / top-k / top-p</label>
    <div style="display:flex;gap:8px">
      <input name="temperature" value="0.8"><input name="top_k" value="50">
      <input name="top_p" value="0.95"></div></div>
  <button>Submit</button> <span id="run-msg" class="muted"></span>
</form>
<h2 style="margin-top:16px">Result</h2>
<pre class="result" id="result"></pre>
</div>
<div>
<h2>Recent</h2>
<table><thead><tr><th>ID</th><th>Model</th><th>Status</th><th></th></tr>
</thead><tbody id="recent"></tbody></table>
</div></div>
<script>{_ESC}
let pollTimer = null;
async function refresh() {{
  const r = await (await fetch('/api/inference/recent')).json();
  document.getElementById('recent').innerHTML = r.requests.map(q =>
    `<tr><td>${{q.id}}</td><td>${{esc(q.model_name)}}</td>`+
    `<td><span class="pill ${{q.status}}">${{q.status}}</span></td>`+
    `<td><button onclick="view(${{q.id}})">view</button></td></tr>`).join('');
}}
async function view(id) {{
  const r = await (await fetch('/api/inference/status/'+id)).json();
  const q = r.request;
  document.getElementById('result').textContent =
    q.status === 'completed' ? q.result :
    q.status === 'failed' ? 'FAILED: ' + q.error : '(' + q.status + ')';
}}
function poll(id) {{
  if (pollTimer) clearInterval(pollTimer);
  pollTimer = setInterval(async () => {{   // 2s, like inference.html:206-258
    const r = await (await fetch('/api/inference/status/'+id)).json();
    const q = r.request;
    if (q.status === 'completed' || q.status === 'failed') {{
      clearInterval(pollTimer); view(id); refresh();
    }}
  }}, 2000);
}}
document.getElementById('run').addEventListener('submit', async e => {{
  e.preventDefault();
  const f = new FormData(e.target);
  const body = {{
    model_name: f.get('model'), prompt: f.get('prompt'),
    max_new_tokens: parseInt(f.get('max_new_tokens')),
    sampling: {{ temperature: parseFloat(f.get('temperature')),
                top_k: parseInt(f.get('top_k')),
                top_p: parseFloat(f.get('top_p')) }} }};
  const res = await fetch('/api/inference/submit',
    {{method:'POST', body:JSON.stringify(body)}});
  const j = await res.json();
  if (j.status === 'success') {{
    document.getElementById('run-msg').textContent = 'request ' + j.request_id;
    document.getElementById('result').textContent = '(pending)';
    poll(j.request_id);
  }} else document.getElementById('run-msg').textContent = j.message;
  refresh();
}});
refresh(); setInterval(refresh, 10000);
</script></main></body></html>"""
