"""Minimal JSON-over-HTTP service base (stdlib only).

Both the worker agent and the master control plane are built on this —
the TPU build's stand-in for the reference's Flask (worker/app.py) and
Django (master/) stacks, with the same wire shape: JSON bodies, bearer-token
auth (reference: worker/app.py:32-47), and structured error responses
(reference: worker/app.py:133-137).
"""

from __future__ import annotations

import json
import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from distributed_llm_inferencing_tpu.utils import clock, locks, trace
from distributed_llm_inferencing_tpu.utils.faults import FaultInjector


# Monitoring surfaces polled every few seconds (master health loop,
# dashboard, Prometheus scrapers): their server spans are pure
# self-inflicted noise that would evict real request spans from the
# tracer's ring buffer, so they run un-recorded (headers/propagation
# still work — utils/trace.py span(keep=False)).
QUIET_TRACE_PATHS = frozenset(
    {"/health", "/metrics", "/api/trace", "/api/cluster_metrics",
     "/api/nodes/status", "/api/inference/recent", "/api/timeseries",
     "/api/slo", "/api/profile", "/api/events",
     # HA peer channel: heartbeat frames land every lease/3 — pure
     # span noise — and the discovery endpoints are poll surfaces
     "/replicate", "/api/ha", "/api/leader"})


class Route:
    def __init__(self, method: str, pattern: str, fn: Callable):
        self.method = method
        self.regex = re.compile("^" + re.sub(
            r"<(\w+)>", r"(?P<\1>[^/]+)", pattern) + "/?$")
        self.fn = fn


class JsonHTTPService:
    """Register handlers; serve with ThreadingHTTPServer.

    Handler signature: fn(body: dict, **path_params) -> (status, payload),
    -> (status, payload, headers), or -> payload (200 implied). Payload
    of type (bytes, content_type) passes through raw (HTML pages, SSE
    handled separately).
    """

    def __init__(self, name: str, auth_key: Optional[str] = None,
                 max_inflight: Optional[int] = None):
        self.name = name
        self.auth_key = auth_key
        self.routes: List[Route] = []
        self._server: Optional[ThreadingHTTPServer] = None
        # bounded in-flight request cap (0 = uncapped): thread-per-
        # request ingress answers 503 + Retry-After once this many
        # requests are mid-dispatch, so a connection flood hits a wall
        # BEFORE it can exhaust memory — admission control proper
        # (master api_submit) only runs after a handler thread exists
        self.max_inflight = (int(os.environ.get(
            "DLI_HTTPD_MAX_INFLIGHT", 0)) if max_inflight is None
            else int(max_inflight))
        # Fault-injection harness (utils/faults.py): armed from DLI_FAULTS
        # at construction or at runtime via the admin endpoints below.
        # Pays one lock acquire per request when nothing is armed. The
        # admin surface is a remote kill switch (mode "crash"), so it
        # only exists when fault injection is explicitly enabled —
        # production services never expose it by accident.
        self.faults = FaultInjector.from_env(name)
        if os.environ.get("DLI_FAULTS") or \
                os.environ.get("DLI_FAULTS_ENABLE", "").lower() in \
                ("1", "true"):
            self.add("GET", "/api/faults", self.faults.api_get)
            self.add("POST", "/api/faults", self.faults.api_post)
            self.add("POST", "/api/faults/clear", self.faults.api_clear)

    def route(self, method: str, pattern: str):
        def deco(fn):
            self.routes.append(Route(method, pattern, fn))
            return fn
        return deco

    def add(self, method: str, pattern: str, fn: Callable):
        self.routes.append(Route(method, pattern, fn))

    # ---- serving -----------------------------------------------------

    def make_handler(service):
        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet; logging via Metrics
                pass

            def handle(self):
                try:
                    super().handle()
                except (BrokenPipeError, ConnectionResetError):
                    # the client vanished mid-response (its timeout fired,
                    # or a fault dropped the link) — normal under failure
                    # testing, not a server error worth a traceback
                    pass

            def _trace_headers(self):
                # every response — errors included — names the trace it
                # belongs to, so a failed request is findable in /api/trace
                ctx = trace.current()
                if ctx is not None:
                    self.send_header(trace.TRACE_HEADER, ctx.trace_id)
                    self.send_header(trace.SPAN_HEADER, ctx.span_id)

            def _send_json(self, status: int, payload, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self._trace_headers()
                self.end_headers()
                self.wfile.write(body)

            def _send_raw(self, status: int, data: bytes, ctype: str):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self._trace_headers()
                self.end_headers()
                self.wfile.write(data)

            def _authorized(self) -> bool:
                if not service.auth_key:
                    return True
                hdr = self.headers.get("Authorization", "")
                return hdr == f"Bearer {service.auth_key}"

            def _dispatch(self, method: str):
                # bounded in-flight cap (DLI_HTTPD_MAX_INFLIGHT): the
                # saturation answer is an honest 503 + Retry-After sent
                # from the cheapest possible path — no span, no route
                # scan — so a flood is refused at near-zero cost
                if not self.server.try_begin_request():
                    self._drain_body()
                    return self._send_json(
                        503, {"status": "error",
                              "message": "server saturated "
                                         f"({service.max_inflight} "
                                         "requests in flight)"},
                        {"Retry-After": "1"})
                try:
                    self._dispatch_capped(method)
                finally:
                    self.server.end_request()

            def _dispatch_capped(self, method: str):
                # Server span for the whole request: adopts the caller's
                # trace context from X-DLI-Trace-Id/X-DLI-Parent-Span (or
                # roots a fresh trace), and stays current while the
                # response is written so even 4xx/5xx lines carry the
                # trace headers (_send_json._trace_headers).
                path, _, query = self.path.partition("?")
                tracer = trace.get_tracer()
                with tracer.span(f"http {method} {path}",
                                 parent=trace.extract(self.headers),
                                 attrs={"service": service.name,
                                        "method": method},
                                 keep=path not in QUIET_TRACE_PATHS) as sp:
                    self._dispatch_traced(method, path, query, sp)

            def _inject_fault(self, f) -> bool:
                """Apply one armed fault (utils/faults.py FaultSpec).
                Returns True when the request was consumed — no normal
                dispatch should follow."""
                import socket
                if f.mode == "latency":
                    clock.sleep(f.delay_s)
                    return False      # then handle the request normally
                if f.delay_s:
                    clock.sleep(f.delay_s)
                self.close_connection = True
                if f.mode == "corrupt":
                    body = b"#!<<injected corrupt body; not JSON>>"
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return True
                if f.mode == "error":
                    self._send_json(500, {"status": "error",
                                          "message": "injected fault"})
                    return True
                if f.mode == "disconnect":
                    # headers + a partial body, then a hard close: the
                    # client fails mid-read (IncompleteRead)
                    try:
                        self.send_response(200)
                        self.send_header("Content-Type", "application/json")
                        self.send_header("Content-Length", "65536")
                        self.end_headers()
                        self.wfile.write(b'{"status": "succ')
                        self.wfile.flush()
                    except OSError:
                        pass
                elif f.mode == "crash":
                    # kill the whole server: the listener closes, so
                    # every later connect is refused — a crashed worker
                    threading.Thread(target=service.shutdown,
                                     daemon=True).start()
                # reset / disconnect / crash: abort the connection with
                # zero (further) response bytes
                try:
                    self.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                return True

            def _drain_body(self):
                # keep-alive (HTTP/1.1): an unread request body would be
                # parsed as the NEXT request line on this connection —
                # discard it before any response sent without dispatching
                n = int(self.headers.get("Content-Length") or 0)
                while n > 0:
                    chunk = self.rfile.read(min(n, 1 << 16))
                    if not chunk:
                        break
                    n -= len(chunk)

            def _dispatch_traced(self, method: str, path: str, query: str,
                                 sp):
                def send(status, payload, headers=None):
                    sp.attrs["status"] = status
                    return self._send_json(status, payload, headers)

                if not self._authorized():
                    self._drain_body()
                    return send(401, {"status": "error",
                                      "message": "unauthorized"})
                # fault harness — after auth, so unauthenticated traffic
                # can neither trigger a crash fault nor consume a
                # times-bounded schedule; never intercepts its own admin
                # surface, or an armed "*" fault could not be cleared
                if not path.startswith("/api/faults"):
                    f = service.faults.intercept(path)
                    if f is not None and self._inject_fault(f):
                        sp.attrs["status"] = 0   # connection-level fault
                        return
                allowed = set()
                for r in service.routes:
                    m = r.regex.match(path)
                    if not m:
                        continue
                    if r.method != method:
                        # the path exists under another method: keep
                        # looking for an exact match, 405 if none
                        allowed.add(r.method)
                        continue
                    body = {}
                    if method in ("POST", "PUT"):
                        n = int(self.headers.get("Content-Length") or 0)
                        if n:
                            try:
                                body = json.loads(self.rfile.read(n) or b"{}")
                            except json.JSONDecodeError:
                                return send(400, {"status": "error",
                                                  "message": "invalid JSON body"})
                    if query and method == "GET" and isinstance(body, dict):
                        # GET-only: query params reach handlers through
                        # the body dict (GET /api/timeseries?metric=…).
                        # POST/PUT bodies stay JSON-typed — a raw query
                        # string like ?enabled=false merged into them
                        # would coerce wrong (bool("false") is True)
                        from urllib.parse import parse_qs
                        for k, vs in parse_qs(
                                query, keep_blank_values=True).items():
                            body.setdefault(k, vs[-1])
                    try:
                        result = r.fn(body, **m.groupdict(), _request=self) \
                            if _wants_request(r.fn) else r.fn(body, **m.groupdict())
                    except _Streaming:
                        sp.attrs["status"] = 200
                        return  # handler already wrote the response
                    except Exception as e:  # structured 500, like worker/app.py:133-137
                        return send(500, {"status": "error",
                                          "message": str(e)})
                    hdrs = None
                    if isinstance(result, tuple) and len(result) == 3 and \
                            isinstance(result[0], int) and \
                            isinstance(result[2], dict):
                        status, payload, hdrs = result
                    elif isinstance(result, tuple) and len(result) == 2 and \
                            isinstance(result[0], int):
                        status, payload = result
                    else:
                        status, payload = 200, result
                    if isinstance(payload, tuple):  # (bytes, content_type)
                        sp.attrs["status"] = status
                        return self._send_raw(status, payload[0], payload[1])
                    return send(status, payload, hdrs)
                self._drain_body()
                if allowed:
                    # registered path, wrong method: 405 + Allow, not the
                    # misleading 404 this used to fall through to
                    return send(405, {"status": "error",
                                      "message": f"method {method} not "
                                                 f"allowed for {path}"},
                                headers={"Allow": ", ".join(sorted(allowed))})
                send(404, {"status": "error", "message": "not found"})

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_DELETE(self):
                self._dispatch("DELETE")

        return Handler

    def serve(self, host: str, port: int, background: bool = False
              ) -> ThreadingHTTPServer:
        self._server = _TrackingHTTPServer((host, port), self.make_handler(),
                                           max_inflight=self.max_inflight)
        self._server.daemon_threads = True
        if background:
            t = threading.Thread(target=self._server.serve_forever, daemon=True)
            t.start()
        else:
            self._server.serve_forever()
        return self._server

    @property
    def port(self) -> int:
        return self._server.server_address[1] if self._server else 0

    def shutdown(self):
        """Stop serving, close the listener, AND sever every live
        client connection. Keep-alive clients (the master's pooled RPC
        sessions) otherwise keep talking to this 'dead' server through
        their established sockets — a real process death closes them
        all, so a simulated one (chaos crash fault, test teardown) must
        too. Idempotent — a crash fault may already have shut the
        server before teardown runs."""
        srv, self._server = self._server, None
        if srv:
            srv.shutdown()
            srv.server_close()
            if hasattr(srv, "close_client_connections"):
                srv.close_client_connections()


class _TrackingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that remembers live client sockets so
    shutdown can hard-close persistent (keep-alive) connections, not
    just the listener — and counts in-flight request dispatches so the
    handler can refuse work past ``max_inflight`` (503 + Retry-After)
    instead of letting thread-per-request ingress grow without bound."""

    def __init__(self, *a, max_inflight: int = 0, **kw):
        self._client_socks: set = set()
        self._client_socks_lock = locks.lock("httpd.client_socks")
        self._max_inflight = int(max_inflight)
        self._inflight_reqs = 0
        self._inflight_lock = locks.lock("httpd.inflight")
        super().__init__(*a, **kw)

    def try_begin_request(self) -> bool:
        """Reserve one in-flight dispatch slot; False when saturated
        (cap 0 = uncapped). The handler MUST pair a successful reserve
        with end_request()."""
        if self._max_inflight <= 0:
            return True
        with self._inflight_lock:
            if self._inflight_reqs >= self._max_inflight:
                return False
            self._inflight_reqs += 1
            return True

    def end_request(self):
        if self._max_inflight <= 0:
            return
        with self._inflight_lock:
            self._inflight_reqs -= 1

    def get_request(self):
        sock, addr = super().get_request()
        with self._client_socks_lock:
            self._client_socks.add(sock)
        return sock, addr

    def shutdown_request(self, request):
        with self._client_socks_lock:
            self._client_socks.discard(request)
        super().shutdown_request(request)

    def close_client_connections(self):
        import socket
        with self._client_socks_lock:
            socks = list(self._client_socks)
            self._client_socks.clear()
        for s in socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


class _Streaming(Exception):
    """Raised by handlers that wrote the response themselves (SSE)."""


def _wants_request(fn) -> bool:
    import inspect
    try:
        return "_request" in inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False


def jsonl_stream(request_handler, events):
    """Write a chunked JSON-lines response from an iterator of dict
    events — one JSON object per line, flushed as produced. Unlike
    ``sse_stream`` the connection stays keep-alive (chunked framing
    delimits the body), so a master demultiplexing per-sub-request
    results off ``POST /inference_batch`` returns the connection to its
    pool when the stream ends instead of paying a fresh TCP handshake
    per batch."""
    request_handler.send_response(200)
    request_handler.send_header("Content-Type", "application/jsonlines")
    request_handler.send_header("Transfer-Encoding", "chunked")
    request_handler._trace_headers()
    request_handler.end_headers()
    try:
        for ev in events:
            data = json.dumps(ev).encode() + b"\n"
            request_handler.wfile.write(
                f"{len(data):x}\r\n".encode() + data + b"\r\n")
            request_handler.wfile.flush()
        request_handler.wfile.write(b"0\r\n\r\n")
        request_handler.wfile.flush()
    except (BrokenPipeError, ConnectionResetError):
        # the caller vanished mid-stream (its timeout fired, or a fault
        # cut the link): the producer threads still run to completion so
        # their results land in the idempotency cache for the retry
        request_handler.close_connection = True
    raise _Streaming()


def binary_stream(request_handler, chunks,
                  content_type="application/octet-stream"):
    """Write a chunked binary response from an iterator of byte chunks —
    the KV-transfer twin of ``jsonl_stream`` (runtime/kvwire.py frames
    ride this out of ``POST /kv_fetch``). Chunked framing delimits the
    body, so the peer's pooled keep-alive session gets its connection
    back when the stream ends."""
    request_handler.send_response(200)
    request_handler.send_header("Content-Type", content_type)
    request_handler.send_header("Transfer-Encoding", "chunked")
    request_handler._trace_headers()
    request_handler.end_headers()
    try:
        for data in chunks:
            if not data:
                continue
            request_handler.wfile.write(
                f"{len(data):x}\r\n".encode() + data + b"\r\n")
            request_handler.wfile.flush()
        request_handler.wfile.write(b"0\r\n\r\n")
        request_handler.wfile.flush()
    except (BrokenPipeError, ConnectionResetError):
        # the fetching peer vanished mid-transfer (its timeout fired, or
        # a fault cut the link): it degrades to recompute on its side
        request_handler.close_connection = True
    raise _Streaming()


def sse_stream(request_handler, events):
    """Write an SSE response from an iterator of dict events."""
    request_handler.send_response(200)
    request_handler.send_header("Content-Type", "text/event-stream")
    request_handler.send_header("Cache-Control", "no-cache")
    request_handler.send_header("Connection", "close")  # no length: close delimits
    request_handler._trace_headers()
    request_handler.end_headers()
    try:
        for ev in events:
            data = f"data: {json.dumps(ev)}\n\n".encode()
            request_handler.wfile.write(data)
            request_handler.wfile.flush()
    except (BrokenPipeError, ConnectionResetError):
        pass
    raise _Streaming()
