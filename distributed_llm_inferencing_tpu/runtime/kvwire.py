"""Cross-node KV transfer wire: frame codec + worker-side fetch client.

PR 5 made the KV cache a *cluster-level* resource in name — content-
addressed host arenas plus a digest advertisement — but a block's bytes
still never left the node that prefilled them. This module is the wire
that makes the cluster's KV mobile (FlowKV, arxiv 2504.03775): a
prefill-role worker serves its arena blocks over ``POST /kv_fetch`` as a
stream of length-prefixed binary frames, and a decode-role worker pulls
the blocks it is missing into its own arena before admission
(runtime/batcher.py ``_restore_from_peer``), falling through to the
existing bitwise-identical arena restore.

Wire format (one chunked ``application/octet-stream`` response)::

    frame    := MAGIC(4) | hdr_len(u32 BE) | payload_len(u32 BE)
                | hdr(JSON, hdr_len bytes) | payload(payload_len bytes)
    hdr      := {"digest": str, "pages": [{"dtype": str, "shape": [...]},
                 ...]}                          # one block's pages
              | {"digest": str, "quant": "kvq8", "pages": [...specs...],
                 "meta": [{"kind": "raw"|"q8", ...}, ...]}
                                                # int8-quantized block
              | {"end": true, "served": int, "served_bytes": int,
                 "missing": [...], "truncated": int}
                                                # terminal frame, no payload
    payload  := concatenated C-order page bytes, in hdr order

The payload is the arena entry's exact bytes — the same bytes the radix
cache evicted on the source — so a restore from a fetched block stays
bitwise identical to a cold prefill. An int8 arena (DLI_KV_HOST_DTYPE)
ships its blocks as ``kvq8`` frames: the stored q/scale arrays as-is
(no requantize on send), with per-page meta the receiver validates
(ops/kvblock_quant.py ``block_from_wire``) before trusting a record.
Every structural surprise (bad magic, over-cap lengths, short read,
shape/dtype drift, inconsistent quant meta) raises :class:`WireError`;
the caller treats any failure as "recompute", never as a request
failure. The terminal frame carries ``served``/``served_bytes`` so a
size-capped partial (clean close after N blocks) is distinguishable
from a mid-stream disconnect and the recompute fallback can be sized
to what is actually missing.

:class:`KVFetchClient` is the pull side: per-peer pooled keep-alive
``requests.Session`` with ``(connect, read)`` timeout tuples, breaker-
style session teardown on connection faults (the PR 4 ``_purge_session``
treatment, worker-side), exact created-vs-reused connection accounting
(``dli_worker_peer_conns_created/reused_total``), and a ``rpc:/kv_fetch``
client-side fault point so the chaos harness can cut the transfer from
the decode node's side of the wire.
"""

from __future__ import annotations

import json
import logging
import struct
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from distributed_llm_inferencing_tpu.ops import kvblock_quant as kvq
from distributed_llm_inferencing_tpu.utils import clock, locks

log = logging.getLogger("dli.kvwire")

MAGIC = b"KVF1"
_HDR_STRUCT = struct.Struct(">II")
# Structural sanity caps — a corrupt length prefix must fail fast, not
# allocate gigabytes: one header is a small JSON dict, one payload is one
# KV block's pages (a few MB at most for any real config).
MAX_HDR_BYTES = 1 << 16
MAX_FRAME_PAYLOAD = 256 << 20
# Per-fetch digest-count cap (both sides enforce it): bounds one RPC's
# worst-case working set independently of the byte cap.
MAX_DIGESTS = 4096


class WireError(ValueError):
    """Structurally invalid / truncated / corrupt KV transfer stream."""


class KVFetchError(RuntimeError):
    """Transfer failed at the HTTP layer (non-200, connection fault)."""


def encode_frame(digest: str, pages: Sequence[np.ndarray]) -> bytes:
    """One block's pages as a self-describing binary frame."""
    pages = [np.ascontiguousarray(p) for p in pages]
    hdr = json.dumps({
        "digest": str(digest),
        "pages": [{"dtype": p.dtype.str, "shape": list(p.shape)}
                  for p in pages]}).encode()
    payload = b"".join(p.tobytes() for p in pages)
    return MAGIC + _HDR_STRUCT.pack(len(hdr), len(payload)) + hdr + payload


def encode_kvq_frame(digest: str, record: dict) -> bytes:
    """One int8-quantized block record as a ``kvq8`` frame: the stored
    q/scale arrays ship as-is (no requantize on send), the header's
    ``meta`` tells the receiver how to reassemble and validate them."""
    arrays = [np.ascontiguousarray(a) for a in kvq.wire_arrays(record)]
    hdr = json.dumps({
        "digest": str(digest), "quant": "kvq8",
        "pages": [{"dtype": a.dtype.str, "shape": list(a.shape)}
                  for a in arrays],
        "meta": kvq.wire_meta(record)}).encode()
    payload = b"".join(a.tobytes() for a in arrays)
    return MAGIC + _HDR_STRUCT.pack(len(hdr), len(payload)) + hdr + payload


def encode_stored(digest: str, obj) -> bytes:
    """Frame for whatever representation the arena stored — raw page
    tuple or quantized record — without converting either way."""
    if kvq.is_quantized_block(obj):
        return encode_kvq_frame(digest, obj)
    return encode_frame(digest, obj)


def stored_nbytes(obj) -> int:
    """Payload bytes ``encode_stored`` will ship for an arena entry."""
    if kvq.is_quantized_block(obj):
        return kvq.stored_nbytes(obj)
    return sum(int(p.nbytes) for p in obj)


def logical_nbytes(obj) -> int:
    """Full-precision bytes the entry restores to (the raw-wire cost a
    quantized transfer avoided — the compression accounting's numerator)."""
    if kvq.is_quantized_block(obj):
        return kvq.logical_nbytes(obj)
    return sum(int(p.nbytes) for p in obj)


def encode_end(served: int, missing: Sequence[str],
               truncated: int = 0, served_bytes: int = 0) -> bytes:
    """Terminal frame: how the stream ended, so a short-but-clean close
    is distinguishable from a mid-stream disconnect. The missing LIST is
    capped (a 4096-digest fetch against a cold arena would otherwise
    build a header past the decoder's MAX_HDR_BYTES and fail the whole
    stream); ``missing_count`` always carries the true total, and
    ``served``/``served_bytes`` carry what actually crossed the wire so
    a size-capped partial sizes its recompute fallback honestly."""
    missing = list(missing)
    hdr = json.dumps({"end": True, "served": int(served),
                      "served_bytes": int(served_bytes),
                      "missing": missing[:256],
                      "missing_count": len(missing),
                      "truncated": int(truncated)}).encode()
    return MAGIC + _HDR_STRUCT.pack(len(hdr), 0) + hdr


class _StreamReader:
    """Exact-count reads over an iterator of byte chunks."""

    def __init__(self, chunks: Iterable[bytes]):
        self._it = iter(chunks)
        self._buf = bytearray()

    def read(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = next(self._it)
            except StopIteration:
                raise WireError(
                    f"stream truncated: wanted {n} bytes, "
                    f"got {len(self._buf)}")
            if chunk:
                self._buf.extend(chunk)
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out


def iter_frames(chunks: Iterable[bytes],
                max_total_bytes: Optional[int] = None):
    """Incrementally decode a /kv_fetch stream: yields
    ``("block", digest, obj)`` per block frame — ``obj`` is the page
    list for raw frames or the quantized record for ``kvq8`` frames —
    then ``("end", hdr)`` for the terminal frame, exactly once. Raises
    :class:`WireError` on any structural problem, including a stream
    that ends without its terminal frame (a mid-stream disconnect must
    not pass for a clean short answer). The streaming restore path
    consumes this a frame at a time so scatter of block N can overlap
    receive of block N+1."""
    rd = _StreamReader(chunks)
    total = 0
    while True:
        head = rd.read(4 + _HDR_STRUCT.size)
        if head[:4] != MAGIC:
            raise WireError("bad frame magic (corrupt stream)")
        hdr_len, payload_len = _HDR_STRUCT.unpack(head[4:])
        if hdr_len > MAX_HDR_BYTES or payload_len > MAX_FRAME_PAYLOAD:
            raise WireError("frame length prefix out of bounds")
        try:
            hdr = json.loads(rd.read(hdr_len))
        except ValueError:
            raise WireError("unparseable frame header")
        if not isinstance(hdr, dict):
            raise WireError("frame header is not an object")
        if hdr.get("end"):
            yield ("end", hdr)
            return
        total += payload_len
        if max_total_bytes is not None and total > max_total_bytes:
            raise WireError(f"stream exceeds byte cap ({max_total_bytes})")
        payload = rd.read(payload_len)
        digest = hdr.get("digest")
        specs = hdr.get("pages")
        if not isinstance(digest, str) or not isinstance(specs, list):
            raise WireError("frame header missing digest/pages")
        pages, off = [], 0
        for spec in specs:
            try:
                dt = np.dtype(spec["dtype"])
                shape = tuple(int(s) for s in spec["shape"])
            except (KeyError, TypeError, ValueError):
                raise WireError("bad page spec in frame header")
            nbytes = dt.itemsize * int(np.prod(shape, dtype=np.int64))
            if off + nbytes > len(payload):
                raise WireError("frame payload shorter than page specs")
            # read-only view into the payload bytes, NOT a copy: the
            # fetch runs on a worker handler thread, and per-page copies
            # are GIL time stolen from the decode loop (the arena stores
            # the views; the payload bytes stay alive through them)
            pages.append(np.frombuffer(
                payload, dtype=dt, count=nbytes // dt.itemsize,
                offset=off).reshape(shape))
            off += nbytes
        if off != len(payload):
            raise WireError("frame payload longer than page specs")
        if hdr.get("quant") is not None:
            if hdr["quant"] != "kvq8":
                raise WireError(
                    f"unknown frame quant scheme {hdr['quant']!r}")
            meta = hdr.get("meta")
            if not isinstance(meta, list):
                raise WireError("kvq8 frame missing meta")
            # the meta crossed the wire: every shape/dtype relationship
            # it declares is validated before the record is trusted
            try:
                obj = kvq.block_from_wire(meta, pages)
            except ValueError as e:
                raise WireError(str(e))
            yield ("block", digest, obj)
        else:
            yield ("block", digest, pages)


def decode_frames(chunks: Iterable[bytes],
                  max_total_bytes: Optional[int] = None
                  ) -> Tuple[Dict[str, object], dict]:
    """Parse a whole /kv_fetch response stream into {digest: block}
    (pages list or quantized record) plus the terminal frame's header."""
    out: Dict[str, object] = {}
    for item in iter_frames(chunks, max_total_bytes=max_total_bytes):
        if item[0] == "end":
            return out, item[1]
        out[item[1]] = item[2]
    raise WireError("stream ended without terminal frame")


class FetchStream:
    """One in-flight streaming /kv_fetch: a receiver thread pumps the
    socket through the frame decoder into a bounded queue while the
    caller consumes blocks — so the caller's device scatter of block N
    overlaps the receive+decode of block N+1 instead of paying
    fetch-then-scatter serially.

    Iterate to get ``(digest, block)`` pairs (block = page list or
    quantized record); after clean exhaustion ``end`` holds the
    terminal-frame header. Transport/stream faults re-raise in the
    consumer as :class:`KVFetchError`/:class:`WireError` (after purging
    the peer's pooled session). ``receiving_done`` flips True the
    moment the socket side finishes — the consumer samples it per
    scatter to measure the overlap fraction it actually achieved.
    Abandoning the iterator early (consumer exception) closes the
    response and drains the queue so the receiver thread always exits
    and the client's concurrency slot is always released."""

    def __init__(self, client: "KVFetchClient", base_url: str, resp,
                 sess, allowed, depth: int):
        import queue
        import threading
        self._client = client
        self._base_url = base_url
        self._resp = resp
        self._sess = sess
        self._allowed = allowed
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
        self.end: Optional[dict] = None
        self.receiving_done = False
        self._finished = False
        self._thread = threading.Thread(
            target=self._pump, name="dli-kvwire-recv", daemon=True)
        self._thread.start()

    def _pump(self) -> None:
        import requests as http
        try:
            for item in iter_frames(
                    self._resp.iter_content(chunk_size=1 << 18),
                    max_total_bytes=self._client.max_bytes):
                if item[0] == "end":
                    self.receiving_done = True
                self._q.put(item)
        except WireError as e:
            self.receiving_done = True
            self._q.put(e)
        except (http.exceptions.RequestException, OSError) as e:
            self.receiving_done = True
            self._q.put(KVFetchError(f"kv_fetch transport failed: {e}"))
        finally:
            self.receiving_done = True
            try:
                self._resp.close()
            except Exception as e:
                log.debug("kv_fetch stream close failed: %r", e)

    def __iter__(self):
        try:
            while True:
                item = self._q.get()
                if isinstance(item, Exception):
                    self._client.purge(self._base_url)
                    raise item
                if item[0] == "end":
                    self.end = item[1]
                    self._client._count_conn_reuse(self._sess)
                    return
                _, digest, obj = item
                if digest in self._allowed:
                    yield digest, obj
        finally:
            self._finish()

    def _finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        import queue
        try:
            self._resp.close()
        except Exception as e:
            log.debug("kv_fetch stream close failed: %r", e)
        # drain until the receiver exits: it may be blocked on a full
        # queue, and the semaphore slot must not leak with it
        while self._thread.is_alive():
            try:
                self._q.get_nowait()
            except queue.Empty:
                self._thread.join(timeout=0.05)
        self._client._sem.release()


class KVFetchClient:
    """Decode-side puller: fetch arena blocks from a peer worker.

    One pooled keep-alive session per peer (the PR 4 treatment applied
    worker-side): ``(connect, read)`` timeout tuples so a black-holed
    peer fails in seconds, session teardown on connection-level faults
    so a restarted peer doesn't feed the next fetch a dead socket, and
    created-vs-reused socket accounting in the worker's registry.
    Thread-safe; shared by every batcher a worker hosts.
    """

    def __init__(self, auth_key: Optional[str] = None, faults=None,
                 metrics=None, connect_timeout: float = 5.0,
                 read_timeout: float = 30.0,
                 max_mb: Optional[float] = None, pool_size: int = 2):
        import os
        from distributed_llm_inferencing_tpu.utils.metrics import Metrics
        self.auth_key = auth_key
        self.faults = faults
        self.metrics = metrics or Metrics()
        self.timeout = (float(connect_timeout), float(read_timeout))
        if max_mb is None:
            try:
                max_mb = float(os.environ.get("DLI_KV_FETCH_MAX_MB", 256))
            except ValueError:
                max_mb = 256.0
        self.max_bytes = int(max_mb * 1024 * 1024)
        self._pool_size = max(1, int(pool_size))
        self._sessions: Dict[str, object] = {}
        self._lock = locks.lock("kvwire.peer_sessions")
        # Peer-fetch concurrency bound: a mass migration off one dying
        # node turns every destination's submit-time prefetch loose at
        # once, and an unbounded fan-in would thundering-herd the one
        # source worker's HTTP threads (and this worker's own handler
        # threads). Fetches past the bound queue on the semaphore and
        # count, so the pile-up is visible before it is a timeout.
        import threading
        try:
            conc = int(os.environ.get("DLI_KV_FETCH_CONCURRENCY", 4))
        except ValueError:
            conc = 4
        self._sem = threading.BoundedSemaphore(max(1, conc))
        # Streaming-restore handoff depth (blocks) between the socket-
        # receiver thread and the scatter consumer: deep enough to ride
        # out scatter jitter, shallow enough that a slow consumer
        # backpressures the socket instead of buffering the whole
        # transfer in host RAM twice.
        try:
            qd = int(os.environ.get("DLI_KV_WIRE_QUEUE", 4))
        except ValueError:
            qd = 4
        self.queue_depth = max(1, qd)
        # pre-register (PR 5 rule): a scrape must be able to tell "no
        # transfers yet" from "metric not exported"
        self.metrics.inc("worker_peer_conns_created", 0)
        self.metrics.inc("worker_peer_conns_reused", 0)
        self.metrics.inc("kv_fetch_queued", 0)

    def _session(self, base_url: str):
        import requests as http
        with self._lock:
            s = self._sessions.get(base_url)
            if s is None:
                s = http.Session()
                adapter = http.adapters.HTTPAdapter(
                    pool_connections=1, pool_maxsize=self._pool_size)
                s.mount("http://", adapter)
                s.mount("https://", adapter)
                s._dli_conns_seen = 0
                self._sessions[base_url] = s
            return s

    def purge(self, base_url: str) -> None:
        """Drop the peer's pooled sockets after a connection-level fault
        (the next fetch dials fresh instead of failing through a dead
        keep-alive socket)."""
        with self._lock:
            s = self._sessions.pop(base_url, None)
        if s is not None:
            try:
                s.close()
            except Exception as e:
                # closing an already-dead socket — harmless, but visible
                log.debug("purged peer session close failed: %r", e)

    def close(self) -> None:
        with self._lock:
            sessions, self._sessions = list(self._sessions.values()), {}
        for s in sessions:
            try:
                s.close()
            except Exception as e:
                log.debug("peer session close failed at teardown: %r", e)

    def _count_conn_reuse(self, sess) -> None:
        """Same urllib3 socket-count delta the master's RPC pool uses:
        ``num_connections`` grows only when a real socket was dialed, so
        no delta means this call rode a pooled connection."""
        try:
            pools = sess.get_adapter("http://").poolmanager.pools
            created = sum(p.num_connections
                          for p in list(pools._container.values()))
        except Exception:
            return
        with self._lock:
            delta = created - sess._dli_conns_seen
            if delta > 0:
                sess._dli_conns_seen = created
        if delta > 0:
            self.metrics.inc("worker_peer_conns_created", delta)
        else:
            self.metrics.inc("worker_peer_conns_reused")

    def _rpc_fault(self, path: str) -> None:
        """Client-side fault point ``rpc:/kv_fetch`` (utils/faults.py):
        cut the transfer from the decode node's side without touching
        the peer process."""
        if self.faults is None:
            return
        f = self.faults.intercept(f"rpc:{path}")
        if f is None:
            return
        import requests as http
        if f.mode == "latency":
            clock.sleep(f.delay_s)
            return
        if f.delay_s:
            clock.sleep(f.delay_s)
        if f.mode == "timeout":
            raise http.exceptions.ReadTimeout("injected kv_fetch timeout")
        raise http.exceptions.ConnectionError("injected kv_fetch fault")

    def fetch(self, base_url: str, model: str, digests: Sequence[str]
              ) -> Dict[str, List[np.ndarray]]:
        """Pull ``digests``' blocks from the peer's arena. Returns only
        the blocks the peer actually served — absent digests are the
        caller's recompute problem, not an error. Raises
        :class:`KVFetchError` / :class:`WireError` on transport or
        stream corruption (the caller degrades to recompute)."""
        import requests as http
        base_url = base_url.rstrip("/")
        digests = [str(d) for d in digests][:MAX_DIGESTS]
        if not self._sem.acquire(blocking=False):
            self.metrics.inc("kv_fetch_queued")
            self._sem.acquire()
        try:
            self._rpc_fault("/kv_fetch")
            sess = self._session(base_url)
            headers = ({"Authorization": f"Bearer {self.auth_key}"}
                       if self.auth_key else {})
            try:
                r = sess.post(f"{base_url}/kv_fetch",
                              json={"model_name": model,
                                    "digests": digests},
                              headers=headers, timeout=self.timeout,
                              stream=True)
            except Exception:
                self.purge(base_url)
                raise
            try:
                if r.status_code != 200:
                    r.close()
                    raise KVFetchError(
                        f"kv_fetch refused ({r.status_code}): "
                        f"{r.text[:200]}")
                # no Content-Type gate: an injected corrupt fault (or a
                # proxy error page) can answer 200 with a JSON/garbage
                # body — parse it as a wire stream and let the magic
                # check reject it
                try:
                    blocks, _end = decode_frames(
                        r.iter_content(chunk_size=1 << 18),
                        max_total_bytes=self.max_bytes)
                finally:
                    r.close()
            except (http.exceptions.RequestException, OSError) as e:
                # mid-stream disconnect/reset: the pooled socket is dead
                self.purge(base_url)
                raise KVFetchError(f"kv_fetch transport failed: {e}")
        finally:
            self._sem.release()
        self._count_conn_reuse(sess)
        allowed = set(digests)
        return {d: pages for d, pages in blocks.items() if d in allowed}

    def fetch_stream(self, base_url: str, model: str,
                     digests: Sequence[str]) -> FetchStream:
        """Streaming twin of :meth:`fetch`: returns a
        :class:`FetchStream` whose iterator hands blocks over as their
        frames decode, receive running ahead on a bounded queue.
        Connect-time refusals raise here exactly like ``fetch``;
        mid-stream faults surface from the iterator. The concurrency
        slot is held until the stream finishes (clean, faulted, or
        abandoned) — a streaming fetch is still one in-flight fetch."""
        import requests as http
        base_url = base_url.rstrip("/")
        digests = [str(d) for d in digests][:MAX_DIGESTS]
        if not self._sem.acquire(blocking=False):
            self.metrics.inc("kv_fetch_queued")
            self._sem.acquire()
        try:
            self._rpc_fault("/kv_fetch")
            sess = self._session(base_url)
            headers = ({"Authorization": f"Bearer {self.auth_key}"}
                       if self.auth_key else {})
            try:
                r = sess.post(f"{base_url}/kv_fetch",
                              json={"model_name": model,
                                    "digests": digests},
                              headers=headers, timeout=self.timeout,
                              stream=True)
            except Exception:
                self.purge(base_url)
                raise
            if r.status_code != 200:
                body = r.text[:200]
                r.close()
                raise KVFetchError(
                    f"kv_fetch refused ({r.status_code}): {body}")
        except BaseException:
            self._sem.release()
            raise
        return FetchStream(self, base_url, r, sess, set(digests),
                           self.queue_depth)
