"""Master control plane: node registry, request queue, scheduler, dashboard.

One-for-one capability replacement of the reference's Django master
(master/dashboard/views.py) with the same JSON API paths
(master/dashboard/urls.py:11-16) and three dashboard pages
(urls.py:6-8), re-architected:

- thread-pool dispatcher + persistent queue instead of an unbounded
  thread-per-request (reference views.py:233-236)
- push-based health monitor with N-strike deactivation and automatic
  reactivation, instead of UI-poll-driven one-strike marking
  (reference views.py:91-105, SURVEY.md §3.4)
- least-loaded scheduling with failover retry, instead of
  ``active_nodes.first()`` and terminal failures
  (reference views.py:389-391, 364-378)
- placement plans (parallel/plan.py) instead of ModelShard file pointers;
  the master actually calls the worker's /load_shard, which the reference
  never did (SURVEY.md §3.2)
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Dict, Optional, Set

import requests as http

from distributed_llm_inferencing_tpu.runtime import dashboard_html, httpd
from distributed_llm_inferencing_tpu.runtime.state import Store
from distributed_llm_inferencing_tpu.utils import trace
from distributed_llm_inferencing_tpu.utils.logging import setup_logging
from distributed_llm_inferencing_tpu.utils.metrics import (
    Metrics, hist_quantile, parse_prometheus)

log = setup_logging("master")

# Reference per-call timeouts (views.py:91,183,400,352-354)
HEALTH_TIMEOUT = 5
UNLOAD_TIMEOUT = 10
LOAD_TIMEOUT = 300
INFER_TIMEOUT = 120
# The worker's own generation budget stays strictly less than the
# master's HTTP timeout, so the worker 408s (and frees its batcher slot)
# BEFORE the master gives up — the reference had the opposite relation
# (master 120s vs worker holding gunicorn 300s, views.py:352 vs
# worker/Dockerfile:47) and a timed-out generation kept running for
# nobody. Computed per-Master from infer_timeout (worker_infer_budget).

MAX_ATTEMPTS = 3          # reference: 1 attempt, terminal (views.py:364-378)
FAILURE_STRIKES = 3       # breaker trip threshold (reference: one strike
                          # and terminal deactivation, views.py:99-105)
# Failover retry backoff: base * 2^attempt, with up to +100% jitter so a
# burst of requeues from one dead node doesn't re-dispatch in lockstep.
RETRY_BACKOFF_BASE = float(os.environ.get("DLI_RETRY_BACKOFF_BASE", 0.5))
RETRY_BACKOFF_MAX = float(os.environ.get("DLI_RETRY_BACKOFF_MAX", 30.0))


class _NodeUnavailable(Exception):
    """Worker is up but not taking work (draining, degraded slice, own
    budget expired): failover to another node WITHOUT a breaker strike.
    ``in_flight`` means the node still RUNS this request's generation —
    the retry must return to it (join/replay), not fail over."""

    def __init__(self, message: str, in_flight: bool = False):
        super().__init__(message)
        self.in_flight = in_flight


class Master:
    def __init__(self, db_path: str = ":memory:", *,
                 dispatcher_threads: int = 4,
                 health_interval: float = 10.0,
                 auth_key: Optional[str] = None,
                 infer_timeout: float = INFER_TIMEOUT,
                 retry_backoff_base: float = RETRY_BACKOFF_BASE):
        self.store = Store(db_path)
        self.infer_timeout = infer_timeout
        self.worker_infer_budget = max(1.0, infer_timeout - 5)
        self.retry_backoff_base = retry_backoff_base
        n = self.store.recover_stale_processing(max_attempts=MAX_ATTEMPTS)
        if n:
            log.info("recovered %d request(s) stranded by a previous run", n)
        self.metrics = Metrics()
        trace.set_service("master")
        # Dispatch tags are the worker-side idempotency key, so they must
        # be unique across master *instances*: request ids restart at 1
        # for a fresh DB, and a bare id could replay another request's
        # cached generation out of a long-lived worker.
        import uuid
        self._run_nonce = uuid.uuid4().hex[:8]
        self.health_interval = health_interval
        self._worker_auth = auth_key or os.environ.get("DLI_AUTH_KEY")
        self._inflight: Dict[int, int] = {}   # node_id -> in-flight count
        self._inflight_lock = threading.Lock()
        self._processing: Dict[int, dict] = {}  # req_id -> node (for cancel)
        # req_id -> submitter's SpanCtx: dispatch runs on another thread,
        # so the request's trace link rides this map, not a contextvar
        self._trace_ctx: Dict[int, object] = {}
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._threads = []
        self._dispatcher_threads = dispatcher_threads

        # Optional auth for the master's own API (the reference master had
        # none at all). When set, every endpoint — pages included — needs
        # the bearer token; without it the master should only bind loopback
        # or a trusted network, since it relays to workers with its own key.
        api_auth = os.environ.get("DLI_MASTER_AUTH_KEY")
        s = self.service = httpd.JsonHTTPService("master", api_auth)
        # pages (reference urls.py:6-8)
        s.add("GET", "/", lambda b: (dashboard_html.DASHBOARD.encode(), "text/html"))
        s.add("GET", "/nodes", lambda b: (dashboard_html.NODES.encode(), "text/html"))
        s.add("GET", "/inference", lambda b: (dashboard_html.INFERENCE.encode(), "text/html"))
        # JSON API (reference urls.py:11-16)
        s.add("GET", "/api/nodes/status", self.api_node_status)
        s.add("POST", "/api/nodes/add", self.api_add_node)
        s.add("POST", "/api/nodes/remove/<node_id>", self.api_remove_node)
        s.add("POST", "/api/inference/submit", self.api_submit)
        s.add("GET", "/api/inference/status/<req_id>", self.api_status)
        s.add("GET", "/api/inference/recent", self.api_recent)
        s.add("POST", "/api/inference/cancel/<req_id>", self.api_cancel)
        # beyond reference
        s.add("GET", "/api/plans", self.api_list_plans)
        s.add("POST", "/api/plans/create", self.api_create_plan)
        s.add("POST", "/api/plans/deploy/<plan_id>", self.api_deploy_plan)
        s.add("POST", "/api/models/load", self.api_load_model)
        s.add("GET", "/api/metrics", lambda b: self.metrics.snapshot())
        s.add("GET", "/metrics", lambda b: (
            self.metrics.prometheus().encode(), "text/plain; version=0.0.4"))
        s.add("GET", "/api/trace", self.api_trace)
        s.add("GET", "/api/cluster_metrics", self.api_cluster_metrics)
        s.add("GET", "/health", lambda b: {"status": "online",
                                           "counts": self.store.counts()})

    # ---- worker RPC --------------------------------------------------

    def _tag(self, req_id) -> str:
        """Worker-side idempotency/cancel key for a request."""
        return f"{self._run_nonce}:{req_id}"

    def _headers(self):
        h = ({"Authorization": f"Bearer {self._worker_auth}"}
             if self._worker_auth else {})
        # propagate the active trace onto every worker call, so the
        # worker's server span joins this request's timeline
        return trace.inject(h)

    def _rpc_fault(self, path):
        """Client-side fault point ``rpc:<path>`` (utils/faults.py): lets
        the chaos harness simulate a network partition from the master's
        side — the worker process never sees the request."""
        f = self.service.faults.intercept(f"rpc:{path}")
        if f is None:
            return
        if f.mode == "latency":
            time.sleep(f.delay_s)
            return
        if f.delay_s:
            time.sleep(f.delay_s)
        if f.mode == "timeout":
            raise http.exceptions.ReadTimeout("injected rpc timeout")
        raise http.exceptions.ConnectionError("injected rpc fault")

    def _worker_get(self, node, path, timeout):
        self._rpc_fault(path)
        return http.get(self.store.node_url(node) + path,
                        headers=self._headers(), timeout=timeout)

    def _worker_post(self, node, path, body, timeout):
        self._rpc_fault(path)
        return http.post(self.store.node_url(node) + path, json=body,
                         headers=self._headers(), timeout=timeout)

    # ---- node API ----------------------------------------------------

    def api_add_node(self, body):
        """≙ add_node (reference views.py:111-165): reachability-gate then
        register."""
        name = body.get("name")
        host = body.get("host")
        port = int(body.get("port", 8100))
        if not name or not host:
            return 400, {"status": "error", "message": "name and host required"}
        node = {"host": host, "port": port}
        try:
            r = http.get(f"http://{host}:{port}/health",
                         headers=self._headers(), timeout=HEALTH_TIMEOUT)
            r.raise_for_status()
            info = r.json()
        except Exception as e:
            return 502, {"status": "error",
                         "message": f"worker unreachable: {e}"}
        existing = self.store.find_node(host, port)
        if existing:
            self.store.update_node(existing["id"], is_active=1,
                                   consecutive_failures=0,
                                   breaker_state="closed", draining=0,
                                   last_heartbeat=time.time(), info=info)
            return {"status": "success", "node_id": existing["id"],
                    "message": "node re-activated"}
        import sqlite3
        try:
            node_id = self.store.add_node(name, host, port, is_active=True)
        except sqlite3.IntegrityError:
            return 400, {"status": "error",
                         "message": f"node name {name!r} already registered "
                                    "at a different address"}
        self.store.update_node(node_id, last_heartbeat=time.time(), info=info)
        log.info("node %s added: %s:%d", name, host, port)
        return {"status": "success", "node_id": node_id}

    def api_remove_node(self, body, node_id):
        """≙ remove_node (views.py:167-221): best-effort unload then delete."""
        node = self.store.get_node(int(node_id))
        if not node:
            return 404, {"status": "error", "message": "no such node"}
        try:
            info = json.loads(node.get("info") or "{}")
            for m in info.get("loaded_models", []):
                self._worker_post(node, "/unload_model",
                                  {"model_name": m["name"]}, UNLOAD_TIMEOUT)
        except Exception as e:
            log.warning("unload during remove failed: %s", e)
        self.store.remove_node(int(node_id))
        return {"status": "success"}

    def api_node_status(self, body):
        """≙ node_status (views.py:74-109) — but served from the health
        monitor's state rather than fanning out HTTP per UI poll."""
        nodes = []
        for n in self.store.list_nodes():
            info = json.loads(n.get("info") or "{}")
            nodes.append({
                "id": n["id"], "name": n["name"], "host": n["host"],
                "port": n["port"], "is_active": bool(n["is_active"]),
                "breaker": n.get("breaker_state") or "closed",
                "strikes": n["consecutive_failures"],
                "draining": bool(n.get("draining")),
                "last_heartbeat": n["last_heartbeat"],
                "resources": info.get("resources"),
                "loaded_models": info.get("loaded_models", []),
                "inflight": self._inflight.get(n["id"], 0),
            })
        return {"status": "success", "nodes": nodes}

    # ---- model/plan API ----------------------------------------------

    def api_create_plan(self, body):
        """The shard_model CLI as an API (reference shard_model.py:16-115):
        produce a placement plan instead of weight files."""
        from distributed_llm_inferencing_tpu.parallel.plan import make_plan
        try:
            plan = make_plan(body["model_name"], body.get("mesh", {"tp": 1}),
                             max_seq=int(body.get("max_seq", 2048)),
                             batch=int(body.get("batch", 1)))
        except (KeyError, ValueError) as e:
            return 400, {"status": "error", "message": str(e)}
        plan_id = self.store.add_plan(body["model_name"], plan)
        return {"status": "success", "plan_id": plan_id, "plan": plan}

    def api_list_plans(self, body):
        return {"status": "success", "plans": self.store.list_plans()}

    def api_deploy_plan(self, body, plan_id):
        """Push a plan to a worker via /load_shard — the call the reference
        defined but never made (SURVEY.md §3.2)."""
        plans = [p for p in self.store.list_plans() if p["id"] == int(plan_id)]
        if not plans:
            return 404, {"status": "error", "message": "no such plan"}
        plan = plans[0]
        node = self._pick_node(model=None)
        if node is None:
            return 503, {"status": "error", "message": "no active nodes"}
        payload = {"plan": plan["plan"]}
        payload.update({k: body[k] for k in
                        ("checkpoint_path", "tokenizer_path",
                         "allow_random_init", "dtype") if k in body})
        r = self._worker_post(node, "/load_shard", payload, LOAD_TIMEOUT)
        if r.status_code == 200:
            self.store.mark_plan_loaded(plan["id"], node["id"])
        return _relay_json(r)

    def api_load_model(self, body):
        """Explicit model pre-load on a chosen or scheduled node."""
        node = (self.store.get_node(int(body["node_id"]))
                if body.get("node_id") else self._pick_node(model=None))
        if node is None:
            return 503, {"status": "error", "message": "no active nodes"}
        r = self._worker_post(node, "/load_model", body, LOAD_TIMEOUT)
        self._refresh_node(node)
        return _relay_json(r)

    # ---- inference API -----------------------------------------------

    def api_submit(self, body):
        """≙ submit_inference (views.py:223-258): enqueue + wake dispatcher."""
        model = body.get("model_name")
        prompt = body.get("prompt")
        if not model or prompt is None:
            return 400, {"status": "error",
                         "message": "model_name and prompt required"}
        # max_length keeps the reference's prompt+new semantics
        # (views.py:351); it is forwarded verbatim so the worker computes
        # new-token count against the tokenized prompt.
        if "max_new_tokens" in body:
            max_new, max_length = int(body["max_new_tokens"]), None
        elif "max_length" in body:
            max_new, max_length = None, int(body["max_length"])
        else:
            max_new, max_length = 100, None
        req_id = self.store.submit_request(
            model, prompt, max_new, body.get("sampling"),
            max_length=max_length)
        # remember the submit span so the dispatcher thread can parent the
        # execution spans to this HTTP request's trace
        ctx = trace.current()
        if ctx is not None:
            self._trace_ctx[req_id] = ctx
        self.metrics.inc("requests_submitted")
        self._wake.set()
        return {"status": "success", "request_id": req_id}

    def api_status(self, body, req_id):
        """≙ inference_status (views.py:260-280)."""
        r = self.store.get_request(int(req_id))
        if not r:
            return 404, {"status": "error", "message": "no such request"}
        return {"status": "success", "request": r}

    def api_recent(self, body):
        """≙ recent_inferences (views.py:282-303)."""
        return {"status": "success", "counts": self.store.counts(),
                "requests": self.store.recent_requests(20)}

    def api_cancel(self, body, req_id):
        """Cancel a pending or in-flight request — no reference counterpart
        (its failures were terminal and its generations uncancellable,
        SURVEY.md §5.3). In-flight: relay to the worker's /cancel (frees
        the batcher slot); pending: fail it before any node picks it up."""
        req_id = int(req_id)
        r = self.store.get_request(req_id)
        if not r:
            return 404, {"status": "error", "message": "no such request"}
        if r["status"] in ("completed", "failed"):
            return 409, {"status": "error",
                         "message": f"request already {r['status']}"}
        node = self._processing.get(req_id)
        if node is not None:
            try:
                w = self._worker_post(node, "/cancel",
                                      {"request_tag": self._tag(req_id)}, 10)
                if w.status_code == 200:
                    return {"status": "success",
                            "message": "cancel relayed to worker"}
                # engine-mode generations are not cancellable mid-program
                # (the worker registers tags for batched requests only)
                return 409, {"status": "error",
                             "message": f"worker cannot cancel: "
                                        f"{w.text[:200]}"}
            except Exception as e:
                return 502, {"status": "error",
                             "message": f"cancel relay failed: {e}"}
        self.store.mark_failed(req_id, "cancelled by user")
        self.metrics.inc("requests_cancelled")
        self._trace_done(req_id)
        return {"status": "success", "message": "request cancelled"}

    # ---- observability -----------------------------------------------

    def _scrape_workers(self, path: str, nodes=None):
        """Fetch ``path`` from every ACTIVE node concurrently (a dead node
        otherwise serializes its full HEALTH_TIMEOUT into the handler and
        the 10s dashboard poll piles up behind it). Returns
        [(node, response-or-None, error-or-None)]. Pass ``nodes`` to
        probe an explicit set (the health loop probes inactive nodes too
        — that is how a tripped breaker finds its way back)."""
        from concurrent.futures import ThreadPoolExecutor
        if nodes is None:
            nodes = self.store.list_nodes(active_only=True)
        if not nodes:
            return []

        def fetch(n):
            try:
                r = self._worker_get(n, path, HEALTH_TIMEOUT)
                r.raise_for_status()
                return n, r, None
            except Exception as e:
                return n, None, str(e)[:200]

        with ThreadPoolExecutor(max_workers=min(8, len(nodes))) as ex:
            return list(ex.map(fetch, nodes))

    def api_trace(self, body):
        """Cluster-wide Chrome trace-event export: the master's own span
        ring buffer merged with a best-effort scrape of every active
        worker's /api/trace, deduplicated — one request submitted here
        loads as one connected timeline in Perfetto."""
        extra = []
        for n, r, err in self._scrape_workers("/api/trace"):
            if err is not None:
                log.debug("trace scrape of node %s failed: %s", n["id"], err)
                continue
            try:
                extra.extend(r.json().get("traceEvents", []))
            except ValueError:
                pass
        return trace.get_tracer().chrome_trace(extra_events=extra)

    def api_cluster_metrics(self, body):
        """One cluster snapshot: scrape every active worker's /metrics
        exposition (concurrently), parse it
        (utils/metrics.parse_prometheus), derive histogram p50/p95 from
        the cumulative ``le=`` buckets, and sum counters across nodes —
        the aggregation the dashboard's metrics table renders. Inactive
        nodes are listed unscraped; unreachable ones report their scrape
        error instead of silently vanishing from the snapshot."""
        nodes, totals = [], {}
        scraped = {}
        for n, r, err in self._scrape_workers("/metrics"):
            scraped[n["id"]] = (r, err)
        for n in self.store.list_nodes():
            entry = {"id": n["id"], "name": n["name"], "host": n["host"],
                     "port": n["port"], "is_active": bool(n["is_active"]),
                     "scraped": False}
            r, err = scraped.get(n["id"], (None, "inactive"))
            if r is not None:
                try:
                    entry.update(scraped=True,
                                 **_group_samples(parse_prometheus(r.text)))
                    for k, v in entry["counters"].items():
                        totals[k] = totals.get(k, 0.0) + v
                except ValueError as e:
                    entry["error"] = str(e)[:200]
            else:
                entry["error"] = err
            nodes.append(entry)
        return {"status": "success", "nodes": nodes,
                "cluster": {"counters": totals,
                            "workers_scraped": sum(
                                1 for x in nodes if x["scraped"])},
                "master": self.metrics.snapshot()}

    # ---- scheduling --------------------------------------------------

    def _node_models(self, node) -> set:
        info = json.loads(node.get("info") or "{}")
        return {m["name"] for m in info.get("loaded_models", [])}

    def _pick_node(self, model: Optional[str],
                   exclude: Optional[Set[int]] = None,
                   reserve: bool = False,
                   prefer: Optional[int] = None):
        """Least-loaded schedulable node, preferring ones with the model
        already loaded (reference: always .first(), views.py:389-391).

        Schedulable = breaker not open AND not draining. A half-open
        node admits at most ONE in-flight request — the probe whose
        outcome closes or re-opens the breaker. Nodes in ``exclude``
        (ones this request already failed on) are used only when no
        other node qualifies: better the suspect node than a spurious
        terminal failure on a single-node cluster.

        ``reserve=True`` increments the node's in-flight count inside the
        same lock as the selection (the caller MUST decrement when done)
        — without it two dispatcher threads could both pass the one-probe
        check on a half-open node and send two concurrent probes.

        ``prefer`` pins the choice to that node when it is schedulable
        and not excluded: a timeout retry goes back to the node that
        still holds the in-flight generation (idempotency join/replay)
        instead of re-generating on an idle-looking peer.
        """
        exclude = exclude or set()
        nodes = [n for n in self.store.list_nodes(active_only=True)
                 if not n.get("draining")]
        with self._inflight_lock:
            def load_key(n):
                return self._inflight.get(n["id"], 0)

            def probe_ok(n):
                return ((n.get("breaker_state") or "closed") != "half_open"
                        or self._inflight.get(n["id"], 0) == 0)

            usable = [n for n in nodes if probe_ok(n)]
            for pool in ([n for n in usable if n["id"] not in exclude],
                         usable):
                if not pool:
                    continue
                pinned = [n for n in pool if n["id"] == prefer]
                have = pinned or [n for n in pool
                                  if model and model in self._node_models(n)]
                chosen = min(have or pool, key=load_key)
                if reserve:
                    self._inflight[chosen["id"]] = \
                        self._inflight.get(chosen["id"], 0) + 1
                return chosen
        return None

    def _refresh_node(self, node):
        try:
            r = self._worker_get(node, "/health", HEALTH_TIMEOUT)
            r.raise_for_status()
            self.store.update_node(
                node["id"], info=r.json(), is_active=1,
                consecutive_failures=0, last_heartbeat=time.time())
        except Exception:
            pass

    def _execute(self, req) -> bool:
        """Run one request on a chosen node. True on success."""
        tracer = trace.get_tracer()
        # adopt the submit-time trace (kept across failover retries; freed
        # when the request reaches a terminal state)
        ctx = self._trace_ctx.get(req["id"])
        with tracer.span("master.execute", parent=ctx,
                         attrs={"req_id": req["id"],
                                "model": req["model_name"],
                                "attempt": req["attempts"]}):
            if req["attempts"] == 0:
                # make the dispatcher-queue wait visible in the timeline —
                # first attempt only (on a failover retry, created_at->now
                # covers the failed execution, not queueing)
                tracer.record("master.queued", req["created_at"],
                              time.time(), parent=trace.current())
            return self._execute_on_node(req)

    def _trace_done(self, req_id: int):
        self._trace_ctx.pop(req_id, None)

    def _backoff(self, attempts: int) -> float:
        """Exponential backoff with full jitter for the next attempt;
        the cap bounds the jittered value, so DLI_RETRY_BACKOFF_MAX is a
        real ceiling."""
        d = self.retry_backoff_base * (2 ** (attempts + 1))
        return min(RETRY_BACKOFF_MAX, d * (1.0 + random.random()))

    def _execute_on_node(self, req) -> bool:
        excluded = set(req.get("excluded_nodes") or [])
        # a retry whose previous node is NOT excluded got there via a
        # pure timeout: that node still holds the in-flight generation,
        # so pin the retry to it (join/replay beats re-generating)
        prefer = (req.get("node_id")
                  if req.get("node_id") and req["node_id"] not in excluded
                  else None)
        node = self._pick_node(req["model_name"], exclude=excluded,
                               reserve=True, prefer=prefer)
        if node is None:
            # nothing schedulable right now (all breakers open / nodes
            # draining): park instead of failing — at least a health
            # interval and a half, so the loop's half-open recovery edge
            # gets a chance to run before the attempt budget burns down
            if req["attempts"] + 1 < MAX_ATTEMPTS:
                self.store.requeue(req["id"],
                                   delay_s=max(self._backoff(req["attempts"]),
                                               self.health_interval * 1.5))
                self.metrics.inc("requests_requeued")
            else:
                self.store.mark_failed(req["id"], "no active worker nodes")
                self._trace_done(req["id"])
            return False
        nid = node["id"]   # in-flight slot already reserved by _pick_node
        try:
            if req["model_name"] not in self._node_models(node):
                # lazy load, like reference views.py:397-401 — random init is
                # NOT silently allowed; operator must preload or register a
                # checkpointed model unless the request says otherwise.
                body = {"model_name": req["model_name"]}
                if req["sampling"].get("allow_random_init"):
                    body["allow_random_init"] = True
                if req["sampling"].get("checkpoint_path"):
                    body["checkpoint_path"] = req["sampling"]["checkpoint_path"]
                r = self._worker_post(node, "/load_model", body, LOAD_TIMEOUT)
                if r.status_code == 503:
                    raise _NodeUnavailable(f"load refused: {r.text[:200]}")
                if 400 <= r.status_code < 500 and r.status_code != 408:
                    # user error (unknown model, bad request): terminal, and
                    # NOT the node's fault — no strike, no retry
                    self.store.mark_failed(req["id"],
                                           f"load rejected: {r.text[:200]}")
                    self.metrics.inc("requests_rejected")
                    self._trace_done(req["id"])
                    return False
                if r.status_code != 200:
                    raise RuntimeError(f"load_model failed: {r.text[:200]}")
                self._refresh_node(node)
            infer_body = {
                "model_name": req["model_name"],
                "prompt": req["prompt"],
                "sampling": req["sampling"],
                # worker-side generation budget < our HTTP timeout, and a
                # tag that makes dispatch idempotent: the worker caches
                # the completed result under it, so a timeout retry
                # replays the generation instead of re-running it
                "timeout": self.worker_infer_budget,
                "request_tag": self._tag(req["id"]),
            }
            if req.get("max_length") is not None:
                infer_body["max_length"] = req["max_length"]
            else:
                infer_body["max_new_tokens"] = req["max_new_tokens"]
            self._processing[req["id"]] = node
            try:
                # the dispatch span is the parent the worker's HTTP server
                # span links to (trace headers injected by _headers)
                with trace.get_tracer().span(
                        "master.dispatch",
                        attrs={"node_id": nid, "host": node["host"],
                               "port": node["port"]}):
                    r = self._worker_post(node, "/inference", infer_body,
                                          self.infer_timeout)
            finally:
                self._processing.pop(req["id"], None)
            if r.status_code in (503, 408):
                # 503: draining / degraded slice — up but not taking
                # work. 408: the worker's own budget expired (busy, not
                # broken). Neither is the node's *fault*: failover
                # without a strike. An in_flight-flagged 408 (idempotency
                # join timed out) additionally pins the retry here.
                try:
                    still = bool(r.json().get("in_flight"))
                except ValueError:
                    still = False
                raise _NodeUnavailable(
                    f"worker unavailable ({r.status_code}): {r.text[:200]}",
                    in_flight=still)
            if 400 <= r.status_code < 500:
                self.store.mark_failed(req["id"],
                                       f"rejected: {r.text[:200]}")
                self.metrics.inc("requests_rejected")
                self._trace_done(req["id"])
                return False
            if r.status_code != 200:
                raise RuntimeError(f"inference failed: {r.text[:200]}")
            data = r.json()
            prev = req.get("node_id")
            if prev and prev != nid:
                # an earlier timed-out attempt may have left a
                # generation running on another node; it completed here
                # instead, so stop that orphan from generating for
                # nobody (best-effort — 404 if it already finished)
                prev_node = self.store.get_node(prev)
                if prev_node:
                    try:
                        self._worker_post(prev_node, "/cancel",
                                          {"request_tag":
                                           self._tag(req["id"])}, 10)
                    except Exception:
                        pass
            self.store.mark_completed(
                req["id"], data.get("result", ""), nid,
                data.get("execution_time", 0.0),
                data.get("tokens_per_s", 0.0))
            self.metrics.inc("requests_completed")
            if data.get("idempotent"):
                # a retry hit the worker's completed-result cache: the
                # generation ran exactly once despite >1 dispatch
                self.metrics.inc("requests_idempotent_replayed")
            self.metrics.observe("request_latency",
                                 time.time() - req["created_at"])
            self._trace_done(req["id"])
            self._node_success(node)
            return True
        except Exception as e:
            log.warning("request %d failed on node %d: %s", req["id"], nid, e)
            self.metrics.inc("requests_errored")
            is_timeout = isinstance(e, http.exceptions.Timeout)
            unavailable = isinstance(e, _NodeUnavailable)
            terminal = req["attempts"] + 1 >= MAX_ATTEMPTS
            if not terminal:
                # Failover retry: exclude this node for the rest of the
                # request's life, park the next attempt behind
                # exponential backoff + jitter (an unavailable node gets
                # no backoff — another node can take it immediately).
                # A pure master-side timeout — or a join 408 flagged
                # in_flight — does NOT exclude: the same node still holds
                # the in-flight generation, and the retry (pinned back to
                # it via the recorded node_id) joins it / replays its
                # cached result instead of re-generating on a peer.
                sticky = is_timeout or getattr(e, "in_flight", False)
                # Delay policy: a sticky retry waits out the backoff so
                # the generation it intends to join/replay has time to
                # finish (immediate re-joins would burn the attempt
                # budget in seconds). A plain unavailable (503/408)
                # fails over with zero delay ONLY when a different node
                # can actually take it — on a single-node cluster the
                # fallback would hand the same draining node straight
                # back, so park on the health loop's cadence instead.
                if sticky or not unavailable:
                    delay = self._backoff(req["attempts"])
                elif any(n["id"] not in excluded and n["id"] != nid
                         and not n.get("draining")
                         for n in self.store.list_nodes(active_only=True)):
                    delay = 0.0
                else:
                    delay = max(self._backoff(req["attempts"]),
                                self.health_interval * 1.5)
                self.store.requeue(
                    req["id"],
                    excluded_node_id=None if sticky else nid,
                    delay_s=delay, last_node_id=nid)
                self.metrics.inc("requests_requeued")
                self._wake.set()
            else:
                self.store.mark_failed(req["id"], str(e))
                self._trace_done(req["id"])
                if is_timeout:
                    # terminal timeout: nobody will ever claim the
                    # result — best-effort cancel so the worker stops
                    # generating for nobody. (With retries left the
                    # generation KEEPS running: its result lands in the
                    # worker's idempotency cache for the retry.)
                    try:
                        self._worker_post(node, "/cancel",
                                          {"request_tag":
                                           self._tag(req["id"])}, 10)
                    except Exception:
                        pass
            # A read timeout means the worker is slow/busy (its generate
            # lock serializes requests), not dead; a 503/408 means it is
            # managing its own load. Striking either would deactivate
            # healthy nodes. Connection-level errors do count toward the
            # breaker.
            if not (is_timeout or unavailable):
                self._node_failure(node)
            return False
        finally:
            with self._inflight_lock:
                self._inflight[nid] = max(0, self._inflight.get(nid, 1) - 1)

    # ---- circuit breaker ---------------------------------------------

    def _node_failure(self, node):
        """Record a node-fault failure: closed --N strikes--> open; a
        failed half-open probe re-opens immediately (the reference
        deactivated on ONE strike, forever — SURVEY.md §3.4)."""
        n = self.store.get_node(node["id"])
        if not n:
            return
        state = n.get("breaker_state") or "closed"
        strikes = n["consecutive_failures"] + 1
        fields = {"consecutive_failures": strikes}
        if state == "half_open" or strikes >= FAILURE_STRIKES:
            fields.update(breaker_state="open", is_active=0,
                          breaker_opened_at=time.time())
            if state != "open":
                self.metrics.inc("breaker_opened")
                log.warning("node %d breaker OPEN (%s, %d strikes)",
                            n["id"], state, strikes)
        self.store.update_node(n["id"], **fields)

    def _node_success(self, node):
        """A real request completed on the node: a half-open probe
        success closes the breaker; accumulated strikes clear."""
        n = self.store.get_node(node["id"])
        if not n:
            return
        state = n.get("breaker_state") or "closed"
        if state == "closed" and not n["consecutive_failures"]:
            return   # steady state: skip the DB write on the hot path
        if state == "half_open":
            self.metrics.inc("breaker_closed")
            log.info("node %d breaker CLOSED (half-open probe succeeded)",
                     n["id"])
        self.store.update_node(n["id"], breaker_state="closed",
                               consecutive_failures=0, is_active=1)

    # ---- background loops --------------------------------------------

    def _dispatch_loop(self):
        while not self._stop.is_set():
            req = self.store.claim_next_pending()
            if req is None:
                self._wake.wait(timeout=0.5)
                self._wake.clear()
                continue
            self._execute(req)

    def _health_loop(self):
        """Push-based monitoring with auto-reactivation — the upgrade over
        the reference's UI-driven polls (SURVEY.md §3.4). Probes run
        concurrently through _scrape_workers: a dead node used to
        serialize its full HEALTH_TIMEOUT into the sweep, so a few dead
        nodes blew the health interval and delayed detection for the
        healthy ones."""
        while not self._stop.is_set():
            self._health_sweep()
            # queue-depth gauge on the monitor's cadence, not per submit
            # (counts() is an aggregate query over the requests table)
            self.metrics.gauge("queue_pending",
                               self.store.counts().get("pending", 0))
            self._stop.wait(self.health_interval)

    def _health_sweep(self):
        """One concurrent probe pass over EVERY node (inactive included:
        an open breaker has no other road back). Probe outcomes drive
        the breaker state machine's recovery edge — open + reachable ->
        half_open; real request traffic closes it from there — and the
        worker-declared draining flag."""
        nodes = self.store.list_nodes()
        by_state = {"closed": 0, "half_open": 0, "open": 0}
        draining_n = 0
        for n, r, err in self._scrape_workers("/health", nodes=nodes):
            if self._stop.is_set():
                return
            state = n.get("breaker_state") or "closed"
            info = None
            if err is None:
                try:
                    info = r.json()
                except ValueError:
                    err = "unparseable health body"
            if info is None:
                self._node_failure(n)
                state = ((self.store.get_node(n["id"]) or n)
                         .get("breaker_state") or "closed")
            else:
                draining = 1 if info.get("status") == "draining" else 0
                fields = {"info": info, "last_heartbeat": time.time(),
                          "draining": draining}
                if state == "open":
                    # the fault cleared: schedulable again, but only as
                    # a probe until a real request succeeds
                    state = "half_open"
                    fields.update(breaker_state="half_open", is_active=1)
                    self.metrics.inc("breaker_half_opened")
                    log.info("node %d breaker HALF-OPEN "
                             "(health probe succeeded)", n["id"])
                elif state == "closed":
                    fields.update(is_active=1, consecutive_failures=0)
                self.store.update_node(n["id"], **fields)
                draining_n += draining
            by_state[state] = by_state.get(state, 0) + 1
        for s, count in by_state.items():
            self.metrics.gauge(f"breaker_{s}_nodes", count)
        self.metrics.gauge("draining_nodes", draining_n)

    # ---- lifecycle ---------------------------------------------------

    def start_background(self):
        for i in range(self._dispatcher_threads):
            t = threading.Thread(target=self._dispatch_loop, daemon=True,
                                 name=f"dispatch-{i}")
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._health_loop, daemon=True,
                             name="health")
        t.start()
        self._threads.append(t)

    def serve(self, host="0.0.0.0", port=8000, background=False):
        self.start_background()
        log.info("master on %s:%d", host, port)
        return self.service.serve(host, port, background=background)

    def stop(self):
        self._stop.set()
        self._wake.set()
        self.service.shutdown()


def _relay_json(r):
    """(status, payload) from a relayed worker response. An unparseable
    body (corrupt response, proxy error page) becomes a structured 502
    with the offending body truncated — not a raw ValueError out of
    ``r.json()`` that the HTTP layer turns into an opaque 500."""
    try:
        return r.status_code, r.json()
    except ValueError:
        return 502, {"status": "error",
                     "message": "worker returned unparseable response "
                                f"(HTTP {r.status_code}): {r.text[:200]}"}


def _strip(name: str) -> str:
    return name[4:] if name.startswith("dli_") else name


def _group_samples(samples) -> dict:
    """Regroup parsed exposition samples into the JSON shape the dashboard
    consumes: counters (``_total``), gauges, and histograms with p50/p95
    interpolated from the cumulative buckets."""
    counters, gauges = {}, {}
    buckets, sums, counts = {}, {}, {}
    for name, labels, value in samples:
        if name.endswith("_total"):
            counters[_strip(name)[:-6]] = value
        elif name.endswith("_bucket") and "le" in labels:
            buckets.setdefault(_strip(name)[:-7], []).append(
                (float(labels["le"]), value))
        elif name.endswith("_sum"):
            sums[_strip(name)[:-4]] = value
        elif name.endswith("_count"):
            counts[_strip(name)[:-6]] = value
        else:
            gauges[_strip(name)] = value
    histograms = {}
    for base, bk in buckets.items():
        histograms[base] = {
            "count": counts.get(base), "sum": sums.get(base),
            "p50": hist_quantile(bk, 0.5), "p95": hist_quantile(bk, 0.95)}
    return {"counters": counters, "gauges": gauges,
            "histograms": histograms}


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(description="TPU inference master")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--db", default="master.sqlite3")
    args = ap.parse_args(argv)
    Master(args.db).serve(args.host, args.port)


if __name__ == "__main__":
    main()
